"""Quantized inference — per-version dtype policies for the serving tier.

A registered model version can be served under a ``dtype_policy`` without
touching any layer code:

- ``"float32"`` — the model as trained (no wrapper);
- ``"bf16"``    — weights stored bfloat16 (half the weight bytes for the
  quantized copy; compute promotes per XLA rules or follows the conf's
  ``compute_dtype``);
- ``"int8"``    — weight-only symmetric int8: every float weight matrix /
  kernel is stored as an ``int8`` tensor plus a float32 per-output-channel
  scale, dequantized INSIDE the jitted forward. Weights stay int8 in device
  memory — a ~4x cut in weight bytes moved per forward, which is the
  resource serving is actually bound by (the training side proved the
  framework sits on the HBM roofline, ARCHITECTURE.md §8). 1-d params
  (biases, norm scales) stay float: they are byte-trivial and their
  precision is disproportionately load-bearing.

The wrapper holds a reference to the base model (its ``states``, conf and
forward are reused), so a live-object registration keeps the caller's
float params alive alongside the quantized copy — by design, the caller
may still be training that object. Checkpoint loads the REGISTRY owns
call ``release_base_params()`` after calibration, so a path-registered
quantized version does not pin a full float copy.

The wrapper duck-types the one method the serving stack calls —
``output(x)`` — so it drops into ``ParallelInference`` / ``ModelRegistry``
hot-swap / rollback like any other model. Calibration happens at
registration: the registry runs a sample batch through the float and the
quantized forward and records the deviation on the version's metadata
(``ModelVersion.quant_error``), optionally failing registration past a
tolerance — a bad quantization is caught at publish time, never by a user
request.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

DTYPE_POLICIES = ("float32", "bf16", "int8")

# params small enough that quantizing them saves nothing but risks accuracy
_MIN_QUANT_SIZE = 64


class QTensor:
    """One int8-quantized weight: ``q`` (int8) × ``scale`` (f32) ≈ original.

    Registered as a JAX pytree node so a params tree holding QTensors flows
    through ``jax.jit`` boundaries like any other tree; dequantization is
    traced into the forward, so the int8 buffers are what lives in device
    memory between requests.
    """

    __slots__ = ("q", "scale")

    def __init__(self, q, scale):
        self.q = q
        self.scale = scale

    def dequantize(self, dtype=jnp.float32):
        return self.q.astype(dtype) * self.scale.astype(dtype)

    @property
    def nbytes(self) -> int:
        return int(np.asarray(self.q).nbytes + np.asarray(self.scale).nbytes)


jax.tree_util.register_pytree_node(
    QTensor,
    lambda t: ((t.q, t.scale), None),
    lambda _, ch: QTensor(*ch))


def quantize_array(w, *, min_size: int = _MIN_QUANT_SIZE):
    """Symmetric int8 quantization of one array; returns a ``QTensor`` or
    the array unchanged when quantization is not worthwhile (non-float,
    tiny, or 0/1-d). Scales are per-output-channel (last axis) for >=2-d
    weights — the axis that is per-unit in every Dense [in, out] and conv
    HWIO kernel this framework produces."""
    wn = np.asarray(w)
    if (not np.issubdtype(wn.dtype, np.floating) or wn.ndim < 2
            or wn.size < min_size):
        return w
    scale = np.max(np.abs(wn), axis=tuple(range(wn.ndim - 1)),
                   keepdims=True) / 127.0
    scale = np.where(scale == 0.0, 1.0, scale).astype(np.float32)
    q = np.clip(np.round(wn / scale), -127, 127).astype(np.int8)
    return QTensor(jnp.asarray(q), jnp.asarray(scale))


def _is_leaf(x) -> bool:
    return hasattr(x, "shape") and hasattr(x, "dtype")


def quantize_pytree(params, *, min_size: int = _MIN_QUANT_SIZE):
    """Quantize every eligible leaf of a params pytree."""
    return jax.tree_util.tree_map(
        lambda w: quantize_array(w, min_size=min_size), params)


def dequantize_pytree(params, dtype=jnp.float32):
    """Inverse of ``quantize_pytree`` — meant to run INSIDE a jit."""
    return jax.tree_util.tree_map(
        lambda t: t.dequantize(dtype) if isinstance(t, QTensor) else t,
        params, is_leaf=lambda t: isinstance(t, QTensor))


def param_nbytes(params) -> int:
    """Total bytes across a params tree (QTensors count their int8+scale)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(
            params, is_leaf=lambda t: isinstance(t, QTensor)):
        if isinstance(leaf, QTensor):
            total += leaf.nbytes
        elif hasattr(leaf, "nbytes"):
            total += int(leaf.nbytes)
    return total


class QuantizedModel:
    """Serve-side wrapper holding a quantized copy of a model's params.

    Duck-types ``output`` for both ``MultiLayerNetwork`` (``output(x,
    mask=None)``) and single/multi-input ``ComputationGraph``
    (``output(*xs, masks=None)``). The base model object is untouched —
    training, checkpointing and the float version's own serving keep
    working; the wrapper only shares its (frozen) ``states`` and conf.
    """

    def __init__(self, base, policy: str = "int8", *,
                 min_size: int = _MIN_QUANT_SIZE):
        if policy not in ("int8", "bf16"):
            raise ValueError(
                f"dtype_policy {policy!r} needs no wrapper"
                if policy == "float32" else
                f"unknown dtype_policy {policy!r} (one of {DTYPE_POLICIES})")
        if getattr(base, "params", None) is None:
            raise ValueError("model has no params to quantize "
                             "(not init()ed, or not a framework model)")
        self.base = base
        self.policy = policy
        self.conf = base.conf
        self._is_graph = hasattr(base.conf, "inputs")
        if policy == "int8":
            self.qparams = quantize_pytree(base.params, min_size=min_size)
        else:  # bf16: a straight storage cast, dequantization is a no-op
            self.qparams = jax.tree_util.tree_map(
                lambda w: (jnp.asarray(w).astype(jnp.bfloat16)
                           if hasattr(w, "dtype")
                           and jnp.issubdtype(jnp.asarray(w).dtype,
                                              jnp.floating)
                           else w),
                base.params)
        self._fn = None

    # ------------------------------------------------------------- plumbing
    @property
    def param_nbytes(self) -> int:
        return param_nbytes(self.qparams)

    def release_base_params(self) -> None:
        """Drop the base model's float params (the quantized copy is what
        serves). Only for a base the CALLER no longer needs — the registry
        does this for checkpoint loads it owns; after it, the base can no
        longer train, checkpoint, or run its own float forward."""
        try:
            self.base.params = None
        except AttributeError:  # duck-typed base without settable params
            pass

    def _out_fn(self):
        if self._fn is None:
            base = self.base
            if self._is_graph:
                def out(qp, states, inputs, masks):
                    params = dequantize_pytree(qp)
                    acts, _, _, _ = base._forward_all(
                        params, states, inputs, train=False, rng=None,
                        masks=masks)
                    return [acts[n] for n in base.conf.outputs]
            else:
                def out(qp, states, x, mask):
                    params = dequantize_pytree(qp)
                    h, _, _ = base._forward_all(params, states, x,
                                                train=False, rng=None,
                                                mask=mask)
                    return h
            self._fn = jax.jit(out)
        return self._fn

    # ------------------------------------------------------------ data path
    def output(self, *xs, mask=None, masks=None):
        from deeplearning4j_tpu.nn.multilayer import _as_jnp
        dtype = self.conf.global_conf.jnp_dtype()
        if self._is_graph:
            if len(xs) == 1 and isinstance(xs[0], (list, tuple)):
                xs = tuple(xs[0])
            inputs = {n: _as_jnp(x, dtype)
                      for n, x in zip(self.conf.inputs, xs)}
            mask_d = None
            if masks is not None:
                mask_d = {n: (None if m is None else _as_jnp(m))
                          for n, m in zip(self.conf.inputs, masks)}
            outs = self._out_fn()(self.qparams, self.base.states, inputs,
                                  mask_d)
            return outs[0] if len(outs) == 1 else outs
        if len(xs) != 1:
            raise TypeError(f"output() takes one input, got {len(xs)}")
        x = _as_jnp(xs[0], dtype)
        m = None if mask is None else _as_jnp(mask)
        return self._out_fn()(self.qparams, self.base.states, x, m)


def quantize_model(model, policy: str,
                   *, min_size: int = _MIN_QUANT_SIZE):
    """Apply a dtype policy; ``"float32"``/None return the model as-is."""
    if policy in (None, "float32"):
        return model
    return QuantizedModel(model, policy, min_size=min_size)


def calibrate(base, quantized, sample_batch) -> dict:
    """Run one sample batch through both forwards; return deviation stats
    (max absolute error and error relative to the float output range)."""
    ref = np.asarray(base.output(np.asarray(sample_batch)),
                     dtype=np.float32)
    got = np.asarray(quantized.output(np.asarray(sample_batch)),
                     dtype=np.float32)
    max_abs = float(np.max(np.abs(got - ref))) if ref.size else 0.0
    span = float(np.max(np.abs(ref))) if ref.size else 0.0
    return {"max_abs_err": max_abs,
            "rel_err": max_abs / (span + 1e-12),
            "sample_rows": int(np.asarray(sample_batch).shape[0])}


def check_tolerance(stats: dict, tolerance: Optional[float]) -> None:
    if tolerance is not None and stats["rel_err"] > tolerance:
        raise ValueError(
            f"quantization error {stats['rel_err']:.4g} exceeds "
            f"tolerance {tolerance:.4g} — version rejected at registration")
