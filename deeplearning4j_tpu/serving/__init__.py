"""Production model-serving subsystem (L6 of the stack).

Turns any trained or imported model into a network service:

- ``metrics``   — now ``deeplearning4j_tpu.observe.metrics`` (the shared
  train+serve observability core; ``serving.metrics`` remains a deprecation
  re-export), surfaced here for compatibility;
- ``registry``  — versioned model registry with atomic hot-swap (built on
  ``ParallelInference.update_model``) and rollback; loads from
  ModelSerializer zips, DL4J checkpoints, Keras h5 or live objects;
- ``admission`` — bounded in-flight admission (429 + Retry-After), graceful
  drain;
- ``server``    — threaded HTTP front-end: ``/v1/models/.../predict``
  (JSON or binary codec), ``/v1/models``, ``/healthz``, ``/readyz``,
  ``/metrics``; deadlines propagate into the batching dispatcher (504,
  expired work never reaches the device), dispatcher crashes contained as
  503s + ``Retry-After``;
- ``client``    — typed client incl. a parsing ``/metrics`` scrape and an
  opt-in ``RetryPolicy`` (budgeted backoff retries, hedged requests);
- ``breaker``   — per-version circuit breakers quarantining a forward
  that keeps crashing the dispatcher, with registry fallback-chain
  failover (round 13; ARCHITECTURE.md §17);
- ``brownout``  — saturation/alert-driven degradation: priority shedding
  + fallback rerouting with hysteresis, recovering automatically.

The role of the reference ecosystem's serving deployments around
``ParallelInference.java`` + the dl4j-streaming routes, made a first-class
subsystem.
"""

from deeplearning4j_tpu.observe.metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
    instrument_http,
    parse_prometheus_text,
)
from deeplearning4j_tpu.serving.admission import (  # noqa: F401
    AdmissionController,
    AdmissionRejected,
    Draining,
)
from deeplearning4j_tpu.serving.breaker import CircuitBreaker  # noqa: F401
from deeplearning4j_tpu.serving.brownout import (  # noqa: F401
    BrownoutController,
)
from deeplearning4j_tpu.serving.registry import (  # noqa: F401
    ModelNotFound,
    ModelRegistry,
    ModelVersion,
    ServedModel,
    VersionQuarantined,
)
from deeplearning4j_tpu.serving.quantize import (  # noqa: F401
    DTYPE_POLICIES,
    QuantizedModel,
    quantize_model,
)
from deeplearning4j_tpu.serving.server import ModelServer  # noqa: F401
from deeplearning4j_tpu.serving.client import (  # noqa: F401
    ModelServingClient,
    RetryPolicy,
    ServingError,
)
