"""Nearest neighbors + clustering (TPU-native).

Parity target: reference ``deeplearning4j-nearestneighbors-parent/
nearestneighbor-core`` (VPTree.java:48, KDTree.java:37, KMeansClustering.java,
lsh/RandomProjectionLSH.java, sptree/SpTree.java, quadtree/QuadTree.java).

Design: the TPU-native fast path is :mod:`bruteforce` — batched pairwise
distances on the MXU with ``lax.top_k`` — which on accelerators beats
pointer-chasing trees for any corpus that fits in HBM. The tree structures
(VPTree, KDTree, SPTree) are kept as host-side structures for API parity,
pruning-based search on CPU, and Barnes-Hut t-SNE support.
"""

from .bruteforce import BruteForceNearestNeighbors, pairwise_distance, knn
from .cluster import Cluster, ClusterSet, Point, PointClassification
from .kdtree import HyperRect, KDTree
from .kmeans import KMeansClustering
from .lsh import RandomProjectionLSH
from .sptree import SpTree
from .quadtree import QuadTree
from .vptree import VPTree, VPTreeFillSearch

__all__ = [
    "BruteForceNearestNeighbors", "pairwise_distance", "knn",
    "Cluster", "ClusterSet", "Point", "PointClassification",
    "HyperRect", "KDTree", "KMeansClustering", "RandomProjectionLSH",
    "SpTree", "QuadTree", "VPTree", "VPTreeFillSearch",
]
