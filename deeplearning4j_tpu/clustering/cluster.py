"""Cluster model objects (parity: ``clustering/cluster/{Point,Cluster,
ClusterSet,PointClassification}.java``).

Host-side value objects; the math lives in :mod:`kmeans` on device.
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np


@dataclass
class Point:
    """A labelled vector (``cluster/Point.java``)."""
    array: np.ndarray
    id: str = field(default_factory=lambda: str(uuid.uuid4()))
    label: Optional[str] = None

    @staticmethod
    def to_points(matrix) -> List["Point"]:
        return [Point(np.asarray(row, np.float32)) for row in np.asarray(matrix)]


@dataclass
class PointClassification:
    """Result of classifying a point into a cluster set
    (``cluster/PointClassification.java``)."""
    cluster: "Cluster"
    distance_from_center: float
    new_location: bool


class Cluster:
    """A center plus its member points (``cluster/Cluster.java``)."""

    def __init__(self, center: np.ndarray, distance: str = "euclidean",
                 id: Optional[str] = None, label: Optional[str] = None):
        self.id = id or str(uuid.uuid4())
        self.label = label
        self.center = np.asarray(center, np.float32)
        self.distance = distance
        self.points: List[Point] = []

    def distance_to_center(self, point: Point) -> float:
        from .bruteforce import pairwise_distance
        import jax.numpy as jnp
        d = pairwise_distance(jnp.asarray(point.array)[None, :],
                              jnp.asarray(self.center)[None, :], self.distance)
        return float(d[0, 0])

    def add_point(self, point: Point, move_center: bool = False) -> None:
        self.points.append(point)
        if move_center:
            self.center = np.mean([p.array for p in self.points], axis=0)

    def is_empty(self) -> bool:
        return not self.points


class ClusterSet:
    """All clusters of one run (``cluster/ClusterSet.java``)."""

    def __init__(self, distance: str = "euclidean"):
        self.distance = distance
        self.clusters: List[Cluster] = []

    def add_new_cluster_with_center(self, center: np.ndarray) -> Cluster:
        c = Cluster(center, self.distance)
        self.clusters.append(c)
        return c

    @property
    def cluster_count(self) -> int:
        return len(self.clusters)

    def get_centers(self) -> np.ndarray:
        return np.stack([c.center for c in self.clusters])

    def classify_point(self, point: Point, move_center: bool = False) -> PointClassification:
        from .bruteforce import knn
        import jax.numpy as jnp
        d, i = knn(jnp.asarray(point.array)[None, :],
                   jnp.asarray(self.get_centers()), 1, self.distance)
        best = self.clusters[int(i[0, 0])]
        new_location = point.id not in {p.id for p in best.points}
        if new_location:
            for c in self.clusters:
                c.points = [p for p in c.points if p.id != point.id]
            best.add_point(point, move_center)
        return PointClassification(best, float(d[0, 0]), new_location)

    def classify_points(self, points: List[Point], move_centers: bool = False) -> None:
        for p in points:
            self.classify_point(p, move_centers)

    def remove_empty_clusters(self) -> List[Cluster]:
        empty = [c for c in self.clusters if c.is_empty()]
        self.clusters = [c for c in self.clusters if not c.is_empty()]
        return empty
