"""Locality-sensitive hashing (parity: ``clustering/lsh/LSH.java`` +
``RandomProjectionLSH.java``).

Sign-of-random-projection hashing; the hash of the whole corpus is one
``(N, D) @ (D, hash_length)`` matmul on device, then bucket lookup +
exact re-ranking on the candidates.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from .bruteforce import knn, pairwise_distance


class RandomProjectionLSH:
    """``RandomProjectionLSH(hashLength, numTables, intDimensions, radius)``.

    Multi-table sign-LSH: each table hashes with its own random projection;
    search unions the query's buckets across tables and re-ranks exactly.
    """

    def __init__(self, hash_length: int = 16, num_tables: int = 4,
                 in_dimensions: int = None, radius: float = 1.0, seed: int = 0):
        self.hash_length = int(hash_length)
        self.num_tables = int(num_tables)
        self.in_dimensions = in_dimensions
        self.radius = float(radius)
        self.seed = seed
        self.data: Optional[np.ndarray] = None
        self._proj: Optional[np.ndarray] = None      # (T, D, H)
        self._tables: List[Dict[int, List[int]]] = []

    def _hash_bits(self, x: np.ndarray) -> np.ndarray:
        """(N, D) -> (T, N) packed integer hashes (one matmul per table)."""
        codes = []
        for t in range(self.num_tables):
            bits = np.asarray(jnp.asarray(x) @ jnp.asarray(self._proj[t])) > 0
            weights = (1 << np.arange(self.hash_length)).astype(np.int64)
            codes.append(bits.astype(np.int64) @ weights)
        return np.stack(codes)

    def make_index(self, data) -> None:
        """Hash + bucket the corpus (``LSH.makeIndex``)."""
        self.data = np.asarray(data, np.float32)
        n, d = self.data.shape
        self.in_dimensions = d
        rng = np.random.default_rng(self.seed)
        self._proj = rng.standard_normal(
            (self.num_tables, d, self.hash_length)).astype(np.float32)
        codes = self._hash_bits(self.data)           # (T, N)
        self._tables = []
        for t in range(self.num_tables):
            buckets: Dict[int, List[int]] = {}
            for i, c in enumerate(codes[t]):
                buckets.setdefault(int(c), []).append(i)
            self._tables.append(buckets)

    def bucket(self, query) -> np.ndarray:
        """Candidate indices sharing a bucket with the query in any table
        (``LSH.bucket``)."""
        q = np.asarray(query, np.float32).reshape(1, -1)
        codes = self._hash_bits(q)[:, 0]
        cand: List[int] = []
        for t in range(self.num_tables):
            cand.extend(self._tables[t].get(int(codes[t]), []))
        return np.unique(np.array(cand, np.int64))

    def search(self, query, max_range: Optional[float] = None
               ) -> Tuple[np.ndarray, np.ndarray]:
        """Exact-ranked candidates within ``max_range`` (``LSH.search``).
        Returns (distances, indices) sorted ascending."""
        max_range = self.radius if max_range is None else float(max_range)
        cand = self.bucket(query)
        if cand.size == 0:
            return np.empty(0, np.float32), np.empty(0, np.int64)
        q = jnp.asarray(np.asarray(query, np.float32).reshape(1, -1))
        d = np.asarray(pairwise_distance(q, jnp.asarray(self.data[cand])))[0]
        keep = d <= max_range
        order = np.argsort(d[keep])
        return d[keep][order].astype(np.float32), cand[keep][order]

    def get_all_nearest_neighbors(self, query, k: int
                                  ) -> Tuple[np.ndarray, np.ndarray]:
        """k-NN among bucket candidates, exact-fallback when the buckets
        under-fill (mirrors VPTreeFillSearch's guarantee)."""
        cand = self.bucket(query)
        q = jnp.asarray(np.asarray(query, np.float32).reshape(1, -1))
        if cand.size < k:
            d, i = knn(q, jnp.asarray(self.data), min(k, len(self.data)))
            return np.asarray(d)[0], np.asarray(i)[0]
        d, i = knn(q, jnp.asarray(self.data[cand]), min(k, cand.size))
        return np.asarray(d)[0], cand[np.asarray(i)[0]]
