"""KD-tree (parity: ``clustering/kdtree/KDTree.java:37`` +
``HyperRect.java``): insert / delete / nn / knn / range queries.

Host-side structure — incremental insert/delete has no jit analog and the
batch path is :mod:`bruteforce`.
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np


class HyperRect:
    """Axis-aligned box with min-distance and contains tests
    (``HyperRect.java``)."""

    def __init__(self, lower: np.ndarray, upper: np.ndarray):
        self.lower = np.asarray(lower, np.float64)
        self.upper = np.asarray(upper, np.float64)

    @classmethod
    def infinite(cls, dims: int) -> "HyperRect":
        return cls(np.full(dims, -np.inf), np.full(dims, np.inf))

    def contains(self, point: np.ndarray) -> bool:
        return bool(np.all(point >= self.lower) and np.all(point <= self.upper))

    def min_distance(self, point: np.ndarray) -> float:
        clipped = np.clip(point, self.lower, self.upper)
        return float(np.sqrt(np.sum((point - clipped) ** 2)))

    def get_lower_half(self, dim: int, split: float) -> "HyperRect":
        u = self.upper.copy(); u[dim] = split
        return HyperRect(self.lower, u)

    def get_upper_half(self, dim: int, split: float) -> "HyperRect":
        l = self.lower.copy(); l[dim] = split
        return HyperRect(l, self.upper)


@dataclass
class _KDNode:
    point: np.ndarray
    left: Optional["_KDNode"] = None
    right: Optional["_KDNode"] = None


class KDTree:
    """Incremental KD-tree over ``dims`` dimensions (``KDTree.java:37``)."""

    def __init__(self, dims: int):
        self.dims = int(dims)
        self.root: Optional[_KDNode] = None
        self.size = 0

    def insert(self, point) -> None:
        point = np.asarray(point, np.float64).reshape(self.dims)
        self.size += 1
        if self.root is None:
            self.root = _KDNode(point)
            return
        node, depth = self.root, 0
        while True:
            dim = depth % self.dims
            if point[dim] < node.point[dim]:
                if node.left is None:
                    node.left = _KDNode(point); return
                node = node.left
            else:
                if node.right is None:
                    node.right = _KDNode(point); return
                node = node.right
            depth += 1

    def delete(self, point) -> bool:
        """Remove one node equal to ``point`` (rebuilds the subtree below
        it — simpler than the classic successor dance, same result)."""
        point = np.asarray(point, np.float64).reshape(self.dims)
        collected: List[np.ndarray] = []
        found = [False]

        def collect(n: Optional[_KDNode]):
            if n is None:
                return
            if not found[0] and np.array_equal(n.point, point):
                found[0] = True
            else:
                collected.append(n.point)
            collect(n.left); collect(n.right)

        collect(self.root)
        if not found[0]:
            return False
        self.root, self.size = None, 0
        for p in collected:
            self.insert(p)
        return True

    def nn(self, point) -> Tuple[float, Optional[np.ndarray]]:
        d, pts = self.knn(point, 1)
        return (d[0], pts[0]) if pts else (np.inf, None)

    def knn(self, point, k: int) -> Tuple[List[float], List[np.ndarray]]:
        point = np.asarray(point, np.float64).reshape(self.dims)
        heap: List[Tuple[float, int, np.ndarray]] = []
        counter = [0]

        def visit(node: Optional[_KDNode], depth: int):
            if node is None:
                return
            d = float(np.sqrt(np.sum((node.point - point) ** 2)))
            if len(heap) < k:
                heapq.heappush(heap, (-d, counter[0], node.point)); counter[0] += 1
            elif d < -heap[0][0]:
                heapq.heapreplace(heap, (-d, counter[0], node.point)); counter[0] += 1
            dim = depth % self.dims
            diff = point[dim] - node.point[dim]
            near, far = (node.left, node.right) if diff < 0 else (node.right, node.left)
            visit(near, depth + 1)
            if len(heap) < k or abs(diff) < -heap[0][0]:
                visit(far, depth + 1)

        visit(self.root, 0)
        pairs = sorted(((-nd, p) for nd, _, p in heap), key=lambda t: t[0])
        return [p[0] for p in pairs], [p[1] for p in pairs]

    def range(self, lower, upper) -> List[np.ndarray]:
        """All points inside the box (``KDTree.java`` range search)."""
        rect = HyperRect(lower, upper)
        out: List[np.ndarray] = []

        def visit(node: Optional[_KDNode], depth: int):
            if node is None:
                return
            if rect.contains(node.point):
                out.append(node.point)
            dim = depth % self.dims
            if node.point[dim] >= rect.lower[dim]:
                visit(node.left, depth + 1)
            if node.point[dim] <= rect.upper[dim]:
                visit(node.right, depth + 1)

        visit(self.root, 0)
        return out
