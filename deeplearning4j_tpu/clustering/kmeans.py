"""KMeans clustering, Lloyd iterations jitted on device.

Parity: ``clustering/kmeans/KMeansClustering.java`` +
``clustering/algorithm/BaseClusteringAlgorithm.java`` (iteration loop with
ClusteringStrategy / termination conditions). The reference distributes
point-to-center assignment over JVM threads (``util/MultiThreadUtils.java``);
here one Lloyd step is a single jitted assignment matmul + segment-sum, so
the whole sweep runs on the MXU.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .bruteforce import pairwise_distance
from .cluster import ClusterSet, Point


@partial(jax.jit, static_argnames=("k", "distance"))
def _lloyd_step(points: jax.Array, centers: jax.Array, k: int,
                distance: str):
    """One assignment + recenter step. Returns (new_centers, assignment,
    total within-cluster distance)."""
    d = pairwise_distance(points, centers, distance)        # (N, k)
    assign = jnp.argmin(d, axis=1)                          # (N,)
    cost = jnp.sum(jnp.min(d, axis=1))
    one_hot = jax.nn.one_hot(assign, k, dtype=points.dtype)  # (N, k)
    counts = one_hot.sum(axis=0)                            # (k,)
    sums = one_hot.T @ points                               # (k, D) — MXU
    new_centers = sums / jnp.maximum(counts, 1.0)[:, None]
    # keep empty clusters where they were (reference re-seeds via strategy)
    new_centers = jnp.where((counts > 0)[:, None], new_centers, centers)
    return new_centers, assign, cost


class KMeansClustering:
    """``KMeansClustering.setup(k, maxIter, distance)`` parity surface.

    ``setup(k, max_iter, distance)`` → instance; ``apply_to(points)`` →
    :class:`ClusterSet`. Convergence: relative cost improvement below
    ``min_distribution_variation`` (reference ``VarianceVariationCondition``)
    or ``max_iter`` sweeps (``FixedIterationCountCondition``).
    """

    def __init__(self, k: int, max_iter: int = 100,
                 distance: str = "euclidean",
                 min_distribution_variation: float = 1e-4,
                 seed: int = 0):
        self.k = int(k)
        self.max_iter = max(1, int(max_iter))  # one Lloyd sweep minimum:
        # fit() must always produce assignments
        self.distance = distance
        self.min_distribution_variation = float(min_distribution_variation)
        self.seed = seed
        self.iteration_costs: List[float] = []
        self._assign = None

    @property
    def assignments(self) -> np.ndarray:
        """Per-point cluster ids from the last ``fit`` sweep."""
        if self._assign is None:
            raise ValueError("call fit() before reading assignments")
        return self._assign

    @classmethod
    def setup(cls, cluster_count: int, max_iteration_count: int = 100,
              distance: str = "euclidean", **kw) -> "KMeansClustering":
        return cls(cluster_count, max_iteration_count, distance, **kw)

    # -- core ---------------------------------------------------------------
    def fit(self, matrix) -> np.ndarray:
        """Run Lloyd's algorithm; returns the (k, D) centers."""
        pts = jnp.asarray(matrix, jnp.float32)
        n = pts.shape[0]
        if n < self.k:
            raise ValueError(f"need >= k={self.k} points, got {n}")
        # k-means++ seeding: random first center, then sample proportional
        # to SQUARED distance in the chosen metric (sqeuclidean is already
        # squared). 'dot' is not a metric (negative = similar) so it seeds
        # by uniform draws over not-yet-chosen indices without computing
        # distances at all (distinct indices; coordinate duplicates remain
        # possible only when the data itself contains duplicates).
        rng = np.random.default_rng(self.seed)
        chosen = [int(rng.integers(0, n))]
        d_min = None
        for _ in range(1, self.k):
            w = None
            if self.distance != "dot":
                d = np.asarray(pairwise_distance(
                    pts, pts[chosen[-1]][None, :], self.distance))[:, 0]
                d_min = d if d_min is None else np.minimum(d_min, d)
                w = (np.maximum(d_min, 0.0) if self.distance == "sqeuclidean"
                     else np.maximum(d_min, 0.0) ** 2)
            if w is not None and w.sum() > 0:
                chosen.append(int(rng.choice(n, p=w / w.sum())))
            else:  # 'dot', or a duplicates-only remainder
                # free is never empty: len(chosen) < k <= n
                free = np.setdiff1d(np.arange(n), chosen)
                chosen.append(int(rng.choice(free)))
        c = jnp.asarray(np.stack([np.asarray(pts[i]) for i in chosen]))

        self.iteration_costs = []
        prev_cost = None
        for _ in range(self.max_iter):
            c, assign, cost = _lloyd_step(pts, c, self.k, self.distance)
            cost = float(cost)
            self.iteration_costs.append(cost)
            if prev_cost is not None:
                denom = max(abs(prev_cost), 1e-12)
                if abs(prev_cost - cost) / denom < self.min_distribution_variation:
                    break
            prev_cost = cost
        self._assign = np.asarray(assign)
        return np.asarray(c)

    def apply_to(self, points: List[Point]) -> ClusterSet:
        """Reference entry point: points → ClusterSet with members placed."""
        matrix = np.stack([p.array for p in points])
        centers = self.fit(matrix)
        cs = ClusterSet(self.distance)
        clusters = [cs.add_new_cluster_with_center(c) for c in centers]
        for p, a in zip(points, self._assign):
            clusters[int(a)].add_point(p)
        cs.remove_empty_clusters()
        return cs

    applyTo = apply_to  # reference-style alias
