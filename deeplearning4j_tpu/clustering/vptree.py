"""Vantage-point tree (parity: ``clustering/vptree/VPTree.java:48``,
``VPTreeFillSearch.java``).

Host-side metric tree with tau pruning for single/low-volume queries on CPU.
For batched queries prefer :class:`~.bruteforce.BruteForceNearestNeighbors`
(one MXU matmul replaces the whole traversal). The two are equivalence-tested
against each other, mirroring the reference's cuDNN-vs-builtin validation
pattern.
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import numpy as np


def _dist(a: np.ndarray, b: np.ndarray, distance: str) -> np.ndarray:
    """Distance from one point ``a`` (D,) to rows of ``b`` (N, D) -> (N,)."""
    b = np.atleast_2d(b)
    if distance in ("euclidean", "sqeuclidean"):
        d = np.sum((b - a) ** 2, axis=-1)
        return d if distance == "sqeuclidean" else np.sqrt(d)
    if distance == "cosine":
        an = a / (np.linalg.norm(a) + 1e-12)
        bn = b / (np.linalg.norm(b, axis=-1, keepdims=True) + 1e-12)
        return 1.0 - bn @ an
    if distance == "manhattan":
        return np.sum(np.abs(b - a), axis=-1)
    if distance == "chebyshev":
        return np.max(np.abs(b - a), axis=-1)
    if distance == "dot":
        return -(b @ a)
    raise ValueError(f"unsupported distance {distance!r}")


@dataclass
class _Node:
    index: int
    threshold: float = 0.0
    left: Optional["_Node"] = None
    right: Optional["_Node"] = None


class VPTree:
    """``VPTree(items, distance)`` then ``search(target, k)``.

    Build: recursive random vantage point + median-of-distances split
    (the reference's parallel build becomes a vectorized distance sweep).
    """

    def __init__(self, items, distance: str = "euclidean",
                 labels: Optional[List[str]] = None, seed: int = 0):
        self.items = np.asarray(items, np.float32)
        self.distance = distance
        self.labels = labels
        self._rng = np.random.default_rng(seed)
        idx = list(range(len(self.items)))
        self.root = self._build(idx)

    def _build(self, idx: List[int]) -> Optional[_Node]:
        if not idx:
            return None
        pos = int(self._rng.integers(0, len(idx)))
        idx[0], idx[pos] = idx[pos], idx[0]
        vp = idx[0]
        rest = idx[1:]
        node = _Node(vp)
        if rest:
            d = _dist(self.items[vp], self.items[rest], self.distance)
            median = float(np.median(d))
            node.threshold = median
            inside = [r for r, dd in zip(rest, d) if dd < median]
            outside = [r for r, dd in zip(rest, d) if dd >= median]
            if not inside or not outside:  # degenerate (duplicates): split evenly
                mid = len(rest) // 2
                inside, outside = rest[:mid], rest[mid:]
            node.left = self._build(inside)
            node.right = self._build(outside)
        return node

    def search(self, target, k: int) -> Tuple[np.ndarray, np.ndarray]:
        """k nearest items to ``target``: ``(distances, indices)`` sorted
        ascending (VPTree.java ``search(INDArray, int, List, List)``)."""
        target = np.asarray(target, np.float32)
        k = min(int(k), len(self.items))
        heap: List[Tuple[float, int]] = []  # max-heap via negated distance
        tau = [np.inf]

        def visit(node: Optional[_Node]):
            if node is None:
                return
            d = float(_dist(target, self.items[node.index][None, :],
                            self.distance)[0])
            if len(heap) < k:
                heapq.heappush(heap, (-d, node.index))
                if len(heap) == k:
                    tau[0] = -heap[0][0]
            elif d < tau[0]:
                heapq.heapreplace(heap, (-d, node.index))
                tau[0] = -heap[0][0]
            if node.left is None and node.right is None:
                return
            if d < node.threshold:
                visit(node.left)
                if d + tau[0] >= node.threshold:
                    visit(node.right)
            else:
                visit(node.right)
                if d - tau[0] <= node.threshold:
                    visit(node.left)

        visit(self.root)
        pairs = sorted((-nd, i) for nd, i in heap)
        return (np.array([p[0] for p in pairs], np.float32),
                np.array([p[1] for p in pairs], np.int64))


class VPTreeFillSearch:
    """Search that always returns exactly k results
    (``VPTreeFillSearch.java`` — falls back to a full scan when the tree
    search under-fills)."""

    def __init__(self, tree: VPTree, k: int, target):
        self.tree = tree
        self.k = int(k)
        self.target = np.asarray(target, np.float32)
        self.results: Optional[np.ndarray] = None
        self.distances: Optional[np.ndarray] = None

    def run(self) -> None:
        d, i = self.tree.search(self.target, self.k)
        if len(i) < self.k:  # fill from full scan
            full = _dist(self.target, self.tree.items, self.tree.distance)
            order = np.argsort(full)[: self.k]
            d, i = full[order].astype(np.float32), order.astype(np.int64)
        self.distances, self.results = d, i
