"""2-D quadtree (parity: ``clustering/quadtree/QuadTree.java`` +
``Cell.java``) — the 2-D special case the reference keeps alongside SpTree;
here a thin wrapper that fixes D=2 and preserves the reference surface
(``getIndex``/north-west style subdivision collapses to SpTree's child
indexing)."""

from __future__ import annotations

import numpy as np

from .sptree import SpTree


class QuadTree(SpTree):
    """Quadtree over (N, 2) points; same force interface as SpTree."""

    def __init__(self, data: np.ndarray):
        data = np.asarray(data, np.float64)
        if data.shape[1] != 2:
            raise ValueError("QuadTree requires 2-D points; use SpTree")
        super().__init__(data)

    @property
    def north_west(self):
        return self.children[0] if self.children else None

    @property
    def north_east(self):
        return self.children[1] if self.children else None

    @property
    def south_west(self):
        return self.children[2] if self.children else None

    @property
    def south_east(self):
        return self.children[3] if self.children else None
