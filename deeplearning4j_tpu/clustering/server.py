"""Nearest-neighbors REST server + client.

Parity with the reference's serving stack
(`deeplearning4j-nearestneighbor-server/.../NearestNeighborsServer.java:42`
— Play HTTP routes ``POST /knn`` (query by index into the served corpus) and
``POST /knnnew`` (query by raw vector), JCommander CLI flags — and the
``-client`` / ``-model`` modules' request/response records), built on stdlib
``http.server`` with JSON bodies. Queries run on the MXU brute-force k-NN
path by default (one device matmul beats host VP-tree traversal for the
corpus sizes a REST hop implies), with VPTree as the host fallback.
"""

from __future__ import annotations

import argparse
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional
from urllib.parse import urlparse

import numpy as np


class NearestNeighbor:
    """One result record (nearestneighbor-model's NearestNeighbor)."""

    def __init__(self, index: int, distance: float):
        self.index = int(index)
        self.distance = float(distance)

    def to_dict(self):
        return {"index": self.index, "distance": self.distance}


class NearestNeighborsServer:
    """Serves k-NN queries over a fixed corpus of points.

    Endpoints:
      - ``POST /knn``     body ``{"ndarray": <index>, "k": n}`` — neighbors of
        an existing corpus row (reference ``/knn`` semantics)
      - ``POST /knnnew``  body ``{"ndarray": [floats], "k": n}`` — neighbors
        of a new point
      - ``GET  /labels``  the optional label list
    """

    def __init__(self, points, labels: Optional[List[str]] = None,
                 similarity_function: str = "euclidean", invert: bool = False,
                 port: int = 9200, use_device: bool = True, metrics=None):
        self.points = np.asarray(points, np.float32)
        self.labels = labels
        self.similarity_function = similarity_function
        self.invert = invert
        self.port = port
        self._httpd = None
        self._thread = None
        # optional shared observability core (observe.metrics registry)
        self._observe = None
        if metrics is not None:
            from deeplearning4j_tpu.observe.metrics import instrument_http
            self._observe = instrument_http(metrics, "knn")
        if use_device:
            from deeplearning4j_tpu.clustering.bruteforce import (
                BruteForceNearestNeighbors)
            self._index = BruteForceNearestNeighbors(
                self.points, distance=similarity_function)
            self._vptree = None
        else:
            from deeplearning4j_tpu.clustering.vptree import VPTree
            self._vptree = VPTree(self.points, distance=similarity_function)
            self._index = None

    # -- query -----------------------------------------------------------
    def query(self, point: np.ndarray, k: int,
              exclude_index: Optional[int] = None) -> List[NearestNeighbor]:
        k_eff = min(k + (1 if exclude_index is not None else 0),
                    len(self.points))
        if self.invert:
            # inverted metric (farthest-first, the reference's --invert):
            # one full distance row, reversed order
            from deeplearning4j_tpu.clustering.bruteforce import pairwise_distance
            import jax.numpy as jnp
            d = np.asarray(pairwise_distance(
                jnp.asarray(point[None, :]), jnp.asarray(self.points),
                self.similarity_function))[0]
            idx = np.argsort(-d)[:k_eff]
            dist = d[idx]
        elif self._index is not None:
            dist, idx = self._index.search(point[None, :], k_eff)
            idx, dist = np.asarray(idx[0]), np.asarray(dist[0])
        else:
            dist, idx = self._vptree.search(point, k_eff)
        out = []
        for i, d in zip(idx, dist):
            if exclude_index is not None and int(i) == exclude_index:
                continue
            out.append(NearestNeighbor(int(i), float(d)))
        return out[:k]

    # -- http ------------------------------------------------------------
    def start(self) -> int:
        server = self

        from deeplearning4j_tpu.observe.metrics import HTTPObserverMixin

        class Handler(HTTPObserverMixin, BaseHTTPRequestHandler):
            observe = server._observe

            def log_message(self, *a):
                pass

            def _json(self, obj, code=200):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if urlparse(self.path).path == "/labels":
                    self._json({"labels": server.labels or []})
                else:
                    self._json({"error": "not found"}, 404)

            def do_POST(self):
                path = urlparse(self.path).path
                n = int(self.headers.get("Content-Length", "0"))
                try:
                    req = json.loads(self.rfile.read(n).decode())
                    k = int(req.get("k", 1))
                    if path == "/knn":
                        i = int(req["ndarray"])
                        if not 0 <= i < len(server.points):
                            self._json({"error": f"index {i} out of range"}, 400)
                            return
                        res = server.query(server.points[i], k, exclude_index=i)
                    elif path == "/knnnew":
                        point = np.asarray(req["ndarray"], np.float32)
                        if point.shape != server.points.shape[1:]:
                            self._json({"error":
                                        f"expected dim {server.points.shape[1]}"},
                                       400)
                            return
                        res = server.query(point, k)
                    else:
                        self._json({"error": "not found"}, 404)
                        return
                    payload = {"results": [r.to_dict() for r in res]}
                    if server.labels:
                        payload["labels"] = [
                            server.labels[r.index] for r in res
                            if r.index < len(server.labels)]
                    self._json(payload)
                except (KeyError, ValueError, json.JSONDecodeError) as e:
                    self._json({"error": str(e)}, 400)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self.port

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None

    # -- CLI (JCommander-flag parity) -------------------------------------
    @staticmethod
    def main(argv: Optional[List[str]] = None) -> "NearestNeighborsServer":
        ap = argparse.ArgumentParser("nearest-neighbors-server")
        ap.add_argument("--ndarrayPath", required=True,
                        help=".npy corpus of shape [n, d]")
        ap.add_argument("--labelsPath", default=None,
                        help="optional text file, one label per row")
        ap.add_argument("--nearestNeighborsPort", type=int, default=9200)
        ap.add_argument("--similarityFunction", default="euclidean")
        ap.add_argument("--invert", action="store_true")
        args = ap.parse_args(argv)
        points = np.load(args.ndarrayPath)
        labels = None
        if args.labelsPath:
            with open(args.labelsPath) as f:
                labels = [l.strip() for l in f]
        server = NearestNeighborsServer(
            points, labels, args.similarityFunction, args.invert,
            args.nearestNeighborsPort)
        server.start()
        return server


class NearestNeighborsClient:
    """JSON client (`nearestneighbor-client` parity)."""

    def __init__(self, url: str, timeout: float = 10.0):
        self.url = url.rstrip("/")
        self.timeout = timeout

    def _post(self, path: str, payload: dict) -> dict:
        import urllib.request
        req = urllib.request.Request(
            self.url + path, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return json.loads(resp.read().decode())

    def knn(self, index: int, k: int) -> dict:
        return self._post("/knn", {"ndarray": int(index), "k": k})

    def knn_new(self, point, k: int) -> dict:
        return self._post("/knnnew",
                          {"ndarray": np.asarray(point).tolist(), "k": k})
