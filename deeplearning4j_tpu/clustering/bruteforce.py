"""On-device brute-force nearest neighbors — the TPU-native fast path.

The reference reaches k-NN through tree structures (``VPTree.java:48``),
because on CPU pruning beats scanning. On TPU the opposite holds: a corpus
of N points in HBM and a batch of Q queries turn into one ``(Q, D) @ (D, N)``
matmul on the MXU plus ``lax.top_k`` — no pointer chasing, no recursion,
fully jittable and shardable over a mesh axis.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

#: distance names accepted everywhere in this package (VPTree.java supports
#: "euclidean" by default plus similarity functions via ND4J reduce ops)
DISTANCES = ("euclidean", "sqeuclidean", "manhattan", "chebyshev", "cosine",
             "dot", "hamming", "jaccard")


def pairwise_distance(queries: jax.Array, corpus: jax.Array,
                      distance: str = "euclidean") -> jax.Array:
    """``(Q, D) x (N, D) -> (Q, N)`` distance matrix.

    Euclidean/cosine/dot route through a single matmul so XLA places the
    work on the MXU; elementwise metrics broadcast (HBM-bound but fused).
    """
    # full-f32 MXU passes: the |q|^2 - 2qc + |c|^2 trick cancels
    # catastrophically near zero under the default bf16 matmul precision
    hi = jax.lax.Precision.HIGHEST
    if distance in ("euclidean", "sqeuclidean"):
        # |q - c|^2 = |q|^2 - 2 q.c + |c|^2 ; the q.c term is the matmul.
        qq = jnp.sum(queries * queries, axis=-1, keepdims=True)
        cc = jnp.sum(corpus * corpus, axis=-1)
        d2 = qq - 2.0 * jnp.matmul(queries, corpus.T, precision=hi) + cc[None, :]
        d2 = jnp.maximum(d2, 0.0)
        return d2 if distance == "sqeuclidean" else jnp.sqrt(d2)
    if distance == "cosine":
        qn = queries / (jnp.linalg.norm(queries, axis=-1, keepdims=True) + 1e-12)
        cn = corpus / (jnp.linalg.norm(corpus, axis=-1, keepdims=True) + 1e-12)
        return 1.0 - jnp.matmul(qn, cn.T, precision=hi)
    if distance == "dot":
        return -jnp.matmul(queries, corpus.T, precision=hi)
    if distance == "manhattan":
        return jnp.sum(jnp.abs(queries[:, None, :] - corpus[None, :, :]), axis=-1)
    if distance == "chebyshev":
        return jnp.max(jnp.abs(queries[:, None, :] - corpus[None, :, :]), axis=-1)
    if distance == "hamming":
        return jnp.mean((queries[:, None, :] != corpus[None, :, :]).astype(jnp.float32), axis=-1)
    if distance == "jaccard":
        mn = jnp.minimum(queries[:, None, :], corpus[None, :, :]).sum(-1)
        mx = jnp.maximum(queries[:, None, :], corpus[None, :, :]).sum(-1)
        return 1.0 - mn / (mx + 1e-12)
    raise ValueError(f"unknown distance {distance!r}; expected one of {DISTANCES}")


@partial(jax.jit, static_argnames=("k", "distance"))
def knn(queries: jax.Array, corpus: jax.Array, k: int,
        distance: str = "euclidean") -> Tuple[jax.Array, jax.Array]:
    """Top-k nearest: returns ``(distances, indices)`` each ``(Q, k)``."""
    d = pairwise_distance(queries, corpus, distance)
    neg, idx = jax.lax.top_k(-d, k)
    return -neg, idx


class BruteForceNearestNeighbors:
    """Device-resident k-NN index (role of ``VPTree`` for batch queries).

    Holds the corpus on device once; every query batch is one jitted
    matmul + top_k. ``query_chunk`` bounds the (Q, N) scratch so huge
    corpora stay within HBM.
    """

    def __init__(self, points, distance: str = "euclidean",
                 query_chunk: int = 4096):
        self.points = jnp.asarray(points, jnp.float32)
        self.distance = distance
        self.query_chunk = int(query_chunk)

    def __len__(self) -> int:
        return int(self.points.shape[0])

    def search(self, queries, k: int) -> Tuple[np.ndarray, np.ndarray]:
        q = jnp.atleast_2d(jnp.asarray(queries, jnp.float32))
        k = min(int(k), len(self))
        outs_d, outs_i = [], []
        for s in range(0, q.shape[0], self.query_chunk):
            d, i = knn(q[s:s + self.query_chunk], self.points, k, self.distance)
            outs_d.append(np.asarray(d))
            outs_i.append(np.asarray(i))
        return np.concatenate(outs_d), np.concatenate(outs_i)

    def search_excluding_self(self, k: int) -> Tuple[np.ndarray, np.ndarray]:
        """k-NN of every corpus point against the corpus, self excluded
        (what Barnes-Hut t-SNE and VPTreeFillSearch need)."""
        d, i = self.search(self.points, k + 1)
        keep_d = np.empty((d.shape[0], k), d.dtype)
        keep_i = np.empty((d.shape[0], k), i.dtype)
        for r in range(d.shape[0]):
            mask = i[r] != r
            keep_i[r] = i[r][mask][:k]
            keep_d[r] = d[r][mask][:k]
        return keep_d, keep_i
