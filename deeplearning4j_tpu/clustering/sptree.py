"""Space-partitioning tree for Barnes-Hut (parity:
``clustering/sptree/SpTree.java`` + ``Cell.java``).

d-dimensional generalization of the quadtree: each node stores a center of
mass and point count; ``compute_non_edge_forces`` applies the Barnes-Hut
theta criterion. Host-side (the tree is rebuilt every t-SNE iteration from
the current embedding — cheap at the N where Barnes-Hut beats the exact
on-device path).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

_NODE_CAPACITY = 1  # reference SpTree stores one point per leaf


class SpTreeCell:
    """Axis-aligned cell (``sptree/Cell.java``)."""

    def __init__(self, corner: np.ndarray, width: np.ndarray):
        self.corner = corner  # center of the cell
        self.width = width    # half-widths per dimension

    def contains(self, point: np.ndarray) -> bool:
        return bool(np.all(np.abs(point - self.corner) <= self.width + 1e-12))


class SpTree:
    """Barnes-Hut tree over an (N, D) embedding (``SpTree.java``)."""

    def __init__(self, data: np.ndarray, corner: Optional[np.ndarray] = None,
                 width: Optional[np.ndarray] = None):
        data = np.asarray(data, np.float64)
        self.data = data
        self.dims = data.shape[1]
        if corner is None:
            mins, maxs = data.min(0), data.max(0)
            center = (mins + maxs) / 2.0
            half = (maxs - mins) / 2.0 + 1e-5
            self.cell = SpTreeCell(center, half)
        else:
            self.cell = SpTreeCell(corner, width)
        self.center_of_mass = np.zeros(self.dims)
        self.cum_size = 0
        self.point_index: int = -1
        self.is_leaf = True
        self.children: List[Optional[SpTree]] = []
        if corner is None:  # root: insert everything
            for i in range(data.shape[0]):
                self.insert(i)

    # -- construction -------------------------------------------------------
    def _subdivide(self) -> None:
        n_children = 1 << self.dims
        half = self.cell.width / 2.0
        self.children = []
        for c in range(n_children):
            offset = np.array([(1 if (c >> d) & 1 else -1) for d in range(self.dims)])
            child = SpTree.__new__(SpTree)
            child.data = self.data
            child.dims = self.dims
            child.cell = SpTreeCell(self.cell.corner + offset * half, half)
            child.center_of_mass = np.zeros(self.dims)
            child.cum_size = 0
            child.point_index = -1
            child.is_leaf = True
            child.children = []
            self.children.append(child)
        self.is_leaf = False

    def insert(self, index: int) -> bool:
        point = self.data[index]
        if not self.cell.contains(point):
            return False
        self.cum_size += 1
        self.center_of_mass += (point - self.center_of_mass) / self.cum_size
        if self.is_leaf and self.point_index < 0:
            self.point_index = index
            return True
        if self.is_leaf:
            # duplicate point: just accumulate mass, don't split forever
            if np.allclose(self.data[self.point_index], point):
                return True
            old = self.point_index
            self.point_index = -1
            self._subdivide()
            for child in self.children:
                if child.insert(old):
                    break
            for child in self.children:
                if child.insert(index):
                    return True
            return False
        for child in self.children:
            if child.insert(index):
                return True
        return False

    # -- Barnes-Hut forces --------------------------------------------------
    def compute_non_edge_forces(self, index: int, theta: float,
                                neg_f: np.ndarray) -> float:
        """Accumulate repulsive force on ``data[index]`` into ``neg_f``;
        returns the partial sum of Q (``SpTree.computeNonEdgeForces``)."""
        if self.cum_size == 0 or (self.is_leaf and self.point_index == index
                                  and self.cum_size == 1):
            return 0.0
        point = self.data[index]
        diff = point - self.center_of_mass
        d2 = float(diff @ diff)
        max_width = float(np.max(self.cell.width * 2.0))
        if self.is_leaf or max_width * max_width < theta * theta * d2:
            mult = self.cum_size
            if self.is_leaf and self.point_index == index:
                mult -= 1
                if mult <= 0:
                    return 0.0
            q = 1.0 / (1.0 + d2)
            sum_q = mult * q
            neg_f += mult * q * q * diff
            return sum_q
        return sum(child.compute_non_edge_forces(index, theta, neg_f)
                   for child in self.children if child.cum_size > 0)

    def compute_edge_forces(self, rows: np.ndarray, cols: np.ndarray,
                            vals: np.ndarray, pos_f: np.ndarray) -> None:
        """Attractive forces from the sparse P matrix (CSR triplets)
        (``SpTree.computeEdgeForces``). Vectorized over all edges."""
        n = pos_f.shape[0]
        for i in range(n):
            lo, hi = rows[i], rows[i + 1]
            if lo == hi:
                continue
            j = cols[lo:hi]
            diff = self.data[i] - self.data[j]
            q = 1.0 / (1.0 + np.sum(diff * diff, axis=1))
            pos_f[i] = np.sum((vals[lo:hi] * q)[:, None] * diff, axis=0)

    def depth(self) -> int:
        if self.is_leaf:
            return 1
        return 1 + max((c.depth() for c in self.children if c.cum_size > 0),
                       default=0)
