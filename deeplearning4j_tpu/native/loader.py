"""Python face of the native prefetching loader.

A DataSetIterator whose batch assembly (shuffled gather, one-hot, [0,1]
normalization for IDX images) runs in C++ worker threads outside the GIL —
the AsyncDataSetIterator role with the heavy work off the training thread.
Falls back to numpy assembly when the native library is unavailable.
"""

from __future__ import annotations

import ctypes
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, DataSetIterator


class NativeDataSetIterator(DataSetIterator):
    """Iterate DataSets assembled by the native loader.

    Construct with in-memory arrays (``features``/``labels``) or IDX files
    (``images_path``/``labels_path`` + ``n_classes`` — the MNIST container
    the reference's MnistDataFetcher parses).
    """

    def __init__(self, features=None, labels=None, *,
                 images_path: Optional[str] = None,
                 labels_path: Optional[str] = None,
                 n_classes: int = 10, batch_size: int = 32,
                 shuffle: bool = False, seed: int = 0, prefetch: int = 3,
                 n_threads: int = 2, drop_last: bool = False,
                 feature_shape: Optional[Tuple[int, ...]] = None):
        from deeplearning4j_tpu import native as _n

        self.batch_size = int(batch_size)
        self._lib = _n._load()
        self._handle = None
        self._feature_shape = feature_shape
        if images_path is not None:
            if self._lib is not None:
                self._handle = self._lib.loader_create_idx(
                    images_path.encode(), labels_path.encode(), n_classes,
                    self.batch_size, int(shuffle), seed, prefetch, n_threads,
                    int(drop_last))
                if not self._handle:
                    raise ValueError(
                        f"Failed to parse IDX files: {images_path}, {labels_path}")
                self._n = self._lib.loader_num_examples(self._handle)
                self._x_elems = self._lib.loader_x_elems(self._handle)
                self._y_elems = self._lib.loader_y_elems(self._handle)
                if feature_shape is None:
                    side = int(round(self._x_elems ** 0.5))
                    if side * side == self._x_elems:
                        self._feature_shape = (side, side, 1)
                return
            # fallback: parse IDX in Python
            features, labels = _parse_idx(images_path, labels_path, n_classes)
        self._x = np.ascontiguousarray(
            np.asarray(features, np.float32).reshape(len(features), -1))
        self._y = np.ascontiguousarray(
            np.asarray(labels, np.float32).reshape(len(labels), -1))
        self._n = self._x.shape[0]
        self._x_elems = self._x.shape[1]
        self._y_elems = self._y.shape[1]
        if feature_shape is None and np.asarray(features).ndim > 2:
            self._feature_shape = tuple(np.asarray(features).shape[1:])
        if self._lib is not None:
            self._handle = self._lib.loader_create_mem(
                self._x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                self._y.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                self._n, self._x_elems, self._y_elems, self.batch_size,
                int(shuffle), seed, prefetch, n_threads, int(drop_last))
        else:
            self._shuffle = shuffle
            self._seed = seed
            self._drop_last = drop_last
            self._epoch = 0

    # -- iteration -------------------------------------------------------
    def num_examples(self) -> int:
        return int(self._n)

    def reset(self) -> None:
        if self._handle is not None:
            self._lib.loader_reset(self._handle)
        else:
            self._epoch += 1

    def __iter__(self):
        if self._handle is not None:
            # re-arm the SAME epoch up front: every fresh iter() starts from
            # batch 0 with the same order (Python-fallback semantics) even
            # when an earlier iteration was abandoned mid-epoch and its
            # generator has not been finalized yet; reset() is what advances
            # the shuffle epoch
            self._lib.loader_rewind(self._handle)
            xbuf = np.empty((self.batch_size, self._x_elems), np.float32)
            ybuf = np.empty((self.batch_size, self._y_elems), np.float32)
            while True:
                got = self._lib.loader_next(
                    self._handle,
                    xbuf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                    ybuf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
                if got == 0:
                    return
                yield self._emit(xbuf[:got].copy(), ybuf[:got].copy())
        else:
            order = np.arange(self._n)
            if self._shuffle:
                np.random.default_rng(self._seed + self._epoch).shuffle(order)
            end = (self._n - self._n % self.batch_size
                   if self._drop_last else self._n)
            for s in range(0, end, self.batch_size):
                sel = order[s:s + self.batch_size]
                yield self._emit(self._x[sel], self._y[sel])

    def _emit(self, x: np.ndarray, y: np.ndarray) -> DataSet:
        if self._feature_shape is not None:
            x = x.reshape((x.shape[0],) + tuple(self._feature_shape))
        return DataSet(x, y)

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle is not None and self._lib is not None:
            self._lib.loader_destroy(handle)
            self._handle = None


def _parse_idx(images_path: str, labels_path: str, n_classes: int):
    # shares the general IDX parser with the dataset fetchers
    from pathlib import Path

    from deeplearning4j_tpu.datasets.fetchers import _read_idx

    imgs = _read_idx(Path(images_path))
    if imgs.ndim != 3:
        raise ValueError(f"Expected rank-3 IDX image file, got {images_path}")
    lab = _read_idx(Path(labels_path))
    if lab.ndim != 1 or len(lab) != len(imgs):
        raise ValueError(f"Bad IDX label file {labels_path}")
    x = imgs.reshape(len(imgs), -1).astype(np.float32) / 255.0
    y = np.zeros((len(lab), n_classes), np.float32)
    y[np.arange(len(lab)), lab] = 1.0
    return x, y
