"""Python face of the native threshold codec, with numpy fallback.

Same selection/sign semantics as the on-device codec in
:mod:`deeplearning4j_tpu.parallel.compression`, packed as signed 1-based
indices (one int32 per element) — the reference's
``thresholdEncode/thresholdDecode`` message layout for the host/DCN wire.
"""

from __future__ import annotations

import ctypes
from typing import Optional, Tuple

import numpy as np


def _as_f32(a) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(a, dtype=np.float32).reshape(-1))


def encode_threshold(residual, threshold: float,
                     capacity: Optional[int] = None) -> Optional[np.ndarray]:
    """Encode: returns int32 signed-index message, or None if more than
    ``capacity`` elements pass the threshold (caller sends dense)."""
    from deeplearning4j_tpu import native as _n

    flat = _as_f32(residual)
    cap = len(flat) if capacity is None else int(capacity)
    lib = _n._load()
    if lib is not None:
        out = np.empty(cap, dtype=np.int32)
        count = lib.threshold_encode(
            flat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), len(flat),
            ctypes.c_float(threshold),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), cap)
        if count < 0:
            return None
        return out[:count].copy()
    # numpy fallback
    idx = np.nonzero(np.abs(flat) >= threshold)[0]
    if len(idx) > cap:
        return None
    return ((idx + 1) * np.sign(flat[idx])).astype(np.int32)


def decode_threshold(message: np.ndarray, threshold: float, size: int,
                     out: Optional[np.ndarray] = None) -> np.ndarray:
    """Apply a message additively onto a dense float32 vector of ``size``.

    When ``out`` is provided it is mutated in place and must be a contiguous
    float32 array (a silent copy would lose the updates). Out-of-range
    indices are dropped on both the native and numpy paths.
    """
    from deeplearning4j_tpu import native as _n

    if out is None:
        out = np.zeros(size, dtype=np.float32)
    elif (not isinstance(out, np.ndarray) or out.dtype != np.float32
          or not out.flags["C_CONTIGUOUS"]):
        raise ValueError("out must be a C-contiguous float32 ndarray "
                         "(in-place application cannot survive a copy)")
    msg = np.ascontiguousarray(message, dtype=np.int32)
    lib = _n._load()
    if lib is not None:
        lib.threshold_decode(
            msg.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), len(msg),
            ctypes.c_float(threshold),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), size)
        return out
    idx = np.abs(msg) - 1
    ok = (idx >= 0) & (idx < size)  # drop out-of-range like the native path
    np.add.at(out, idx[ok], np.sign(msg[ok]).astype(np.float32) * threshold)
    return out


def extract_threshold(residual: np.ndarray, threshold: float,
                      message: np.ndarray) -> np.ndarray:
    """Subtract an encoded message from the residual in place
    (post-encode bookkeeping: residual -= quantized)."""
    from deeplearning4j_tpu import native as _n

    if (not isinstance(residual, np.ndarray) or residual.dtype != np.float32
            or not residual.flags["C_CONTIGUOUS"]):
        raise ValueError("residual must be a C-contiguous float32 ndarray")
    msg = np.ascontiguousarray(message, dtype=np.int32)
    flat = residual.reshape(-1)
    lib = _n._load()
    if lib is not None:
        lib.threshold_extract(
            flat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), len(flat),
            ctypes.c_float(threshold),
            msg.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), len(msg))
        return residual
    idx = np.abs(msg) - 1
    ok = (idx >= 0) & (idx < len(flat))
    np.subtract.at(flat, idx[ok],
                   np.sign(msg[ok]).astype(np.float32) * threshold)
    return residual


def count_threshold(values, threshold: float, n_threads: int = 4) -> int:
    """Number of elements that would be encoded — the capacity-sizing pass
    (EncodedGradientsAccumulator.getOptimalBufferSize role)."""
    from deeplearning4j_tpu import native as _n

    flat = _as_f32(values)
    lib = _n._load()
    if lib is not None:
        return int(lib.threshold_count(
            flat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), len(flat),
            ctypes.c_float(threshold), n_threads))
    return int(np.sum(np.abs(flat) >= threshold))
