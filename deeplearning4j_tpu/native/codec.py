"""Python face of the native threshold codec, with numpy fallback.

Same selection/sign semantics as the on-device codec in
:mod:`deeplearning4j_tpu.parallel.compression`, packed as signed 1-based
indices (one int32 per element) — the reference's
``thresholdEncode/thresholdDecode`` message layout for the host/DCN wire.
"""

from __future__ import annotations

import ctypes
from typing import Optional, Tuple

import numpy as np


def _as_f32(a) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(a, dtype=np.float32).reshape(-1))


def encode_threshold(residual, threshold: float,
                     capacity: Optional[int] = None) -> Optional[np.ndarray]:
    """Encode: returns int32 signed-index message, or None if more than
    ``capacity`` elements pass the threshold (caller sends dense)."""
    from deeplearning4j_tpu import native as _n

    flat = _as_f32(residual)
    cap = len(flat) if capacity is None else int(capacity)
    lib = _n._load()
    if lib is not None:
        out = np.empty(cap, dtype=np.int32)
        count = lib.threshold_encode(
            flat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), len(flat),
            ctypes.c_float(threshold),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), cap)
        if count < 0:
            return None
        return out[:count].copy()
    # numpy fallback
    idx = np.nonzero(np.abs(flat) >= threshold)[0]
    if len(idx) > cap:
        return None
    return ((idx + 1) * np.sign(flat[idx])).astype(np.int32)


def decode_threshold(message: np.ndarray, threshold: float, size: int,
                     out: Optional[np.ndarray] = None) -> np.ndarray:
    """Apply a message additively onto a dense float32 vector of ``size``."""
    from deeplearning4j_tpu import native as _n

    if out is None:
        out = np.zeros(size, dtype=np.float32)
    else:
        out = np.ascontiguousarray(out, dtype=np.float32)
    msg = np.ascontiguousarray(message, dtype=np.int32)
    lib = _n._load()
    if lib is not None:
        lib.threshold_decode(
            msg.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), len(msg),
            ctypes.c_float(threshold),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), size)
        return out
    idx = np.abs(msg) - 1
    np.add.at(out, idx, np.sign(msg).astype(np.float32) * threshold)
    return out
