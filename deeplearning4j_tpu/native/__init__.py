"""Native (C++) runtime components, bound via ctypes.

The reference reaches native code for its ETL and gradient-compression hot
paths (libnd4j threshold kernels, DataVec/JavaCPP loaders — SURVEY.md §2.a).
This package holds the TPU framework's equivalents, compiled from
``src/*.cpp`` with g++ on first use (cached under ``build/``) and loaded with
ctypes — no pybind11 dependency. Every entry point has a pure-Python/numpy
fallback so the framework works without a compiler; ``native_available()``
reports which path is live.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

log = logging.getLogger(__name__)

_SRC_DIR = os.path.join(os.path.dirname(__file__), "src")
_BUILD_DIR = os.path.join(os.path.dirname(__file__), "build")
_LIB_BASENAME = "libdl4jtpu_native.so"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _compile() -> Optional[str]:
    sources = [os.path.join(_SRC_DIR, f) for f in sorted(os.listdir(_SRC_DIR))
               if f.endswith(".cpp")]
    if not sources:
        return None
    os.makedirs(_BUILD_DIR, exist_ok=True)
    out = os.path.join(_BUILD_DIR, _LIB_BASENAME)
    stamp = os.path.join(_BUILD_DIR, ".stamp")
    newest_src = max(os.path.getmtime(s) for s in sources)
    if os.path.exists(out) and os.path.exists(stamp) \
            and os.path.getmtime(stamp) >= newest_src:
        return out
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           "-o", out] + sources
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except (subprocess.CalledProcessError, FileNotFoundError) as e:
        detail = getattr(e, "stderr", "") or str(e)
        log.warning("native build failed, using Python fallbacks: %s",
                    detail.strip()[:500])
        return None
    with open(stamp, "w"):
        pass
    return out


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        path = _compile()
        if path is None:
            _build_failed = True
            return None
        lib = ctypes.CDLL(path)
        c_long = ctypes.c_long
        c_float = ctypes.c_float
        c_void = ctypes.c_void_p
        fp = ctypes.POINTER(ctypes.c_float)
        ip = ctypes.POINTER(ctypes.c_int32)

        lib.threshold_encode.restype = c_long
        lib.threshold_encode.argtypes = [fp, c_long, c_float, ip, c_long]
        lib.threshold_decode.restype = None
        lib.threshold_decode.argtypes = [ip, c_long, c_float, fp, c_long]
        lib.threshold_extract.restype = None
        lib.threshold_extract.argtypes = [fp, c_long, c_float, ip, c_long]
        lib.threshold_count.restype = c_long
        lib.threshold_count.argtypes = [fp, c_long, c_float, ctypes.c_int]

        lib.loader_create_mem.restype = c_void
        lib.loader_create_mem.argtypes = [fp, fp, c_long, c_long, c_long,
                                          c_long, ctypes.c_int, ctypes.c_uint,
                                          ctypes.c_int, ctypes.c_int,
                                          ctypes.c_int]
        lib.loader_create_idx.restype = c_void
        lib.loader_create_idx.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                          ctypes.c_int, c_long, ctypes.c_int,
                                          ctypes.c_uint, ctypes.c_int,
                                          ctypes.c_int, ctypes.c_int]
        lib.loader_next.restype = c_long
        lib.loader_next.argtypes = [c_void, fp, fp]
        for name in ("loader_num_examples", "loader_x_elems",
                     "loader_y_elems", "loader_batch"):
            getattr(lib, name).restype = c_long
            getattr(lib, name).argtypes = [c_void]
        lib.loader_reset.restype = None
        lib.loader_reset.argtypes = [c_void]
        lib.loader_rewind.restype = None
        lib.loader_rewind.argtypes = [c_void]
        lib.loader_destroy.restype = None
        lib.loader_destroy.argtypes = [c_void]

        llp = ctypes.POINTER(ctypes.c_longlong)
        lib.corpus_scan_file.restype = c_void
        lib.corpus_scan_file.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                         ctypes.c_int, llp]
        lib.corpus_scan_fill.restype = None
        lib.corpus_scan_fill.argtypes = [c_void, ctypes.c_char_p, llp]
        lib.corpus_scan_free.restype = None
        lib.corpus_scan_free.argtypes = [c_void]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


from deeplearning4j_tpu.native.codec import (  # noqa: E402,F401
    count_threshold,
    decode_threshold,
    encode_threshold,
    extract_threshold,
)
from deeplearning4j_tpu.native.loader import NativeDataSetIterator  # noqa: E402,F401
