// Threshold gradient codec — native wire-format encoder/decoder.
//
// Role of the reference's libnd4j thresholdEncode/thresholdDecode kernels
// (reached through Nd4j.getExecutioner().thresholdEncode, used by
// EncodingHandler.java:139 and EncodedGradientsAccumulator.java:257): turn a
// dense residual vector into the sparse signed-index message sent over the
// wire, and apply such messages back onto a dense vector. On-device (ICI)
// the quantization runs inside the jitted step; this native codec is the
// host-side DCN path where messages leave the chip, so encoding must not
// hold the GIL or bounce through numpy loops.
//
// Wire format (matches the Python fallback in parallel/compression.py):
//   entry k: int32 v, v = +(i+1) for +threshold at index i, -(i+1) for
//   -threshold. Worst case size is bounded by `capacity` the same way
//   EncodedGradientsAccumulator.getOptimalBufferSize bounds its buffers.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// Returns number of encoded entries, or -1 if capacity would be exceeded
// (caller falls back to dense transmission, the reference's 2-bit bitmap
// worst case). Entries are written in ascending index order.
long threshold_encode(const float* in, long n, float threshold,
                      int32_t* out, long capacity) {
    long count = 0;
    for (long i = 0; i < n; ++i) {
        float v = in[i];
        if (v >= threshold) {
            if (count == capacity) return -1;
            out[count++] = (int32_t)(i + 1);
        } else if (v <= -threshold) {
            if (count == capacity) return -1;
            out[count++] = (int32_t)(-(i + 1));
        }
    }
    return count;
}

// Applies message additively: out[i] += sign * threshold per entry.
void threshold_decode(const int32_t* enc, long count, float threshold,
                      float* out, long n) {
    for (long k = 0; k < count; ++k) {
        int32_t v = enc[k];
        long i = (v > 0 ? (long)v : (long)(-v)) - 1;
        if (i >= 0 && i < n) out[i] += (v > 0 ? threshold : -threshold);
    }
}

// Subtracts the encoded entries from the residual (post-encode bookkeeping:
// residual -= quantized), fused here so Python does one call, not two.
void threshold_extract(float* residual, long n, float threshold,
                       const int32_t* enc, long count) {
    for (long k = 0; k < count; ++k) {
        int32_t v = enc[k];
        long i = (v > 0 ? (long)v : (long)(-v)) - 1;
        if (i >= 0 && i < n) residual[i] -= (v > 0 ? threshold : -threshold);
    }
}

// Multi-threaded count of elements that would be encoded (sizing pass).
long threshold_count(const float* in, long n, float threshold, int n_threads) {
    if (n_threads < 1) n_threads = 1;
    if (n_threads == 1 || n < 1 << 16) {
        long c = 0;
        for (long i = 0; i < n; ++i) {
            float v = in[i];
            if (v >= threshold || v <= -threshold) ++c;
        }
        return c;
    }
    std::vector<std::thread> workers;
    std::vector<long> counts((size_t)n_threads, 0);
    long chunk = (n + n_threads - 1) / n_threads;
    for (int t = 0; t < n_threads; ++t) {
        workers.emplace_back([=, &counts] {
            long lo = (long)t * chunk;
            long hi = lo + chunk < n ? lo + chunk : n;
            long c = 0;
            for (long i = lo; i < hi; ++i) {
                float v = in[i];
                if (v >= threshold || v <= -threshold) ++c;
            }
            counts[(size_t)t] = c;
        });
    }
    long total = 0;
    for (int t = 0; t < n_threads; ++t) {
        workers[(size_t)t].join();
        total += counts[(size_t)t];
    }
    return total;
}

}  // extern "C"
