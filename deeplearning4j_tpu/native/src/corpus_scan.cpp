// Multithreaded streaming corpus word-frequency scan.
//
// The reference's vocabulary construction is a parallel corpus scan across
// JVM threads (VocabConstructor.java:31 + SequenceVectors' per-core
// tokenization); CPython counts tokens under the GIL. This scanner STREAMS
// the file in fixed-size blocks (so memory is O(block + vocab), not
// O(corpus) — the reference's constructor streams sequences the same way),
// splits each block into per-thread chunks at ASCII-whitespace boundaries,
// counts zero-copy string_view tokens in real threads, and merges into a
// global map that only ever copies UNIQUE words.
//
// Tokenization semantics: split on ASCII whitespace (exactly what
// bytes.split() does in the Python fallback); optional ASCII lowercasing.
// Words are returned newline-joined in (count desc, word asc) order so the
// resulting vocabulary is deterministic.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <fstream>
#include <string>
#include <string_view>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

constexpr size_t kBlock = 64u << 20;  // 64 MiB per streamed block

struct ScanResult {
    std::vector<std::pair<std::string, long long>> entries;  // sorted
    long long total_tokens = 0;
    long long words_bytes = 0;  // newline-joined serialization size
};

inline bool is_space(unsigned char c) {
    return c == ' ' || c == '\t' || c == '\n' || c == '\r' || c == '\v' ||
           c == '\f';
}

// tokens are string_views into the (already-lowercased) block buffer: no
// per-token allocation; uniques copy once at the block merge
void count_chunk(const char* data, size_t begin, size_t end,
                 std::unordered_map<std::string_view, long long>* out,
                 long long* total) {
    size_t i = begin;
    while (i < end) {
        while (i < end && is_space((unsigned char)data[i])) i++;
        size_t start = i;
        while (i < end && !is_space((unsigned char)data[i])) i++;
        if (i > start) {
            ++(*out)[std::string_view(data + start, i - start)];
            ++(*total);
        }
    }
}

void count_block(const std::string& buf, int nt,
                 std::unordered_map<std::string, long long>* global,
                 long long* total_tokens) {
    if (buf.empty()) return;
    int threads_n = nt;
    if (buf.size() < (size_t)threads_n * 4096) threads_n = 1;

    // chunk boundaries snapped forward to whitespace so no token splits
    std::vector<size_t> bounds(threads_n + 1, 0);
    bounds[threads_n] = buf.size();
    for (int t = 1; t < threads_n; t++) {
        size_t b = buf.size() * t / threads_n;
        while (b < buf.size() && !is_space((unsigned char)buf[b])) b++;
        bounds[t] = b;
    }

    std::vector<std::unordered_map<std::string_view, long long>> maps(threads_n);
    std::vector<long long> totals(threads_n, 0);
    std::vector<std::thread> workers;
    for (int t = 0; t < threads_n; t++)
        workers.emplace_back(count_chunk, buf.data(), bounds[t],
                             bounds[t + 1], &maps[t], &totals[t]);
    for (auto& th : workers) th.join();

    for (int t = 0; t < threads_n; t++) {
        *total_tokens += totals[t];
        for (auto& kv : maps[t])
            (*global)[std::string(kv.first)] += kv.second;
    }
}

}  // namespace

extern "C" {

// Scan `path`; returns an opaque handle (nullptr on IO failure).
// out[0] = unique words, out[1] = total tokens, out[2] = serialized bytes.
void* corpus_scan_file(const char* path, int n_threads, int to_lower,
                       long long* out) {
    std::ifstream f(path, std::ios::binary);
    if (!f) return nullptr;

    const int nt = n_threads < 1 ? 1 : n_threads;
    std::unordered_map<std::string, long long> global;
    long long total_tokens = 0;
    std::string buf;      // carry (partial trailing token) + fresh block
    size_t carry = 0;     // bytes at the front of buf carried over

    while (true) {
        buf.resize(carry + kBlock);
        f.read(&buf[carry], kBlock);
        const size_t got = (size_t)f.gcount();
        buf.resize(carry + got);
        const bool eof = got < kBlock;

        if (to_lower) {  // only the fresh bytes; carry is already lowered
            for (size_t i = carry; i < buf.size(); i++)
                if (buf[i] >= 'A' && buf[i] <= 'Z') buf[i] += 'a' - 'A';
        }

        size_t usable = buf.size();
        if (!eof) {
            // hold back the trailing partial token for the next block
            while (usable > 0 && !is_space((unsigned char)buf[usable - 1]))
                usable--;
        }
        if (usable == 0 && !eof) {
            // a single token longer than the block: keep accumulating
            carry = buf.size();
            continue;
        }
        std::string rest(buf, usable);
        buf.resize(usable);
        count_block(buf, nt, &global, &total_tokens);
        buf = std::move(rest);
        carry = buf.size();
        if (eof) break;
    }

    auto* res = new ScanResult();
    res->total_tokens = total_tokens;
    res->entries.reserve(global.size());
    for (auto it = global.begin(); it != global.end();) {
        auto node = global.extract(it++);
        res->entries.emplace_back(std::move(node.key()), node.mapped());
    }
    std::sort(res->entries.begin(), res->entries.end(),
              [](const auto& a, const auto& b) {
                  if (a.second != b.second) return a.second > b.second;
                  return a.first < b.first;
              });
    for (auto& e : res->entries) res->words_bytes += (long long)e.first.size() + 1;

    out[0] = (long long)res->entries.size();
    out[1] = res->total_tokens;
    out[2] = res->words_bytes;
    return res;
}

// Fill caller-allocated buffers: words newline-joined (words_bytes long),
// counts (n_unique long longs).
void corpus_scan_fill(void* handle, char* words_buf, long long* counts) {
    auto* res = (ScanResult*)handle;
    char* p = words_buf;
    for (size_t i = 0; i < res->entries.size(); i++) {
        const auto& e = res->entries[i];
        std::memcpy(p, e.first.data(), e.first.size());
        p += e.first.size();
        *p++ = '\n';
        counts[i] = e.second;
    }
}

void corpus_scan_free(void* handle) { delete (ScanResult*)handle; }

}  // extern "C"
