// Native prefetching batch loader.
//
// Role of the reference's native ETL path: AsyncDataSetIterator.java:30 runs
// a JVM prefetch thread over DataVec's record pipeline with device-aware
// buffering; the heavy parsing/copy work happens outside the training
// thread. A Python-thread version of that still serializes on the GIL while
// it shuffles/gathers/casts numpy slices, so this loader does the batch
// assembly in real C++ threads: parse IDX files (or adopt caller-owned float
// buffers), then worker threads fill a bounded ring of ready batches
// (shuffled gather + dtype cast + optional normalization + one-hot) that the
// training loop pops with a single memcpy-free handoff.

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <random>
#include <thread>
#include <vector>

namespace {

struct Batch {
    std::vector<float> x;
    std::vector<float> y;
    long count = 0;
};

struct Loader {
    // dataset (owned or adopted)
    std::vector<float> own_x, own_y;
    const float* data_x = nullptr;  // [n, x_elems]
    const float* data_y = nullptr;  // [n, y_elems]
    long n = 0, x_elems = 0, y_elems = 0;
    long batch = 0;
    bool shuffle = false;
    unsigned seed = 0;
    bool drop_last = false;

    // epoch state: the order vector is an immutable per-epoch snapshot so
    // workers can read it lock-free while reset() installs a fresh one
    std::shared_ptr<const std::vector<long>> order;
    long epoch = 0;

    // ready batches keyed by batch index: the consumer pops strictly in
    // claim order so multi-threaded assembly cannot reorder delivery
    std::map<long, Batch> ready;
    size_t prefetch = 2;
    std::mutex mu;
    std::condition_variable cv_ready, cv_space;
    std::vector<std::thread> workers;
    std::atomic<bool> stopping{false};
    long produced = 0, consumed = 0, total_batches = 0;

    void start(int n_threads) {
        reset_epoch();
        for (int t = 0; t < n_threads; ++t)
            workers.emplace_back([this] { work(); });
    }

    void reset_epoch() {
        auto fresh = std::make_shared<std::vector<long>>((size_t)n);
        for (long i = 0; i < n; ++i) (*fresh)[(size_t)i] = i;
        if (shuffle) {
            std::mt19937_64 rng(seed + (unsigned long)epoch);
            std::shuffle(fresh->begin(), fresh->end(), rng);
        }
        order = std::move(fresh);
        total_batches = drop_last ? n / batch : (n + batch - 1) / batch;
        produced = consumed = 0;
    }

    void work() {
        for (;;) {
            long b = -1, my_epoch = -1;
            std::shared_ptr<const std::vector<long>> ord;
            {
                std::unique_lock<std::mutex> lk(mu);
                cv_space.wait(lk, [this] {
                    return stopping.load() ||
                           (ready.size() + (size_t)0 < prefetch &&
                            produced < total_batches);
                });
                if (stopping.load()) return;
                b = produced++;
                my_epoch = epoch;
                ord = order;
            }
            long lo = b * batch;
            long hi = lo + batch < n ? lo + batch : n;
            Batch out;
            out.count = hi - lo;
            out.x.resize((size_t)(out.count * x_elems));
            out.y.resize((size_t)(out.count * y_elems));
            for (long r = lo; r < hi; ++r) {
                long src = (*ord)[(size_t)r];
                std::memcpy(&out.x[(size_t)((r - lo) * x_elems)],
                            data_x + src * x_elems,
                            (size_t)x_elems * sizeof(float));
                std::memcpy(&out.y[(size_t)((r - lo) * y_elems)],
                            data_y + src * y_elems,
                            (size_t)y_elems * sizeof(float));
            }
            {
                std::unique_lock<std::mutex> lk(mu);
                if (my_epoch == epoch)  // drop stale batches after reset()
                    ready.emplace(b, std::move(out));
            }
            cv_ready.notify_all();
        }
    }

    // returns rows copied, 0 at epoch end
    long next(float* x_out, float* y_out) {
        std::unique_lock<std::mutex> lk(mu);
        if (consumed >= total_batches) return 0;
        cv_ready.wait(lk, [this] {
            return stopping.load() || ready.count(consumed) != 0;
        });
        if (stopping.load()) return 0;
        auto it = ready.find(consumed);
        Batch b = std::move(it->second);
        ready.erase(it);
        ++consumed;
        lk.unlock();
        cv_space.notify_all();
        std::memcpy(x_out, b.x.data(), b.x.size() * sizeof(float));
        std::memcpy(y_out, b.y.data(), b.y.size() * sizeof(float));
        return b.count;
    }

    void reset(bool bump_epoch) {
        std::unique_lock<std::mutex> lk(mu);
        // drop whatever the workers queued for the old epoch
        ready.clear();
        if (bump_epoch) ++epoch;
        reset_epoch();
        lk.unlock();
        cv_space.notify_all();
    }

    ~Loader() {
        {
            // take the lock so no worker can be between predicate-check and
            // wait() when the flag flips (lost-wakeup → join deadlock)
            std::unique_lock<std::mutex> lk(mu);
            stopping.store(true);
        }
        cv_space.notify_all();
        cv_ready.notify_all();
        for (auto& w : workers) w.join();
    }
};

static uint32_t read_be32(FILE* f) {
    unsigned char b[4];
    if (fread(b, 1, 4, f) != 4) return 0;
    return ((uint32_t)b[0] << 24) | ((uint32_t)b[1] << 16) |
           ((uint32_t)b[2] << 8) | (uint32_t)b[3];
}

}  // namespace

extern "C" {

// Adopt caller-owned float32 buffers (must outlive the loader).
void* loader_create_mem(const float* x, const float* y, long n, long x_elems,
                        long y_elems, long batch, int shuffle, unsigned seed,
                        int prefetch, int n_threads, int drop_last) {
    auto* L = new Loader();
    L->data_x = x;
    L->data_y = y;
    L->n = n;
    L->x_elems = x_elems;
    L->y_elems = y_elems;
    L->batch = batch;
    L->shuffle = shuffle != 0;
    L->seed = seed;
    L->drop_last = drop_last != 0;
    L->prefetch = (size_t)(prefetch < 1 ? 1 : prefetch);
    L->start(n_threads < 1 ? 1 : n_threads);
    return L;
}

// Parse IDX image+label files (the MNIST/EMNIST container format the
// reference's MnistDataFetcher reads), normalize pixels to [0,1], one-hot
// labels. Returns nullptr on parse failure.
void* loader_create_idx(const char* images_path, const char* labels_path,
                        int n_classes, long batch, int shuffle, unsigned seed,
                        int prefetch, int n_threads, int drop_last) {
    FILE* fi = fopen(images_path, "rb");
    if (!fi) return nullptr;
    FILE* fl = fopen(labels_path, "rb");
    if (!fl) {
        fclose(fi);
        return nullptr;
    }
    auto fail = [&]() -> void* {
        fclose(fi);
        fclose(fl);
        return nullptr;
    };
    uint32_t magic_i = read_be32(fi), n_img = read_be32(fi);
    uint32_t rows = read_be32(fi), cols = read_be32(fi);
    uint32_t magic_l = read_be32(fl), n_lab = read_be32(fl);
    if (magic_i != 0x00000803 || magic_l != 0x00000801 || n_img != n_lab)
        return fail();
    long n = (long)n_img, elems = (long)rows * (long)cols;
    auto* L = new Loader();
    L->own_x.resize((size_t)(n * elems));
    L->own_y.assign((size_t)(n * n_classes), 0.0f);
    std::vector<unsigned char> buf((size_t)elems);
    for (long i = 0; i < n; ++i) {
        if (fread(buf.data(), 1, (size_t)elems, fi) != (size_t)elems) {
            delete L;
            return fail();
        }
        float* dst = &L->own_x[(size_t)(i * elems)];
        for (long j = 0; j < elems; ++j) dst[j] = buf[(size_t)j] / 255.0f;
        int lab = fgetc(fl);
        if (lab < 0 || lab >= n_classes) {
            delete L;
            return fail();
        }
        L->own_y[(size_t)(i * n_classes + lab)] = 1.0f;
    }
    fclose(fi);
    fclose(fl);
    L->data_x = L->own_x.data();
    L->data_y = L->own_y.data();
    L->n = n;
    L->x_elems = elems;
    L->y_elems = n_classes;
    L->batch = batch;
    L->shuffle = shuffle != 0;
    L->seed = seed;
    L->drop_last = drop_last != 0;
    L->prefetch = (size_t)(prefetch < 1 ? 1 : prefetch);
    L->start(n_threads < 1 ? 1 : n_threads);
    return L;
}

long loader_next(void* h, float* x_out, float* y_out) {
    return static_cast<Loader*>(h)->next(x_out, y_out);
}

// advance to the next epoch (fresh shuffle order)
void loader_reset(void* h) { static_cast<Loader*>(h)->reset(true); }

// re-arm the SAME epoch (identical order) — used when iteration restarts
// without an explicit reset, matching the Python fallback's semantics
void loader_rewind(void* h) { static_cast<Loader*>(h)->reset(false); }

long loader_num_examples(void* h) { return static_cast<Loader*>(h)->n; }

long loader_x_elems(void* h) { return static_cast<Loader*>(h)->x_elems; }

long loader_y_elems(void* h) { return static_cast<Loader*>(h)->y_elems; }

long loader_batch(void* h) { return static_cast<Loader*>(h)->batch; }

void loader_destroy(void* h) { delete static_cast<Loader*>(h); }

}  // extern "C"
