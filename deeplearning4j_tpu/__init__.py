"""deeplearning4j_tpu — a TPU-native deep learning framework.

A ground-up JAX/XLA/Pallas/pjit re-design with the capabilities of the
Deeplearning4j reference stack (see SURVEY.md): declarative layer-config DSL,
sequential (MultiLayerNetwork) and DAG (ComputationGraph) models, DL4J-semantic
updaters and weight inits, evaluation / early stopping / transfer learning,
checkpointing + Keras import, a model zoo, NLP embeddings, clustering, and
mesh-sharded distributed training over ICI/DCN.

The compute path is pure-functional JAX: layers are (init_params, forward)
pairs, gradients come from ``jax.grad`` over the whole-model loss, and the
training step is a single jitted, donated-buffer function. Distribution is
expressed with ``jax.sharding`` over a device ``Mesh`` — not thread replication.
"""

__version__ = "0.1.0"

from deeplearning4j_tpu.nn.conf import (  # noqa: F401
    NeuralNetConfiguration,
    MultiLayerConfiguration,
    InputType,
)

# Lazy top-level conveniences: the heavyweight model/zoo modules import on
# first attribute access, keeping bare `import deeplearning4j_tpu` fast.
_LAZY = {
    "MultiLayerNetwork": "deeplearning4j_tpu.nn.multilayer",
    "ComputationGraph": "deeplearning4j_tpu.nn.graph",
    "ParallelWrapper": "deeplearning4j_tpu.parallel",
    "ParallelInference": "deeplearning4j_tpu.parallel",
    "Evaluation": "deeplearning4j_tpu.eval",
    "DataSet": "deeplearning4j_tpu.datasets.dataset",
    "ModelSelector": "deeplearning4j_tpu.zoo.zoo_model",
    "SameDiff": "deeplearning4j_tpu.autodiff.samediff",
}


def __getattr__(name):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib
    return getattr(importlib.import_module(mod), name)
