"""Model import from other frameworks (reference: deeplearning4j-modelimport)."""
