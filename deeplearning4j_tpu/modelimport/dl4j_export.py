"""Reverse migration: write our models as DL4J-format checkpoints.

``modelimport/dl4j.py`` reads the reference's ``ModelSerializer`` zips;
this module writes them — ``configuration.json`` in the DL4J
MultiLayerConfiguration JSON dialect, ``coefficients.bin`` in the ND4J
binary layout, and ``updaterState.bin`` for known updater classes
(``ModelSerializer.java:51`` writeModel's file set) — so a model trained
here can be handed back to a DL4J deployment and keep fine-tuning.

Scope: MultiLayerNetworks AND ComputationGraphs over the common layer
families (Dense, Output/RnnOutput, Convolution, Subsampling,
BatchNormalization, Embedding, Activation, Dropout, LSTM/GravesLSTM,
SimpleRnn, GlobalPooling, Loss) and graph vertex types (Merge,
ElementWise, Subset, Stack/Unstack, Scale/Shift, L2/L2Normalize,
LastTimeStep/ReverseTimeSeries/DuplicateToTimeSeries).
Anything the dialect cannot express raises loudly (IDropout objects,
lr schedules, other layer types). The emitted dialect is exactly what
``import_dl4j_configuration`` parses, and the flattened parameter vector
follows ``_dl4j_param_specs`` (ParamInitializer order, 'f' weight order,
HWIO→OIHW conv kernels, BN running stats in-line). Layout boundaries
(cnn→ff flatten with its NHWC→NCHW dense-weight row permutation, and
DL4J's rnn↔ff preprocessors around time-distributed dense layers) are
emitted as ``inputPreProcessors``.

Like the reader, the wire format is implemented from the 0.9.x layout;
round trips are verified through the reader (no ND4J runtime exists in
this image to cross-check).
"""

from __future__ import annotations

import json
import zipfile
from typing import Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.modelimport.dl4j import (
    _UPDATER_STATE_SLOTS,
    UnsupportedDl4jConfigurationException,
    _dl4j_param_specs,
    _layer_seq,
    _updater_blocks,
)
from deeplearning4j_tpu.modelimport.nd4j_binary import nd4j_array_to_bytes

__all__ = ["export_multi_layer_network", "export_computation_graph"]

_ACT_CLASS = {
    "relu": "ActivationReLU", "relu6": "ActivationReLU6",
    "sigmoid": "ActivationSigmoid", "tanh": "ActivationTanH",
    "softmax": "ActivationSoftmax", "identity": "ActivationIdentity",
    "linear": "ActivationIdentity", "softplus": "ActivationSoftPlus",
    "softsign": "ActivationSoftSign", "elu": "ActivationELU",
    "selu": "ActivationSELU", "cube": "ActivationCube",
    "hardsigmoid": "ActivationHardSigmoid",
    "hardtanh": "ActivationHardTanH", "leakyrelu": "ActivationLReLU",
    "rationaltanh": "ActivationRationalTanh", "swish": "ActivationSwish",
    "gelu": "ActivationGELU", "thresholdedrelu": "ActivationThresholdedReLU",
}

_LOSS_CLASS = {
    "mcxent": "LossMCXENT", "negativeloglikelihood": "LossMCXENT",
    "mse": "LossMSE", "xent": "LossBinaryXENT", "l1": "LossL1",
    "mae": "LossL1", "kld": "LossKLD", "poisson": "LossPoisson",
    "cosine_proximity": "LossCosineProximity", "hinge": "LossHinge",
    "squared_hinge": "LossSquaredHinge",
    "msle": "LossMeanSquaredLogarithmicError",
}

_CONV_MODE = {"truncate": "Truncate", "same": "Same", "strict": "Strict"}

# layers whose DL4J implementation consumes/produces time-major 3-D input
_RNN_NATURED = {"LSTMLayer", "GravesLSTMLayer", "GravesBidirectionalLSTMLayer",
                "SimpleRnnLayer", "GRULayer", "RnnOutputLayer"}
_FF_NATURED = {"DenseLayer", "OutputLayer", "ElementWiseMultiplicationLayer",
               "EmbeddingLayer"}


def _activation_entry(act) -> Optional[dict]:
    if act is None:
        return None
    params: Dict[str, float] = {}
    if isinstance(act, tuple):
        act, params = act[0], dict(act[1])
    key = str(act).lower()
    cls = _ACT_CLASS.get(key)
    if cls is None:
        raise UnsupportedDl4jConfigurationException(
            f"cannot express activation {act!r} in the DL4J dialect")
    out = {"@class": f"org.nd4j.linalg.activations.impl.{cls}"}
    out.update(params)
    return out


def _loss_entry(loss) -> dict:
    cls = _LOSS_CLASS.get(str(loss).lower())
    if cls is None:
        raise UnsupportedDl4jConfigurationException(
            f"cannot express loss {loss!r} in the DL4J dialect")
    return {"@class": f"org.nd4j.linalg.lossfunctions.impl.{cls}"}


def _updater_entry(u) -> Optional[dict]:
    if u is None:
        return None
    name = type(u).__name__
    table = {"Sgd": "Sgd", "Adam": "Adam", "AdaMax": "AdaMax",
             "AdaDelta": "AdaDelta", "AdaGrad": "AdaGrad", "Nadam": "Nadam",
             "Nesterovs": "Nesterovs", "RmsProp": "RmsProp", "NoOp": "NoOp"}
    if name not in table:
        raise UnsupportedDl4jConfigurationException(
            f"cannot express updater {name} in the DL4J dialect")
    out: Dict[str, object] = {
        "@class": f"org.nd4j.linalg.learning.config.{table[name]}"}
    lr = getattr(u, "learning_rate", None)
    if isinstance(lr, (int, float)):
        out["learningRate"] = float(lr)
    elif lr is not None:
        raise UnsupportedDl4jConfigurationException(
            "cannot export a learning-rate SCHEDULE to the DL4J dialect")
    for ours, theirs in (("beta1", "beta1"), ("beta2", "beta2"),
                         ("momentum", "momentum"),
                         ("rms_decay", "rmsDecay")):
        v = getattr(u, ours, None)
        if isinstance(v, (int, float)):
            out[theirs] = float(v)
    return out


def _distribution_entry(dist) -> dict:
    """Serialize a ``Distribution`` spec into DL4J's ``@class``-tagged
    ``dist`` field (``org.deeplearning4j.nn.conf.distribution.*`` —
    the inverse of ``dl4j._distribution``), so ``DISTRIBUTION`` weight
    init exports with the payload DL4J needs to re-init from it."""
    if dist is None:
        raise UnsupportedDl4jConfigurationException(
            "weightInit DISTRIBUTION without a Distribution spec cannot "
            "be expressed in the DL4J dialect")
    if isinstance(dist, dict):
        from deeplearning4j_tpu.nn.weights import Distribution
        dist = Distribution.from_dict(dist)
    pkg = "org.deeplearning4j.nn.conf.distribution"
    k = dist.kind
    if k == "normal":
        return {"@class": f"{pkg}.NormalDistribution",
                "mean": float(dist.mean), "std": float(dist.std)}
    if k == "uniform":
        return {"@class": f"{pkg}.UniformDistribution",
                "lower": float(dist.lower), "upper": float(dist.upper)}
    if k == "truncated_normal":
        return {"@class": f"{pkg}.TruncatedNormalDistribution",
                "mean": float(dist.mean), "std": float(dist.std)}
    if k == "log_normal":
        return {"@class": f"{pkg}.LogNormalDistribution",
                "mean": float(dist.mean), "std": float(dist.std)}
    if k == "orthogonal":
        return {"@class": f"{pkg}.OrthogonalDistribution",
                "gain": float(dist.gain)}
    if k == "constant":
        return {"@class": f"{pkg}.ConstantDistribution",
                "value": float(dist.value)}
    if k == "binomial":
        return {"@class": f"{pkg}.BinomialDistribution",
                "numberOfTrials": int(dist.n),
                "probabilityOfSuccess": float(dist.p)}
    raise UnsupportedDl4jConfigurationException(
        f"cannot express distribution kind {k!r} in the DL4J dialect")


def _layer_entry(layer, updater_entry) -> Tuple[str, dict]:
    """(WRAPPER_OBJECT type name, cfg dict) for one layer."""
    cls = type(layer).__name__
    cfg: Dict[str, object] = {}
    if getattr(layer, "name", None):
        cfg["layerName"] = layer.name
    act = _activation_entry(getattr(layer, "activation", None))
    if act is not None:
        cfg["activationFn"] = act
    if updater_entry is not None:
        cfg["iUpdater"] = updater_entry
    drop = getattr(layer, "dropout", None)
    if drop is not None:
        from deeplearning4j_tpu.nn.dropout import Dropout as _PlainDropout
        if type(drop) is _PlainDropout:
            if not isinstance(drop.p, (int, float)):
                raise UnsupportedDl4jConfigurationException(
                    "cannot express a SCHEDULED dropout probability "
                    f"({type(drop.p).__name__}) in the DL4J dialect — "
                    "plain Dropout objects export as scalar dropOut")
            # a plain inverted-dropout object IS DL4J's scalar dropOut
            drop = float(drop.p)
        if not isinstance(drop, (int, float)):
            raise UnsupportedDl4jConfigurationException(
                f"cannot express dropout object {type(drop).__name__} in "
                "the DL4J dialect (scalar keep probabilities only)")
        cfg["dropOut"] = float(drop)
    # per-layer regularization / init travel with the layer so handback
    # fine-tuning keeps training the same objective
    for ours, theirs in (("l1", "l1"), ("l2", "l2"),
                         ("l1_bias", "l1Bias"), ("l2_bias", "l2Bias")):
        v = getattr(layer, ours, None)
        if v:
            cfg[theirs] = float(v)
    wi = getattr(layer, "weight_init", None)
    if wi:
        cfg["weightInit"] = str(wi).upper()
        if str(wi) == "distribution":
            cfg["dist"] = _distribution_entry(
                getattr(layer, "distribution", None))

    def ff():
        cfg["nin"] = int(layer.n_in)
        cfg["nout"] = int(layer.n_out)

    if cls == "DenseLayer":
        ff()
        cfg["hasBias"] = bool(getattr(layer, "has_bias", True))
        return "dense", cfg
    if cls in ("OutputLayer", "RnnOutputLayer"):
        ff()
        cfg["lossFn"] = _loss_entry(layer.loss)
        return ("output" if cls == "OutputLayer" else "rnnoutput"), cfg
    if cls == "LossLayer":
        cfg["lossFn"] = _loss_entry(layer.loss)
        return "loss", cfg
    if cls == "ConvolutionLayer":
        ff()
        cfg["kernelSize"] = list(layer.kernel_size)
        cfg["stride"] = list(layer.stride)
        cfg["padding"] = list(layer.padding)
        cfg["dilation"] = list(layer.dilation)
        cfg["convolutionMode"] = _CONV_MODE[layer.convolution_mode]
        return "convolution", cfg
    if cls == "SubsamplingLayer":
        cfg["poolingType"] = layer.pooling_type.upper()
        cfg["kernelSize"] = list(layer.kernel_size)
        cfg["stride"] = list(layer.stride)
        cfg["padding"] = list(layer.padding)
        cfg["convolutionMode"] = _CONV_MODE[layer.convolution_mode]
        return "subsampling", cfg
    if cls == "BatchNormalizationLayer":
        cfg["eps"] = float(layer.eps)
        cfg["decay"] = float(layer.decay)
        cfg["nin"] = cfg["nout"] = int(layer.n_in)
        if getattr(layer, "lock_gamma_beta", False):
            cfg["lockGammaBeta"] = True
        return "batchNormalization", cfg
    if cls == "EmbeddingLayer":
        ff()
        cfg["hasBias"] = bool(getattr(layer, "has_bias", False))
        return "embedding", cfg
    if cls == "ActivationLayer":
        return "activation", cfg
    if cls == "DropoutLayer":
        return "dropout", cfg
    if cls in ("LSTMLayer", "GravesLSTMLayer"):
        ff()
        cfg["forgetGateBiasInit"] = float(
            getattr(layer, "forget_gate_bias_init", 1.0))
        return ("LSTM" if cls == "LSTMLayer" else "gravesLSTM"), cfg
    if cls == "SimpleRnnLayer":
        ff()
        return "SimpleRnn", cfg
    if cls == "GlobalPoolingLayer":
        cfg["poolingType"] = layer.pooling_type.upper()
        return "GlobalPooling", cfg
    raise UnsupportedDl4jConfigurationException(
        f"export does not support layer type {cls}")


def _walk_boundaries(conf):
    """(preprocessor entries, cnn→ff weight-permutation map).

    Tracks the DL4J-side data nature (ff / rnn / cnn) through the stack
    and emits the preprocessor DL4J needs at every transition:
    ``cnnToFeedForward`` (with the NHWC→NCHW weight permutation recorded
    for the receiving dense layer), ``rnnToFeedForward`` /
    ``feedForwardToRnn`` around time-distributed dense layers. Boundary
    kinds with no DL4J spelling here (cnn3d / cnn_seq / cnn_flat inputs)
    raise instead of silently exporting a wrong checkpoint.
    """
    pre: Dict[str, dict] = {}
    permute: Dict[int, Tuple[int, int, int]] = {}
    it = conf.input_type
    if it is not None and it.kind not in ("ff", "rnn", "cnn"):
        raise UnsupportedDl4jConfigurationException(
            f"cannot export input type kind {it.kind!r} to the DL4J "
            "dialect (ff / rnn / cnn only)")
    nature = it.kind if it is not None else None
    for i, layer in enumerate(conf.layers):
        cls = type(layer).__name__
        fed = conf.layer_input_types[i]
        if cls in _RNN_NATURED:
            if nature == "ff":
                pre[str(i)] = {"feedForwardToRnn": {}}
            elif nature == "cnn":
                raise UnsupportedDl4jConfigurationException(
                    "cnn→rnn boundary export is not supported")
            nature = "rnn"
        elif cls in _FF_NATURED:
            if nature == "cnn":
                if fed is None or fed.kind != "ff" or it is None:
                    raise UnsupportedDl4jConfigurationException(
                        f"unsupported cnn boundary into layer {i} ({cls})")
                pre[str(i)], permute[i] = _cnn_to_ff_entry(it)
            elif nature == "rnn":
                # time-distributed dense: DL4J flattens time around it
                pre[str(i)] = {"rnnToFeedForward": {}}
            nature = "ff"
        elif cls in ("ConvolutionLayer", "SubsamplingLayer"):
            if nature not in ("cnn", None):
                raise UnsupportedDl4jConfigurationException(
                    f"{nature}→cnn boundary export is not supported")
            nature = "cnn"
        elif cls == "GlobalPoolingLayer":
            nature = "ff"  # DL4J GlobalPooling consumes rnn/cnn natively
        # shape-preserving layers (BN, Activation, Dropout) keep nature
        if it is not None and fed is not None:
            it = layer.output_type(fed)
    return pre, permute


def _flatten_segment(layer, name, order, arr) -> np.ndarray:
    """Inverse of _iter_param_slices' reshape/convert for one value."""
    a = np.asarray(arr, np.float32)
    cls = type(layer).__name__
    if cls == "ConvolutionLayer" and name == "W":
        # ours HWIO → DL4J OIHW, then C-order flatten
        return np.transpose(a, (3, 2, 0, 1)).reshape(-1)
    if order == "f":
        return a.reshape(-1, order="F")
    return a.reshape(-1)


def _permute_nhwc_rows_to_nchw(w: np.ndarray, h: int, wdt: int,
                               c: int) -> np.ndarray:
    """Reorder dense-weight ROWS from our NHWC flatten index
    (h·W·C + w·C + c) to DL4J's NCHW (c·H·W + h·W + w)."""
    idx = np.arange(h * wdt * c).reshape(h, wdt, c)      # ours: [h][w][c]
    nchw_order = idx.transpose(2, 0, 1).reshape(-1)      # walk c, h, w
    return np.asarray(w)[nchw_order]


def _export_value(layer, i, name, order, container, permute) -> np.ndarray:
    arr = np.asarray(container[name], np.float32)
    if i in permute and name == "W":
        arr = _permute_nhwc_rows_to_nchw(arr, *permute[i])
    return _flatten_segment(layer, name, order, arr)


def _updater_state_vector(net, permute) -> Optional[np.ndarray]:
    """updaterState.bin contents in DL4J's block/slot layout, or None
    when some updater class has no known slot layout. Works for both
    network kinds — ``_layer_seq`` yields MLN layer indices or graph
    vertex names as the container keys."""
    blocks = _updater_blocks(net.conf, net._updaters)
    segs: List[np.ndarray] = []
    layers = dict(_layer_seq(net.conf))
    for u, block in blocks:
        slots = _UPDATER_STATE_SLOTS.get(type(u).__name__)
        if slots is None:
            return None
        for slot in slots:
            for i, name, _shape, order, _convert in block:
                state = net.updater_states[i][name]
                if slot not in state:
                    return None
                segs.append(_export_value(layers[i], i, name, order,
                                          {name: state[slot]}, permute))
    if not segs:
        return np.zeros(0, np.float32)
    return np.concatenate(segs)


def export_multi_layer_network(net, path: str,
                               save_updater: bool = True,
                               normalizer=None) -> None:
    """Write ``net`` as a DL4J-format zip (configuration.json +
    coefficients.bin + updaterState.bin + normalizer.bin when
    ``normalizer`` is given, matching ``ModelSerializer.writeModel``'s
    optional dataNormalization argument, ``ModelSerializer.java:106,
    165-168``); re-importable via ``restore_multi_layer_network`` and
    structured for DL4J's own ``ModelSerializer``."""
    conf = net.conf
    if conf.input_pre_processors:
        raise UnsupportedDl4jConfigurationException(
            "explicit input_pre_processor specs have no DL4J serialized "
            "form; export supports automatically inferred boundaries only")

    g = conf.global_conf
    default_updater = _updater_entry(g.updater) or {
        "@class": "org.nd4j.linalg.learning.config.Sgd",
        "learningRate": 0.1}

    confs: List[dict] = []
    for i, layer in enumerate(conf.layers):
        upd = _updater_entry(layer.updater) or default_updater
        t, cfg = _layer_entry(layer, upd)
        # effective bias updater (layer override, else global bias updater;
        # multilayer.py:85 resolution) — emitted when it differs from the
        # weight updater, since it moves UpdaterBlock boundaries and with
        # them the updaterState.bin layout (BaseLayer.java biasUpdater)
        bias_u = getattr(layer, "bias_updater", None) or g.bias_updater
        if bias_u is not None:
            bias_entry = _updater_entry(bias_u)
            if bias_entry != upd:
                cfg["biasUpdater"] = bias_entry
        entry: Dict[str, object] = {"layer": {t: cfg}}
        if i == 0:
            entry["seed"] = int(g.seed)
        confs.append(entry)

    pre, permute = _walk_boundaries(conf)

    doc: Dict[str, object] = {"backprop": True, "confs": confs,
                              # 1.0-era MultiLayerConfiguration counters:
                              # Adam/Nadam bias correction needs the step
                              # count to resume identically
                              "iterationCount": int(net.iteration),
                              "epochCount": int(net.epoch)}
    if conf.backprop_type == "truncated_bptt":
        doc["backpropType"] = "TruncatedBPTT"
        doc["tbpttFwdLength"] = int(conf.tbptt_fwd_length)
        doc["tbpttBackLength"] = int(conf.tbptt_bwd_length)
    else:
        doc["backpropType"] = "Standard"
    if pre:
        doc["inputPreProcessors"] = pre

    _write_model_zip(net, path, doc, permute, save_updater, normalizer)


def _flatten_params(net, permute) -> np.ndarray:
    """Flattened parameter vector in DL4J layout order — ``_layer_seq``
    yields MLN layer indices or graph vertex names as container keys."""
    segments: List[np.ndarray] = []
    for key, layer in _layer_seq(net.conf):
        for name, _shape, order, _convert, target in _dl4j_param_specs(layer):
            container = (net.params[key] if target == "param"
                         else net.states[key])
            if name not in container:
                raise UnsupportedDl4jConfigurationException(
                    f"layer {key!r} has no value for expected param {name!r}")
            segments.append(_export_value(layer, key, name, order,
                                          container, permute))
    return (np.concatenate(segments) if segments
            else np.zeros(0, np.float32)).reshape(1, -1)


def _write_model_zip(net, path, doc, permute, save_updater,
                     normalizer=None) -> None:
    """Shared ModelSerializer-zip epilogue for both network kinds."""
    flat = _flatten_params(net, permute)
    upd_flat = _updater_state_vector(net, permute) if save_updater else None
    norm_bytes = None
    if normalizer is not None:
        from deeplearning4j_tpu.modelimport.normalizer_serde import (
            normalizer_to_bytes)
        norm_bytes = normalizer_to_bytes(normalizer)
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("configuration.json", json.dumps(doc, indent=1))
        z.writestr("coefficients.bin", nd4j_array_to_bytes(flat, order="c"))
        if upd_flat is not None and upd_flat.size:
            z.writestr("updaterState.bin",
                       nd4j_array_to_bytes(upd_flat.reshape(1, -1),
                                           order="c"))
        if norm_bytes is not None:
            # ModelSerializer.java:165-168 — normalizer as additional entry
            z.writestr("normalizer.bin", norm_bytes)


# ---------------------------------------------------------------------------
# ComputationGraph export (ModelSerializer.writeModel's graph half)

def _vertex_entry(v) -> Tuple[str, dict]:
    """Inverse of ``dl4j._convert_dl4j_vertex``: our vertex object → the
    DL4J WRAPPER_OBJECT (type name, cfg). Vertex kinds with no DL4J
    spelling (non-identity PreprocessorVertex, MoE routing, …) raise."""
    from deeplearning4j_tpu.nn import vertices as V

    if isinstance(v, V.MergeVertex):
        return "MergeVertex", {}
    if isinstance(v, V.ElementWiseVertex):
        # canonical DL4J Op enum names — the runtime also accepts aliases
        # ('sum'/'mul'/…) that must not leak into the wire format
        canon = {"add": "Add", "sum": "Add", "subtract": "Subtract",
                 "sub": "Subtract", "product": "Product", "prod": "Product",
                 "mul": "Product", "average": "Average", "avg": "Average",
                 "max": "Max"}
        op = canon.get(str(v.op).lower())
        if op is None:
            raise UnsupportedDl4jConfigurationException(
                f"cannot express ElementWiseVertex op {v.op!r} in the DL4J "
                "dialect")
        return "ElementWiseVertex", {"op": op}
    if isinstance(v, V.SubsetVertex):
        return "SubsetVertex", {"from": int(v.from_index),
                                "to": int(v.to_index)}
    if isinstance(v, V.StackVertex):
        return "StackVertex", {}
    if isinstance(v, V.UnstackVertex):
        return "UnstackVertex", {"from": int(v.from_index),
                                 "stackSize": int(v.stack_size)}
    if isinstance(v, V.ScaleVertex):
        return "ScaleVertex", {"scaleFactor": float(v.scale_factor)}
    if isinstance(v, V.ShiftVertex):
        return "ShiftVertex", {"shiftFactor": float(v.shift_factor)}
    if isinstance(v, V.L2NormalizeVertex):
        return "L2NormalizeVertex", {}
    if isinstance(v, V.L2Vertex):
        return "L2Vertex", {}
    if isinstance(v, V.LastTimeStepVertex):
        return "LastTimeStepVertex", {"maskArrayInputName": v.mask_input}
    if isinstance(v, V.ReverseTimeSeriesVertex):
        return "ReverseTimeSeriesVertex", {"maskArrayInputName": v.mask_input}
    if isinstance(v, V.DuplicateToTimeSeriesVertex):
        return "DuplicateToTimeSeriesVertex", {"inputName": v.ts_input}
    raise UnsupportedDl4jConfigurationException(
        f"cannot express graph vertex {type(v).__name__} in the DL4J "
        "dialect")


def _cnn_to_ff_entry(it) -> Tuple[dict, tuple]:
    """The ONE wire spelling of the conv→dense flatten boundary, shared by
    the MLN (`_walk_boundaries`) and graph (`_graph_boundaries`) walkers:
    (cnnToFeedForward entry, NHWC→NCHW dense-W permutation key)."""
    return ({"cnnToFeedForward": {
        "inputHeight": it.height, "inputWidth": it.width,
        "numChannels": it.channels}},
        (it.height, it.width, it.channels))


def _graph_boundaries(conf) -> Tuple[Dict[str, dict], Dict[str, tuple]]:
    """(LayerVertex ``preProcessor`` entries, dense-W permutation map) for
    every automatic layout preprocessor the graph build registered — the
    graph twin of ``_walk_boundaries``, carried INSIDE LayerVertex like
    DL4J does (``LayerVertex.java:45``). A conv→dense flatten emits
    ``cnnToFeedForward`` (with our NHWC rows re-indexed to its NCHW
    feature order); any other registered boundary (cnn_flat inputs,
    cnn_seq reshapes into recurrent layers, cnn3d, …) has no
    round-trippable spelling and raises loudly.

    A conf that came THROUGH the importer carries the original DL4J
    entries instead of input types (``_dl4j_layer_preprocessors``); those
    re-emit verbatim and WITHOUT the weight permutation — the imported
    model's dense rows already index NCHW features."""
    pre: Dict[str, dict] = {}
    permute: Dict[str, tuple] = {}
    imported = getattr(conf, "_dl4j_layer_preprocessors", {}) or {}
    for name in getattr(conf, "preprocessors", {}) or {}:
        if name in imported:
            pre[name] = imported[name]
            continue
        vd = conf.vertices.get(name)
        its = conf.vertex_input_types.get(name, [])
        it = its[0] if its else None
        cls = type(vd.obj).__name__ if vd is not None and vd.is_layer else None
        if cls in _FF_NATURED and it is not None and it.kind == "cnn":
            pre[name], permute[name] = _cnn_to_ff_entry(it)
            continue
        raise UnsupportedDl4jConfigurationException(
            f"graph vertex {name!r} carries an input preprocessor with no "
            "DL4J round-trip spelling (only the conv→dense "
            "CnnToFeedForward boundary is supported) — restructure with a "
            "GlobalPoolingLayer, or export as MultiLayerNetwork")
    return pre, permute


def export_computation_graph(net, path: str,
                             save_updater: bool = True,
                             normalizer=None) -> None:
    """Write a ComputationGraph as a DL4J-format zip (configuration.json
    in the ComputationGraphConfiguration dialect + coefficients.bin in
    DL4J's OWN topological parameter order + updaterState.bin);
    re-importable via ``restore_computation_graph``
    (``ModelSerializer.java:51`` writeModel, graph case —
    ``ComputationGraphConfiguration.java:62-90`` vertices/vertexInputs/
    networkInputs/networkOutputs).

    The flattened parameter vector follows the same
    ``topologicalSortOrder()`` emulation the reader uses
    (``dl4j._dl4j_topological_order``), so branchy DAGs lay out
    deterministically on both sides."""
    conf = net.conf
    g = conf.global_conf
    pre_entries, permute = _graph_boundaries(conf)

    default_updater = _updater_entry(g.updater) or {
        "@class": "org.nd4j.linalg.learning.config.Sgd",
        "learningRate": 0.1}

    vertices: Dict[str, dict] = {}
    vertex_inputs: Dict[str, list] = {}
    for name, vd in conf.vertices.items():
        if vd.is_layer:
            upd = _updater_entry(vd.obj.updater) or default_updater
            t, cfg = _layer_entry(vd.obj, upd)
            bias_u = getattr(vd.obj, "bias_updater", None) or g.bias_updater
            if bias_u is not None:
                bias_entry = _updater_entry(bias_u)
                if bias_entry != upd:
                    cfg["biasUpdater"] = bias_entry
            lv = {"layerConf": {"layer": {t: cfg}}}
            if name in pre_entries:
                lv["preProcessor"] = pre_entries[name]
            vertices[name] = {"LayerVertex": lv}
        else:
            vt, vc = _vertex_entry(vd.obj)
            vertices[name] = {vt: vc}
        vertex_inputs[name] = list(vd.inputs)

    doc: Dict[str, object] = {
        "vertices": vertices,
        "vertexInputs": vertex_inputs,
        "networkInputs": list(conf.inputs),
        "networkOutputs": list(conf.outputs),
        "iterationCount": int(net.iteration),
        "epochCount": int(net.epoch),
    }
    from deeplearning4j_tpu.nn.conf.network import normalize_backprop_type
    if normalize_backprop_type(conf.backprop_type) == "truncated_bptt":
        doc["backpropType"] = "TruncatedBPTT"
        doc["tbpttFwdLength"] = int(conf.tbptt_fwd_length)
        doc["tbpttBackLength"] = int(conf.tbptt_bwd_length)
    else:
        doc["backpropType"] = "Standard"

    # flattened params in DL4J's topological layer order (same walk the
    # reader's _iter_param_slices does), with conv→dense boundary weights
    # re-indexed to the NCHW feature order the emitted preprocessor implies
    _write_model_zip(net, path, doc, permute, save_updater, normalizer)
