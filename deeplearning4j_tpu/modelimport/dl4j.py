"""DL4J configuration import — migration path from reference checkpoints.

Reads the reference's ``MultiLayerConfiguration.toJson()`` format (Jackson,
``nn/conf/MultiLayerConfiguration.java:57-63`` top-level fields; layer
subtype names from the ``@JsonSubTypes`` registry in
``nn/conf/layers/Layer.java:54-86``; per-layer fields from ``BaseLayer.java:
42-54`` / ``FeedForwardLayer.java:21-22`` / ``ConvolutionLayer.java:35-37``)
and builds the equivalent config here. ``ModelSerializer`` zips
(``util/ModelSerializer.java:120-125``: ``configuration.json`` +
``coefficients.bin`` + ``updaterState.bin``) restore FULLY via
:func:`restore_multi_layer_network` — the flattened ND4J parameter vector is
parsed by ``nd4j_binary.py`` and mapped onto the param pytree (DL4J
ParamInitializer order, 'f' weight order, conv OIHW→HWIO), and the updater
state is rebuilt for uniform updater configs.

The parser is deliberately tolerant about field spellings ("nin"/"nIn",
activation as enum string or ``@class`` wrapper) — the same posture as the
reference's own legacy deserializers (``nn/conf/serde/``), because real DL4J
JSON varies across 0.6-1.0 versions.
"""

from __future__ import annotations

import json
import zipfile
from typing import Any, Dict, Optional

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration


class InvalidDl4jConfigurationException(ValueError):
    pass


class UnsupportedDl4jConfigurationException(ValueError):
    pass


def _get(d: dict, *names, default=None):
    for n in names:
        if n in d:
            return d[n]
    return default


# -- activation / loss / updater / weight-init vocabulary -------------------

_ACTIVATIONS = {
    "relu": "relu", "relu6": "relu6", "sigmoid": "sigmoid", "tanh": "tanh",
    "tanh.": "tanh", "softmax": "softmax", "identity": "identity",
    "softplus": "softplus", "softsign": "softsign", "elu": "elu",
    "selu": "selu", "cube": "cube", "hardsigmoid": "hardsigmoid",
    "hardtanh": "hardtanh", "leakyrelu": "leakyrelu", "lrelu": "leakyrelu",
    "rationaltanh": "rationaltanh", "swish": "swish", "gelu": "gelu",
    "rrelu": "leakyrelu", "thresholdedrelu": "thresholdedrelu",
}


def _activation(v):
    """activationFn: enum string ("RELU"), {"@class": ".ActivationReLU"},
    or WRAPPER_OBJECT {"ReLU": {...}}. Parameterized activations
    (ActivationLReLU/RReLU/ELU with an ``alpha`` field) come back as
    ``(name, {"alpha": …})`` tuples so the coefficient is preserved."""
    if v is None:
        return None
    params: dict = {}
    if isinstance(v, str):
        key = v.lower()
    elif isinstance(v, dict):
        cls = v.get("@class")
        if cls is not None:
            params = v
        elif len(v) == 1:
            cls = next(iter(v))
            if isinstance(v[cls], dict):
                params = v[cls]
        if cls is None:
            return None
        key = cls.rsplit(".", 1)[-1]
        if key.lower().startswith("activation"):
            key = key[len("Activation"):]
        key = key.lower()
    else:
        return None
    key = key.replace("_", "")
    if key not in _ACTIVATIONS:
        raise UnsupportedDl4jConfigurationException(
            f"unknown DL4J activation {v!r}")
    mapped = _ACTIVATIONS[key]
    if mapped in ("leakyrelu", "elu") and "alpha" in params:
        return (mapped, {"alpha": float(params["alpha"])})
    if mapped == "thresholdedrelu" and "theta" in params:
        return (mapped, {"theta": float(params["theta"])})
    return mapped


_LOSSES = {
    "mcxent": "mcxent", "negativeloglikelihood": "mcxent", "mse": "mse",
    "l2": "mse", "binaryxent": "xent", "xent": "xent", "mae": "l1",
    "l1": "l1", "kld": "kld", "kldivergence": "kld", "poisson": "poisson",
    "cosineproximity": "cosine_proximity", "hinge": "hinge",
    "squaredhinge": "squared_hinge", "meansquaredlogarithmicerror": "msle",
}


def _loss(v) -> Optional[str]:
    if v is None:
        return None
    if isinstance(v, str):
        key = v.lower()
    elif isinstance(v, dict):
        cls = v.get("@class")
        if cls is None and len(v) == 1:
            cls = next(iter(v))
        if cls is None:
            return None
        key = cls.rsplit(".", 1)[-1]
        if key.lower().startswith("loss"):
            key = key[len("Loss"):]
        key = key.lower()
    else:
        return None
    key = key.replace("_", "")
    if key not in _LOSSES:
        raise UnsupportedDl4jConfigurationException(f"unknown DL4J loss {v!r}")
    return _LOSSES[key]


def _updater(v):
    """iUpdater: {"@class": "org.nd4j.linalg.learning.config.Adam", ...}."""
    from deeplearning4j_tpu.nn import updaters as U
    if v is None or not isinstance(v, dict):
        return None
    cls = v.get("@class")
    if cls is None and len(v) == 1:
        cls, v = next(iter(v.items()))
    if cls is None:
        return None
    name = cls.rsplit(".", 1)[-1].lower()
    lr = _get(v, "learningRate", "lr", default=None)
    kw: Dict[str, Any] = {}
    if lr is not None:
        kw["learning_rate"] = float(lr)
    table = {
        "sgd": U.Sgd, "adam": U.Adam, "adamax": U.AdaMax,
        "adadelta": U.AdaDelta, "adagrad": U.AdaGrad, "nadam": U.Nadam,
        "nesterovs": U.Nesterovs, "rmsprop": U.RmsProp, "noop": U.NoOp,
    }
    if name not in table:
        raise UnsupportedDl4jConfigurationException(
            f"unknown DL4J updater {cls!r}")
    if name == "nesterovs" and "momentum" in v:
        kw["momentum"] = float(v["momentum"])
    if name in ("adam", "adamax", "nadam"):
        if "beta1" in v:
            kw["beta1"] = float(v["beta1"])
        if "beta2" in v:
            kw["beta2"] = float(v["beta2"])
    if name == "rmsprop" and "rmsDecay" in v:
        kw["rms_decay"] = float(v["rmsDecay"])
    try:
        return table[name](**kw)
    except TypeError:
        kw.pop("learning_rate", None)
        return table[name](**kw)


def _weight_init(v) -> Optional[str]:
    return None if v is None else str(v).lower()


def _legacy_updater(cfg: dict, name: Optional[str] = None):
    """Pre-0.9 dialect: the layer carries an ``updater`` ENUM string plus
    flat hyperparameter fields (``learningRate``, ``momentum``,
    ``rmsDecay``, ``rho``, ``adamMeanDecay``/``adamVarDecay``) — the exact
    shape the reference's legacy deserializers convert to IUpdater
    (exercised by ``regressiontest/RegressionTest050.java`` …080)."""
    from deeplearning4j_tpu.nn import updaters as U

    name = name if name is not None else cfg.get("updater")
    if not isinstance(name, str):
        return None
    name = name.lower()
    lr = _get(cfg, "learningRate", "lr")
    kw: Dict[str, Any] = {}
    if lr is not None:
        kw["learning_rate"] = float(lr)
    if name == "nesterovs":
        if "momentum" in cfg:
            kw["momentum"] = float(cfg["momentum"])
        return U.Nesterovs(**kw)
    if name == "rmsprop":
        if "rmsDecay" in cfg:
            kw["rms_decay"] = float(cfg["rmsDecay"])
        return U.RmsProp(**kw)
    if name == "adam":
        if "adamMeanDecay" in cfg:
            kw["beta1"] = float(cfg["adamMeanDecay"])
        if "adamVarDecay" in cfg:
            kw["beta2"] = float(cfg["adamVarDecay"])
        return U.Adam(**kw)
    if name == "adadelta":
        kw.pop("learning_rate", None)
        return U.AdaDelta(rho=float(cfg.get("rho", 0.95)))
    if name == "adagrad":
        return U.AdaGrad(**kw)
    if name == "adamax":
        if "adamMeanDecay" in cfg:
            kw["beta1"] = float(cfg["adamMeanDecay"])
        if "adamVarDecay" in cfg:
            kw["beta2"] = float(cfg["adamVarDecay"])
        return U.AdaMax(**kw)
    if name == "nadam":
        return U.Nadam(**kw)
    if name == "sgd":
        return U.Sgd(**kw)
    if name == "none":
        return U.NoOp()  # Updater.NONE freezes the params (NoOp IUpdater)
    if name == "custom":
        return None
    raise UnsupportedDl4jConfigurationException(
        f"unknown legacy DL4J updater enum {cfg.get('updater')!r}")


def _distribution(v):
    """``dist`` field: legacy WRAPPER_OBJECT (``{"normal": {"mean": …}}``)
    or ``@class``-tagged (``{"@class": "….NormalDistribution", …}``)."""
    from deeplearning4j_tpu.nn.weights import Distribution

    if not isinstance(v, dict):
        return None
    if "@class" in v:
        kind = v["@class"].rsplit(".", 1)[-1]
        kind = kind[:-len("Distribution")] if kind.endswith("Distribution") else kind
        cfg = v
    elif len(v) == 1:
        kind, cfg = next(iter(v.items()))
        cfg = cfg or {}
    else:
        return None
    kind = kind.lower()
    if kind == "normal" or kind == "gaussian":
        return Distribution(kind="normal", mean=float(cfg.get("mean", 0.0)),
                            std=float(_get(cfg, "std", "standardDeviation",
                                           default=1.0)))
    if kind == "uniform":
        return Distribution(kind="uniform", lower=float(cfg.get("lower", -1.0)),
                            upper=float(cfg.get("upper", 1.0)))
    if kind == "binomial":
        return Distribution(
            kind="binomial",
            n=int(_get(cfg, "numberOfTrials", "n", default=1)),
            p=float(_get(cfg, "probabilityOfSuccess", "p", default=0.5)))
    if kind in ("truncatednormal", "truncated_normal"):
        return Distribution(kind="truncated_normal",
                            mean=float(cfg.get("mean", 0.0)),
                            std=float(_get(cfg, "std", "standardDeviation",
                                           default=1.0)))
    if kind in ("lognormal", "log_normal"):
        return Distribution(kind="log_normal",
                            mean=float(cfg.get("mean", 0.0)),
                            std=float(_get(cfg, "std", "standardDeviation",
                                           default=1.0)))
    if kind == "orthogonal":
        return Distribution(kind="orthogonal",
                            gain=float(cfg.get("gain", 1.0)))
    if kind == "constant":
        return Distribution(kind="constant",
                            value=float(cfg.get("value", 0.0)))
    raise UnsupportedDl4jConfigurationException(
        f"unknown DL4J distribution {v!r}")


def _constraints(v, conv: bool = False):
    """DL4J serialized per-layer ``constraints`` list → our LayerConstraint
    chain (``BaseConstraint.java:18``: Jackson ``@class`` entries carrying
    ``params``/``epsilon``/``dimensions`` + subclass fields). The four
    reference classes map 1:1 onto ``nn/constraints.py``.

    DL4J ``dimensions`` are reduction axes over DL4J's param layouts
    ([nIn,nOut] dense, [out,in,kH,kW] conv); the canonical per-unit choices
    ([1] for 2D, [1,2,3] for conv — ``MaxNormConstraint.java:33``) both
    correspond to this framework's default (all-but-last over [n_in,n_out] /
    HWIO). Non-canonical dimension sets import with a warning and the
    default axes rather than silently dropping the constraint."""
    if not isinstance(v, list) or not v:
        return None
    from deeplearning4j_tpu.nn import constraints as C

    out = []
    for entry in v:
        if not isinstance(entry, dict):
            continue
        short = entry.get("@class", "").rsplit(".", 1)[-1]
        dims = entry.get("dimensions")
        canonical = [1, 2, 3] if conv else [1]
        if dims is not None and list(dims) != canonical:
            import warnings
            warnings.warn(
                f"DL4J constraint {short} has non-canonical dimensions "
                f"{list(dims)}; importing with this framework's default "
                "(per-output-unit) reduction axes", stacklevel=3)
        names = tuple(entry.get("params") or ()) or None
        common = dict(param_names=names, dimensions=None)
        if short == "MaxNormConstraint":
            out.append(C.MaxNormConstraint(
                max_norm=float(entry.get("maxNorm", 1.0)), **common))
        elif short == "MinMaxNormConstraint":
            out.append(C.MinMaxNormConstraint(
                min_norm=float(entry.get("min", 0.0)),
                max_norm=float(entry.get("max", 1.0)),
                rate=float(entry.get("rate", 1.0)), **common))
        elif short == "UnitNormConstraint":
            out.append(C.UnitNormConstraint(**common))
        elif short == "NonNegativeConstraint":
            out.append(C.NonNegativeConstraint(**common))
        else:
            import warnings
            warnings.warn(
                f"ignoring unsupported DL4J constraint {short!r} — the "
                "imported model loses this train-time projection",
                stacklevel=3)
    return out or None


# -- per-layer conversion ----------------------------------------------------

def _base_kwargs(cfg: dict, conv: bool = False) -> dict:
    """Fields shared by BaseLayer subclasses. ``conv`` flags layers whose
    weights are 4-D in DL4J ([out,in,kH,kW]) so the canonical constraint
    ``dimensions`` are [1,2,3] rather than [1]."""
    kw: Dict[str, Any] = {}
    name = _get(cfg, "layerName", "layername")
    if name:
        kw["name"] = name
    act = _activation(_get(cfg, "activationFn", "activationFunction",
                           "activation"))
    if act is not None:
        if act == "leakyrelu" and "leakyreluAlpha" in cfg:
            # pre-0.8 dialect: alpha rides the layer, not the activation
            kw["activation"] = ("leakyrelu",
                                {"alpha": float(cfg["leakyreluAlpha"])})
        else:
            kw["activation"] = act  # str, or (name, params) tuple
    wi = _weight_init(_get(cfg, "weightInit", "weightinit"))
    if wi == "distribution":
        dist = _distribution(cfg.get("dist"))
        if dist is not None:
            kw["weight_init"] = "distribution"
            kw["distribution"] = dist
    elif wi:
        kw["weight_init"] = wi
    for src, dst in (("l1", "l1"), ("l2", "l2")):
        val = cfg.get(src)
        if isinstance(val, (int, float)) and val == val and val != 0.0:
            kw[dst] = float(val)
    drop = _get(cfg, "dropOut", "dropout")
    if isinstance(drop, (int, float)) and 0.0 < float(drop) < 1.0:
        # pre-1.0 dropOut double == Dropout retain probability, ours too
        kw["dropout"] = float(drop)
    idrop = _get(cfg, "iDropout", "idropout")
    if isinstance(idrop, dict):
        from deeplearning4j_tpu.nn import dropout as D
        cls = idrop.get("@class", "")
        short = cls.rsplit(".", 1)[-1]
        if short == "Dropout" and "p" in idrop:
            kw["dropout"] = float(idrop["p"])
        elif short == "AlphaDropout" and "p" in idrop:
            kw["dropout"] = D.AlphaDropout(p=float(idrop["p"]))
        elif short == "GaussianDropout" and "rate" in idrop:
            kw["dropout"] = D.GaussianDropout(rate=float(idrop["rate"]))
        elif short == "GaussianNoise" and "stddev" in idrop:
            kw["dropout"] = D.GaussianNoise(stddev=float(idrop["stddev"]))
        elif short == "SpatialDropout" and "p" in idrop:
            kw["dropout"] = D.SpatialDropout(p=float(idrop["p"]))
        else:
            import warnings
            warnings.warn(
                f"ignoring unsupported DL4J iDropout {cls!r} — training "
                "regularization of the imported model is dropped",
                stacklevel=2)
    cons = _constraints(cfg.get("constraints"), conv=conv)
    if cons:
        kw["constraints"] = cons
    upd_v = _get(cfg, "iUpdater", "iupdater", "updater")
    upd = (_legacy_updater(cfg, upd_v) if isinstance(upd_v, str)
           else _updater(upd_v))
    if upd is not None:
        kw["updater"] = upd
    # per-layer bias updater override (BaseLayer.java biasUpdater) — this
    # shifts UpdaterBlock boundaries, so dropping it would corrupt the
    # updaterState.bin mapping
    bias_upd = _updater(_get(cfg, "biasUpdater", "biasupdater"))
    if bias_upd is not None:
        kw["bias_updater"] = bias_upd
    gn = _get(cfg, "gradientNormalization")
    if gn and gn != "None":
        snake = "".join(("_" + c.lower() if c.isupper() else c)
                        for c in gn).lstrip("_")
        kw["gradient_normalization"] = snake
        thr = _get(cfg, "gradientNormalizationThreshold")
        if thr is not None:
            kw["gradient_normalization_threshold"] = float(thr)
    return kw


def _nin_nout(cfg: dict) -> dict:
    out = {}
    nin = _get(cfg, "nin", "nIn", "nIn_")
    nout = _get(cfg, "nout", "nOut")
    if nin:
        out["n_in"] = int(nin)
    if nout:
        out["n_out"] = int(nout)
    return out


def _pair(v, default=(1, 1)):
    if v is None:
        return default
    if isinstance(v, int):
        return (v, v)
    return tuple(int(x) for x in v[:2]) if len(v) >= 2 else (int(v[0]),) * 2


def _conv_mode(v) -> str:
    return {"Same": "same", "Truncate": "truncate",
            "Strict": "strict"}.get(v, "truncate")


def convert_dl4j_layer(type_name: str, cfg: dict):
    """One WRAPPER_OBJECT layer entry {type_name: cfg} → our Layer."""
    from deeplearning4j_tpu.nn import layers as L

    t = type_name
    base = _base_kwargs(cfg, conv=t in ("convolution", "deconvolution2d",
                                        "separableConvolution2d",
                                        "depthwiseConvolution2d"))
    ff = _nin_nout(cfg)

    if t == "dense":
        return L.DenseLayer(**base, **ff,
                            has_bias=bool(_get(cfg, "hasBias", default=True)))
    if t in ("output", "rnnoutput", "CenterLossOutputLayer"):
        loss = _loss(_get(cfg, "lossFn", "lossFunction"))
        cls = {"output": L.OutputLayer, "rnnoutput": L.RnnOutputLayer,
               "CenterLossOutputLayer": L.CenterLossOutputLayer}[t]
        kw = dict(base, **ff)
        if loss:
            kw["loss"] = loss
        if t == "CenterLossOutputLayer":
            if "alpha" in cfg:
                kw["alpha"] = float(cfg["alpha"])
            if "lambda" in cfg:
                kw["lambda_"] = float(cfg["lambda"])
        return cls(**kw)
    if t in ("loss", "RnnLossLayer", "CnnLossLayer"):
        loss = _loss(_get(cfg, "lossFn", "lossFunction")) or "mse"
        cls = {"loss": L.LossLayer, "RnnLossLayer": L.LossLayer,
               "CnnLossLayer": L.CnnLossLayer}[t]
        return cls(**base, loss=loss)
    if t in ("convolution", "convolution1d"):
        kw = dict(base, **ff,
                  kernel_size=_pair(_get(cfg, "kernelSize"), (3, 3)),
                  stride=_pair(_get(cfg, "stride"), (1, 1)),
                  padding=_pair(_get(cfg, "padding"), (0, 0)),
                  dilation=_pair(_get(cfg, "dilation"), (1, 1)),
                  convolution_mode=_conv_mode(_get(cfg, "convolutionMode")))
        cls = L.Convolution1DLayer if t == "convolution1d" else L.ConvolutionLayer
        if t == "convolution1d":
            kw["kernel_size"] = kw["kernel_size"][0]
            kw["stride"] = kw["stride"][0]
        return cls(**kw)
    if t in ("subsampling", "subsampling1d"):
        pt = str(_get(cfg, "poolingType", default="MAX")).lower()
        kw = dict(base,
                  pooling_type="avg" if pt in ("avg", "average") else pt,
                  kernel_size=_pair(_get(cfg, "kernelSize"), (2, 2)),
                  stride=_pair(_get(cfg, "stride"), (2, 2)),
                  padding=_pair(_get(cfg, "padding"), (0, 0)),
                  convolution_mode=_conv_mode(_get(cfg, "convolutionMode")))
        return (L.Subsampling1DLayer if t == "subsampling1d"
                else L.SubsamplingLayer)(**kw)
    if t == "batchNormalization":
        kw = dict(base)
        if "eps" in cfg:
            kw["eps"] = float(cfg["eps"])
        if "decay" in cfg:
            kw["decay"] = float(cfg["decay"])
        if cfg.get("lockGammaBeta"):
            # locked gamma/beta carry NO params in the DL4J vector — must be
            # mirrored or every later slice shifts during ingestion
            kw["lock_gamma_beta"] = True
        n = _get(cfg, "nin", "nIn", "nout", "nOut")
        if n:
            kw["n_in"] = int(n)
        return L.BatchNormalizationLayer(**kw)
    if t == "localResponseNormalization":
        kw = dict(base)
        for f in ("k", "n", "alpha", "beta"):
            if f in cfg:
                kw[f] = cfg[f]
        return L.LocalResponseNormalizationLayer(**kw)
    if t == "embedding":
        return L.EmbeddingLayer(**base, **ff,
                                has_bias=bool(_get(cfg, "hasBias",
                                                   default=False)))
    if t == "activation":
        return L.ActivationLayer(**base)
    if t == "dropout":
        return L.DropoutLayer(**base)
    if t == "LSTM":
        return L.LSTMLayer(**base, **ff, forget_gate_bias_init=float(
            _get(cfg, "forgetGateBiasInit", default=1.0)))
    if t == "gravesLSTM":
        return L.GravesLSTMLayer(**base, **ff, forget_gate_bias_init=float(
            _get(cfg, "forgetGateBiasInit", default=1.0)))
    if t == "gravesBidirectionalLSTM":
        return L.GravesBidirectionalLSTMLayer(**base, **ff,
                                              forget_gate_bias_init=float(
            _get(cfg, "forgetGateBiasInit", default=1.0)))
    if t == "SimpleRnn":
        return L.SimpleRnnLayer(**base, **ff)
    if t == "GlobalPooling":
        pt = str(_get(cfg, "poolingType", default="MAX")).lower()
        return L.GlobalPoolingLayer(
            **base, pooling_type="avg" if pt in ("avg", "average") else pt)
    if t == "zeroPadding":
        return L.ZeroPaddingLayer(**base,
                                  padding=tuple(_get(cfg, "padding", default=(1, 1, 1, 1))))
    if t == "Upsampling2D":
        s = _get(cfg, "size", default=2)
        return L.UpsamplingLayer(**base, size=_pair(s, (2, 2)))
    if t == "autoEncoder":
        kw = dict(base, **ff)
        if "corruptionLevel" in cfg:
            kw["corruption_level"] = float(cfg["corruptionLevel"])
        return L.AutoEncoderLayer(**kw)
    if t == "ElementWiseMult":
        return L.ElementWiseMultiplicationLayer(**base, **ff)
    if t == "MaskZeroLayer":
        inner_t, inner_cfg = next(iter(_get(cfg, "underlying", default={}).items()))
        return L.MaskZeroLayer(layer=convert_dl4j_layer(inner_t, inner_cfg),
                               mask_value=float(_get(cfg, "maskingValue",
                                                     default=0.0)))
    if t == "Bidirectional":
        mode = str(_get(cfg, "mode", default="CONCAT")).lower()
        inner = _get(cfg, "fwd", "rnnLayer", default=None)
        if inner is None:
            raise InvalidDl4jConfigurationException(
                "Bidirectional layer without inner rnn config")
        inner_t, inner_cfg = next(iter(inner.items()))
        return L.BidirectionalWrapper(
            layer=convert_dl4j_layer(inner_t, inner_cfg),
            mode={"add": "add", "mul": "mul", "average": "average",
                  "concat": "concat"}.get(mode, "concat"))
    if t == "FrozenLayer":
        inner = _get(cfg, "layer", default=None)
        if isinstance(inner, dict) and len(inner) == 1:
            inner_t, inner_cfg = next(iter(inner.items()))
            return L.FrozenLayer(layer=convert_dl4j_layer(inner_t, inner_cfg))
        raise InvalidDl4jConfigurationException("FrozenLayer without inner layer")
    raise UnsupportedDl4jConfigurationException(
        f"unsupported DL4J layer type {t!r}")


# -- top-level ---------------------------------------------------------------

def import_dl4j_configuration(source: str):
    """DL4J ``MultiLayerConfiguration`` JSON (string or dict) → our config."""
    d = json.loads(source) if isinstance(source, str) else source
    confs = d.get("confs")
    if confs is None:
        raise InvalidDl4jConfigurationException(
            "not a MultiLayerConfiguration JSON (no 'confs')")

    b = NeuralNetConfiguration.builder()
    first = confs[0] if confs else {}
    if "seed" in first:
        b.seed(int(first["seed"]))
    lb = b.list()
    for conf in confs:
        layer_entry = conf.get("layer")
        if not isinstance(layer_entry, dict) or len(layer_entry) != 1:
            raise InvalidDl4jConfigurationException(
                f"bad layer entry {layer_entry!r}")
        t, cfg = next(iter(layer_entry.items()))
        lb.layer(convert_dl4j_layer(t, cfg))

    bp = d.get("backpropType")
    if bp == "TruncatedBPTT":
        fwd = int(d.get("tbpttFwdLength", 20))
        lb.t_bptt_length(fwd, int(d.get("tbpttBackLength", fwd)))
    built = lb.build()
    # 1.0-era training counters (absent in 0.9.x zips): carried so a
    # resumed Adam/Nadam keeps its bias-correction step count
    built._dl4j_counters = (int(d.get("iterationCount", 0)),
                            int(d.get("epochCount", 0)))
    for k, v in (d.get("inputPreProcessors") or {}).items():
        fn = _convert_dl4j_preprocessor(v)
        if fn is not None:
            built.preprocessors[int(k)] = fn
    return built


def _convert_dl4j_preprocessor(entry):
    """One ``inputPreProcessors`` entry → activation fn (or None = identity).

    Accepts both serde dialects: WRAPPER_OBJECT ``{"cnnToFeedForward":
    {...}}`` and 1.0-era ``{"@class": "...CnnToFeedForwardPreProcessor",
    ...}``. DL4J flattens CNN activations in NCHW order
    (``CnnToFeedForwardPreProcessor.java``), so the dense weights of an
    imported checkpoint index features as c·H·W + h·W + w — the transposes
    below preserve that indexing over our NHWC activations.
    Rnn↔FeedForward preprocessors are identity here: dense layers apply
    position-wise over [N,T,C] natively. Unknown preprocessor types degrade
    to a warning + identity so config-only import keeps working (the
    reference's tolerant serde posture).
    """
    if isinstance(entry, dict) and "@class" in entry:
        t, cfg = entry["@class"], entry
    elif isinstance(entry, dict) and len(entry) == 1:
        t, cfg = next(iter(entry.items()))
    else:
        raise InvalidDl4jConfigurationException(
            f"bad inputPreProcessors entry {entry!r}")
    cfg = cfg or {}
    t = t[1:] if t.startswith(".") else t
    name = t.rsplit(".", 1)[-1]
    key = name[0].lower() + name[1:]
    if key in ("cnnToFeedForwardPreProcessor", "cnnToFeedForward"):
        return lambda x: x.transpose(0, 3, 1, 2).reshape(x.shape[0], -1)
    if key in ("feedForwardToCnnPreProcessor", "feedForwardToCnn"):
        h = int(_get(cfg, "inputHeight", "numRows"))
        w = int(_get(cfg, "inputWidth", "numColumns"))
        c = int(_get(cfg, "numChannels", "depth", default=1))
        return lambda x: x.reshape(x.shape[0], c, h, w).transpose(0, 2, 3, 1)
    if key in ("cnnToRnnPreProcessor", "cnnToRnn"):
        # per-step NCHW-order flatten: [N,T,H,W,C] → [N,T,C·H·W]
        return lambda x: x.transpose(0, 1, 4, 2, 3).reshape(
            x.shape[0], x.shape[1], -1)
    if key in ("rnnToCnnPreProcessor", "rnnToCnn"):
        h = int(_get(cfg, "inputHeight", "numRows"))
        w = int(_get(cfg, "inputWidth", "numColumns"))
        c = int(_get(cfg, "numChannels", "depth", default=1))
        return lambda x: x.reshape(
            x.shape[0], x.shape[1], c, h, w).transpose(0, 1, 3, 4, 2)
    if key in ("rnnToFeedForwardPreProcessor", "rnnToFeedForward",
               "feedForwardToRnnPreProcessor", "feedForwardToRnn"):
        return None  # position-wise application makes these identity here
    import warnings
    warnings.warn(
        f"ignoring unsupported DL4J input preprocessor {t!r} (identity); "
        "verify the imported network's activations if this index mattered",
        stacklevel=2)
    return None


def _convert_dl4j_vertex(type_name: str, cfg: dict):
    """One WRAPPER_OBJECT vertex entry {type_name: cfg} → our vertex or, for
    LayerVertex, the converted Layer (``nn/conf/graph/GraphVertex.java:41-53``
    subtype names)."""
    from deeplearning4j_tpu.nn import vertices as V

    t = type_name
    if t == "LayerVertex":
        layer_conf = _get(cfg, "layerConf", default={}) or {}
        layer_entry = layer_conf.get("layer")
        if not isinstance(layer_entry, dict) or len(layer_entry) != 1:
            raise InvalidDl4jConfigurationException(
                f"LayerVertex without layer config: {cfg!r}")
        lt, lc = next(iter(layer_entry.items()))
        return convert_dl4j_layer(lt, lc)
    if t == "MergeVertex":
        return V.MergeVertex()
    if t == "ElementWiseVertex":
        op = str(_get(cfg, "op", default="Add")).lower()
        return V.ElementWiseVertex(op={"max": "max"}.get(op, op))
    if t == "SubsetVertex":
        return V.SubsetVertex(from_index=int(_get(cfg, "from", "from_", default=0)),
                              to_index=int(_get(cfg, "to", default=0)))
    if t == "StackVertex":
        return V.StackVertex()
    if t == "UnstackVertex":
        return V.UnstackVertex(from_index=int(_get(cfg, "from", "from_", default=0)),
                               stack_size=int(_get(cfg, "stackSize", default=1)))
    if t == "ScaleVertex":
        return V.ScaleVertex(scale_factor=float(_get(cfg, "scaleFactor", default=1.0)))
    if t == "ShiftVertex":
        return V.ShiftVertex(shift_factor=float(_get(cfg, "shiftFactor", default=0.0)))
    if t == "L2Vertex":
        return V.L2Vertex()
    if t == "L2NormalizeVertex":
        return V.L2NormalizeVertex()
    if t == "LastTimeStepVertex":
        return V.LastTimeStepVertex(mask_input=_get(cfg, "maskArrayInputName"))
    if t == "ReverseTimeSeriesVertex":
        return V.ReverseTimeSeriesVertex(mask_input=_get(cfg, "maskArrayInputName"))
    if t == "DuplicateToTimeSeriesVertex":
        return V.DuplicateToTimeSeriesVertex(
            ts_input=_get(cfg, "inputName", "inputVertexName"))
    if t == "PreprocessorVertex":
        return V.PreprocessorVertex(preprocessor="identity")
    raise UnsupportedDl4jConfigurationException(
        f"unsupported DL4J graph vertex type {t!r}")


def import_dl4j_graph_configuration(source: str):
    """DL4J ``ComputationGraphConfiguration`` JSON → our graph config
    (``nn/conf/ComputationGraphConfiguration.java:62-90``: vertices +
    vertexInputs maps, networkInputs/networkOutputs)."""
    from deeplearning4j_tpu.nn.layers.base import Layer

    d = json.loads(source) if isinstance(source, str) else source
    vertices = d.get("vertices")
    if vertices is None:
        raise InvalidDl4jConfigurationException(
            "not a ComputationGraphConfiguration JSON (no 'vertices')")
    vertex_inputs = d.get("vertexInputs") or {}
    inputs = d.get("networkInputs") or []
    outputs = d.get("networkOutputs") or []

    g = NeuralNetConfiguration.builder().graph_builder()
    g.add_inputs(*inputs)
    layer_pre: Dict[str, object] = {}
    layer_pre_raw: Dict[str, dict] = {}
    for name, entry in vertices.items():
        if not isinstance(entry, dict) or len(entry) != 1:
            raise InvalidDl4jConfigurationException(f"bad vertex {name!r}")
        vt, vc = next(iter(entry.items()))
        vc = vc or {}
        obj = _convert_dl4j_vertex(vt, vc)
        srcs = vertex_inputs.get(name, [])
        if isinstance(obj, Layer):
            # LayerVertex.java:45 carries an input preprocessor — dropping
            # it would silently mis-shape e.g. a conv→dense flatten
            pp = vc.get("preProcessor")
            if pp is not None:
                fn = _convert_dl4j_preprocessor(pp)
                if fn is not None:
                    layer_pre[name] = fn
                    # kept verbatim so a restored graph RE-exports the same
                    # boundary (its weights already index DL4J's order)
                    layer_pre_raw[name] = pp
            g.add_layer(name, obj, *srcs)
        else:
            g.add_vertex(name, obj, *srcs)
    g.set_outputs(*outputs)
    if d.get("backpropType") == "TruncatedBPTT":
        fwd = int(d.get("tbpttFwdLength", 20))
        g.t_bptt_length(fwd, int(d.get("tbpttBackLength", fwd)))
    built = g.build()
    # LayerVertex preprocessors override/install AFTER build (no input
    # types in the DL4J graph dialect, so build inferred none)
    built.preprocessors.update(layer_pre)
    built._dl4j_layer_preprocessors = layer_pre_raw
    # 1.0-era training counters, like the MLN path: a resumed Adam/Nadam
    # needs its bias-correction step count
    built._dl4j_counters = (int(d.get("iterationCount", 0)),
                            int(d.get("epochCount", 0)))
    return built


def _read_zip_configuration(z: "zipfile.ZipFile", path: str) -> dict:
    """Shared ModelSerializer-zip prologue: validate + parse the JSON."""
    names = set(z.namelist())
    if "configuration.json" not in names:
        raise InvalidDl4jConfigurationException(
            f"{path}: no configuration.json in zip (entries: {sorted(names)})")
    return json.loads(z.read("configuration.json").decode("utf-8"))


def import_dl4j_zip(path: str):
    """ModelSerializer zip → (config, metadata). For parameter ingestion
    use :func:`restore_multi_layer_network`. When the zip carries a
    ``normalizer.bin`` (``ModelSerializer.java:40``), the parsed normalizer
    object rides along as ``meta["normalizer"]``."""
    from deeplearning4j_tpu.modelimport.normalizer_serde import (
        normalizer_from_bytes)

    with zipfile.ZipFile(path) as z:
        names = set(z.namelist())
        raw = _read_zip_configuration(z, path)
        conf = (import_dl4j_graph_configuration(raw) if "vertices" in raw
                else import_dl4j_configuration(raw))
        meta = {"has_coefficients": "coefficients.bin" in names,
                "has_updater_state": "updaterState.bin" in names,
                "has_normalizer": "normalizer.bin" in names,
                "normalizer": None}
        if meta["has_normalizer"]:
            # a CUSTOM-strategy / pre-0.9 / corrupt normalizer must not
            # fail the MODEL import — the reference's restore path never
            # touches normalizer.bin either; record the reason instead
            try:
                meta["normalizer"] = normalizer_from_bytes(
                    z.read("normalizer.bin"))
            except Exception as e:  # incl. BadZipFile on a bit-rotted entry
                meta["normalizer_error"] = f"{type(e).__name__}: {e}"
    return conf, meta


def restore_normalizer(path: str):
    """``ModelSerializer.restoreNormalizerFromFile`` parity
    (``util/ModelSerializer.java:707``): parse the zip's ``normalizer.bin``
    into a fitted :class:`~deeplearning4j_tpu.datasets.normalizers.Normalizer`.
    Returns None when the zip has no normalizer entry (the reference returns
    null there too)."""
    from deeplearning4j_tpu.modelimport.normalizer_serde import (
        normalizer_from_bytes)

    with zipfile.ZipFile(path) as z:
        if "normalizer.bin" not in set(z.namelist()):
            return None
        return normalizer_from_bytes(z.read("normalizer.bin"))


def add_normalizer_to_model(path: str, normalizer) -> None:
    """``ModelSerializer.addNormalizerToModel`` parity
    (``util/ModelSerializer.java:654``): rewrite the zip with every entry
    except any existing ``normalizer.bin`` (``:670`` skips it,
    case-insensitively), then append the serialized normalizer as a fresh
    entry (``:682-686``)."""
    from deeplearning4j_tpu.modelimport.normalizer_serde import (
        normalizer_to_bytes)
    from deeplearning4j_tpu.util.model_serializer import replace_zip_entry

    replace_zip_entry(path, "normalizer.bin", normalizer_to_bytes(normalizer))


def restore_multi_layer_network_configuration(path: str):
    """Zip → fresh MultiLayerNetwork built from the reference config
    (the configuration half of ``ModelSerializer.restoreMultiLayerNetwork``,
    ``util/ModelSerializer.java:182``)."""
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    conf, _ = import_dl4j_zip(path)
    return MultiLayerNetwork(conf)


# ---- coefficients.bin parameter ingestion ---------------------------------
#
# ``ModelSerializer.restoreMultiLayerNetwork`` (``util/ModelSerializer.java:
# 182``) restores configuration AND the flattened ``coefficients.bin``
# parameter vector (+ ``updaterState.bin``). The vector is the network's
# single flattened param buffer (``MultiLayerNetwork.init():549``), laid out
# layer by layer in each layer's ParamInitializer order, with each weight
# matrix stored in DL4J's default weight order 'f'
# (``WeightInitUtil.DEFAULT_WEIGHT_INIT_ORDER``) except conv kernels, whose
# initializer reshapes with 'c' ([nOut, nIn, kH, kW]).

def _dl4j_param_specs(layer):
    """Ordered flattened-view slices for one layer: each spec is
    ``(name, dl4j_shape, memory_order, convert, target)`` with target
    "param" or "state". Empty list = layer holds no parameters."""
    import numpy as np

    cls = type(layer).__name__
    shapes = layer.param_shapes()

    def ravel(a):
        return np.ascontiguousarray(a).reshape(-1)

    def ident(a):
        return np.ascontiguousarray(a)

    if cls == "BatchNormalizationLayer":
        # BatchNormalizationParamInitializer order: gamma, beta, mean, var
        n = layer.n_in
        specs = []
        if "gamma" in shapes:
            specs += [("gamma", (1, n), "c", ravel, "param"),
                      ("beta", (1, n), "c", ravel, "param")]
        specs += [("mean", (1, n), "c", ravel, "state"),
                  ("var", (1, n), "c", ravel, "state")]
        return specs
    if not shapes:
        return []
    if cls == "ConvolutionLayer":
        # ConvolutionParamInitializer: W [nOut, nIn, kH, kW] 'c' → our HWIO
        kh, kw = layer.kernel_size
        specs = [("W", (layer.n_out, layer.n_in, kh, kw), "c",
                  lambda a: np.transpose(a, (2, 3, 1, 0)), "param")]
        if "b" in shapes:
            specs.append(("b", (1, layer.n_out), "c", ravel, "param"))
        return specs
    if cls == "GravesBidirectionalLSTMLayer":
        # GravesBidirectionalLSTMParamInitializer order: WF, RWF, bF then
        # WB, RWB, bB — our wrapper stores them as f_/b_-prefixed leaves
        specs = []
        for pre in ("f_", "b_"):
            specs += [(pre + "W", shapes[pre + "W"], "f", ident, "param"),
                      (pre + "RW", shapes[pre + "RW"], "f", ident, "param"),
                      (pre + "b", (1, shapes[pre + "b"][0]), "c", ravel,
                       "param")]
        return specs
    if cls in ("LSTMLayer", "GravesLSTMLayer", "SimpleRnnLayer", "GRULayer"):
        # LSTMParamInitializer order: W [nIn, 4H], RW [H, 4H(+3 peephole
        # cols for Graves — our layout already matches)], b; IFOG gate order
        # is shared (LSTMHelpers.java layout, see nn/layers/recurrent.py)
        specs = [("W", shapes["W"], "f", ident, "param"),
                 ("RW", shapes["RW"], "f", ident, "param")]
        if "b" in shapes:
            specs.append(("b", (1, shapes["b"][0]), "c", ravel, "param"))
        return specs
    if set(shapes) <= {"W", "b"} and len(shapes.get("W", (0, 0))) == 2:
        # dense family (Dense/Output/Embedding/ElementWiseMult):
        # DefaultParamInitializer, weights reshaped 'f'
        specs = [("W", shapes["W"], "f", ident, "param")]
        if "b" in shapes:
            specs.append(("b", (1, shapes["b"][0]), "c", ravel, "param"))
        return specs
    raise UnsupportedDl4jConfigurationException(
        f"coefficients.bin ingestion does not support layer type {cls} "
        f"(params {sorted(shapes)}); restore the configuration only via "
        "restore_multi_layer_network_configuration")


def _java_int_set_iter(elems):
    """Iteration order of a ``java.util.HashSet<Integer>`` populated by
    ``add()`` in ``elems`` order: buckets ascend (Integer hash is the value;
    HashMap's spread ``h ^ h>>>16`` is the identity below 2^16), entries
    within a bucket keep insertion order (Java 8 appends to the tail, and
    resize splits preserve relative order). Capacity starts at 16 and
    doubles whenever size exceeds 0.75 * capacity."""
    cap = 16
    while len(elems) > 0.75 * cap:
        cap *= 2
    buckets = {}
    for e in elems:
        h = e ^ (e >> 16)
        buckets.setdefault(h & (cap - 1), []).append(e)
    out = []
    for b in sorted(buckets):
        out.extend(buckets[b])
    return out


def _dl4j_topological_order(conf, java_set_order: bool = True):
    """Replicate ``ComputationGraph.topologicalSortOrder()``
    (``ComputationGraph.java:1211``) exactly: Kahn's algorithm over vertex
    INDICES (networkInputs in order, then vertices in serialization order),
    a FIFO work queue, and successor processing in Java HashSet<Integer>
    iteration order. The initial queue ascends by index because
    ``inputEdges`` is a ``HashMap<Integer, ...>`` whose keys 0..n-1 all land
    in their own buckets (capacity > n after resize).

    ``java_set_order=False`` runs the same sort with plain ascending
    successor order — used to detect the (rare, >16-vertex fan-out) cases
    where the bucket-order emulation is the only thing pinning the result.
    """
    names = list(conf.inputs) + list(conf.vertices)
    idx = {n: i for i, n in enumerate(names)}
    input_edges = {}
    output_elems = {}
    for n in conf.inputs:
        input_edges[idx[n]] = set()
    for name, vd in conf.vertices.items():
        i = idx[name]
        srcs = list(vd.inputs)
        if not srcs:
            input_edges[i] = set()
            continue
        s = set()
        for src in srcs:
            j = idx[src]
            s.add(j)
            lst = output_elems.setdefault(j, [])
            if i not in lst:
                lst.append(i)
        input_edges[i] = s
    queue = [i for i in sorted(input_edges) if not input_edges[i]]
    out = []
    while queue:
        nxt = queue.pop(0)
        out.append(nxt)
        succs = output_elems.get(nxt, [])
        succs = (_java_int_set_iter(succs) if java_set_order
                 else sorted(succs))
        for v in succs:
            input_edges[v].discard(nxt)
            if not input_edges[v]:
                queue.append(v)
    if len(out) != len(names):
        raise InvalidDl4jConfigurationException("graph contains a cycle")
    return [names[i] for i in out]


def _graph_layer_order(conf):
    """LAYER vertices in the order DL4J's ``ComputationGraph.init``
    allocates flattened param views (its topological order filtered to
    layer vertices, ``ComputationGraph.java:467-470``)."""
    order = _dl4j_topological_order(conf)
    return [n for n in order
            if n in conf.vertices and conf.vertices[n].is_layer]


def _layer_seq(conf):
    """Uniform (key, layer) sequence for both network kinds: MLN confs walk
    ``layers`` by index; graph confs walk LAYER vertices in DL4J's OWN
    topological order (``_dl4j_topological_order`` — exact
    ``topologicalSortOrder()`` emulation, deterministic for branchy
    graphs), the order ``ComputationGraph.init`` allocates its flattened
    param views in (``ComputationGraph.java:467-470``)."""
    if hasattr(conf, "layers"):
        return list(enumerate(conf.layers))
    return [(n, conf.vertices[n].obj) for n in _graph_layer_order(conf)]


def _iter_param_slices(conf, flat):
    """Yield (layer_key, name, target, converted_array) walking the
    flattened vector in DL4J layout order."""
    import numpy as np

    pos = 0
    flat = np.asarray(flat).reshape(-1)
    for i, layer in _layer_seq(conf):
        for name, dl4j_shape, order, convert, target in _dl4j_param_specs(layer):
            n = int(np.prod(dl4j_shape))
            seg = flat[pos:pos + n]
            if seg.size != n:
                raise InvalidDl4jConfigurationException(
                    f"coefficients.bin too short: layer {i} param {name!r} "
                    f"wants {n} values at offset {pos}, only {seg.size} left")
            pos += n
            arr = seg.reshape(dl4j_shape,
                              order="F" if order == "f" else "C")
            yield i, name, target, convert(arr)
    if pos != flat.size:
        raise InvalidDl4jConfigurationException(
            f"coefficients.bin length mismatch: consumed {pos} of "
            f"{flat.size} values — layer inventory disagrees with the "
            "checkpoint")


def _copy_container(c):
    """Shallow-copy a param container: MLN list-of-dicts or graph
    name-keyed dict-of-dicts (both index the same way downstream)."""
    if isinstance(c, dict):
        return {k: dict(v) for k, v in c.items()}
    return [dict(x) for x in c]


def apply_coefficients(net, flat) -> None:
    """Map a DL4J flattened parameter vector onto an initialized
    MultiLayerNetwork or ComputationGraph (params + BN running stats)."""
    import jax.numpy as jnp

    dtype = net.conf.global_conf.jnp_dtype()
    params = _copy_container(net.params)
    states = _copy_container(net.states)
    for i, name, target, arr in _iter_param_slices(net.conf, flat):
        dest = params[i] if target == "param" else states[i]
        if name in dest and tuple(dest[name].shape) != tuple(arr.shape):
            raise InvalidDl4jConfigurationException(
                f"layer {i} param {name!r}: checkpoint shape {arr.shape} vs "
                f"model shape {tuple(dest[name].shape)}")
        # running stats keep their initialized dtype (BN pins them to f32
        # regardless of the global dtype — see nn/layers/norm.py)
        dt = dest[name].dtype if name in dest else dtype
        dest[name] = jnp.asarray(arr, dt)
    net.params = params
    net.states = states


# DL4J GradientUpdater state-view subdivision order → our state keys
_UPDATER_STATE_SLOTS = {
    "Adam": ("m", "v"), "AdaMax": ("m", "u"), "Nadam": ("m", "v"),
    "AMSGrad": ("m", "v", "v_hat"), "Nesterovs": ("v",), "RmsProp": ("g2",),
    "AdaGrad": ("h",), "AdaDelta": ("eg2", "edx2"), "Sgd": (), "NoOp": (),
}


def _updater_blocks(conf, updaters):
    """DL4J ``UpdaterBlock`` boundaries over the flattened layout
    (``BaseMultiLayerUpdater.java:92``): trainable params coalesce into
    contiguous blocks, SPLIT wherever (a) a non-trainable run (BatchNorm
    global mean/var, which DL4J pairs with a stateless NoOp pseudo-updater)
    interrupts them, or (b) adjacent params' updater CONFIGS differ
    (``UpdaterUtils.updaterConfigurationsEquals``: full equality incl. LR
    and schedules — our frozen-dataclass ``==`` is exactly that test).
    Yields ``(updater, [(layer_key, name, dl4j_shape, order, convert), …])``
    per block. (DL4J additionally never coalesces pretrain params across
    layers; no pretrain-param layer type is in the restore scope here.)"""
    blocks, current, cur_u = [], [], None
    for i, layer in _layer_seq(conf):
        for name, dl4j_shape, order, convert, target in _dl4j_param_specs(layer):
            if target != "param":
                if current:
                    blocks.append((cur_u, current))
                    current, cur_u = [], None
                continue
            u = updaters[i][name]
            if current and u != cur_u:
                blocks.append((cur_u, current))
                current = []
            cur_u = u
            current.append((i, name, dl4j_shape, order, convert))
    if current:
        blocks.append((cur_u, current))
    return blocks


def apply_updater_state(net, flat) -> bool:
    """Map a DL4J ``updaterState.bin`` vector onto the net's updater states.

    DL4J groups contiguous same-config params into ``UpdaterBlock``s and the
    state view is each block's ``[slot0(block), slot1(block), …]`` segment
    concatenated in flattened param order (``BaseMultiLayerUpdater.java:55``,
    per-updater slot layout e.g. ``AdamUpdater.setStateViewArray``).
    Heterogeneous configs (per-layer learning rates, bias updaters) are
    handled by splitting blocks at every config change, exactly as DL4J
    does. Returns False (state left freshly initialized) only when some
    updater class has no known slot layout."""
    import numpy as np
    import jax.numpy as jnp

    flat = np.asarray(flat).reshape(-1)
    blocks = _updater_blocks(net.conf, net._updaters)
    if any(type(u).__name__ not in _UPDATER_STATE_SLOTS for u, _ in blocks):
        return False
    want = sum(len(_UPDATER_STATE_SLOTS[type(u).__name__])
               * int(np.prod(shape))
               for u, b in blocks for (_, _, shape, _, _) in b)
    if want == 0:
        return flat.size == 0
    if flat.size != want:
        raise InvalidDl4jConfigurationException(
            f"updaterState.bin length {flat.size} != expected {want} "
            "(per-block updater slots over the trainable params)")
    dtype = net.conf.global_conf.jnp_dtype()
    new_states = _copy_container(net.updater_states)
    pos = 0
    for u, block in blocks:
        for slot in _UPDATER_STATE_SLOTS[type(u).__name__]:
            at = pos
            for i, name, dl4j_shape, order, convert in block:
                n = int(np.prod(dl4j_shape))
                arr = flat[at:at + n].reshape(
                    dl4j_shape, order="F" if order == "f" else "C")
                at += n
                new_states[i][name] = {**new_states[i][name],
                                       slot: jnp.asarray(convert(arr), dtype)}
            pos = at  # next slot (or next block) starts right after
        # next block starts right after this block's last slot
    net.updater_states = new_states
    return True


def restore_multi_layer_network(path: str, load_params: bool = True,
                                load_updater: bool = True):
    """Full ``ModelSerializer.restoreMultiLayerNetwork`` parity
    (``util/ModelSerializer.java:182``): configuration + flattened
    ``coefficients.bin`` parameters (+ ``updaterState.bin`` when present and
    the updater configuration is uniform). Returns an initialized
    MultiLayerNetwork carrying the checkpoint's weights."""
    from deeplearning4j_tpu.modelimport.nd4j_binary import (
        read_nd4j_array_from_bytes)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    with zipfile.ZipFile(path) as z:
        names = set(z.namelist())
        raw = _read_zip_configuration(z, path)
        if "vertices" in raw:
            raise UnsupportedDl4jConfigurationException(
                "restore_multi_layer_network is for MultiLayerNetwork zips; "
                "this is a ComputationGraph configuration")
        conf = import_dl4j_configuration(raw)
        net = MultiLayerNetwork(conf).init()
        if load_params and "coefficients.bin" in names:
            coeff = read_nd4j_array_from_bytes(z.read("coefficients.bin"))
            apply_coefficients(net, coeff)
        counters = getattr(net.conf, "_dl4j_counters", None)
        if counters is not None:
            net.iteration, net.epoch = counters
        if (load_params and load_updater and "updaterState.bin" in names):
            upd = read_nd4j_array_from_bytes(z.read("updaterState.bin"))
            apply_updater_state(net, upd)
    return net


def _layer_order_is_forced(conf, order) -> bool:
    """True when every consecutive pair of layer vertices in ``order`` is
    connected by a dependency path — then EVERY topological sort yields the
    same layer sequence and the coefficient mapping is unambiguous."""
    inputs = {name: set(vd.inputs) for name, vd in conf.vertices.items()}

    def reaches(src, dst):  # dst depends (transitively) on src?
        stack, seen = [dst], set()
        while stack:
            cur = stack.pop()
            if cur == src:
                return True
            if cur in seen:
                continue
            seen.add(cur)
            stack.extend(inputs.get(cur, ()))
        return False

    return all(reaches(a, b) for a, b in zip(order, order[1:]))


def restore_computation_graph(path: str, load_params: bool = True,
                              load_updater: bool = True):
    """``ModelSerializer.restoreComputationGraph`` parity
    (``util/ModelSerializer.java:389``): graph configuration + flattened
    parameters (+ updater state for uniform updater configs). Parameter
    layout follows the topological order of layer vertices, the order
    ``ComputationGraph.init`` allocates its flattened views in."""
    from deeplearning4j_tpu.modelimport.nd4j_binary import (
        read_nd4j_array_from_bytes)
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    with zipfile.ZipFile(path) as z:
        names = set(z.namelist())
        raw = _read_zip_configuration(z, path)
        if "vertices" not in raw:
            raise UnsupportedDl4jConfigurationException(
                "restore_computation_graph is for ComputationGraph zips; "
                "this is a MultiLayerNetwork configuration — use "
                "restore_multi_layer_network")
        conf = import_dl4j_graph_configuration(raw)
        net = ComputationGraph(conf).init()
        # coefficients follow DL4J's topologicalSortOrder, which
        # _dl4j_topological_order replicates exactly (FIFO Kahn over vertex
        # indices + Java HashSet successor iteration), so branchy graphs map
        # deterministically. The one residual assumption is the Java
        # HashSet BUCKET order for fan-out sets holding indices >= 16; warn
        # iff that assumption is the only thing pinning the layer order.
        if load_params and "coefficients.bin" in names:
            emulated = _graph_layer_order(conf)
            plain = [n for n in _dl4j_topological_order(
                conf, java_set_order=False)
                if n in conf.vertices and conf.vertices[n].is_layer]
            if emulated != plain:
                import warnings
                warnings.warn(
                    "graph layer order depends on Java HashSet bucket-order "
                    "emulation for >=16-way vertex indices "
                    f"({emulated} vs ascending {plain}); verify restored "
                    "outputs against known activations", stacklevel=2)
            coeff = read_nd4j_array_from_bytes(z.read("coefficients.bin"))
            apply_coefficients(net, coeff)
        counters = getattr(net.conf, "_dl4j_counters", None)
        if counters is not None:
            net.iteration, net.epoch = counters
        if (load_params and load_updater and "updaterState.bin" in names):
            upd = read_nd4j_array_from_bytes(z.read("updaterState.bin"))
            apply_updater_state(net, upd)
    return net
