"""Keras layer-config → framework layer mapping (Keras 1 and Keras 2 dialects).

Reference: ``deeplearning4j-modelimport/.../layers/`` (per-family mappers) and
``config/Keras1LayerConfiguration.java`` / ``Keras2LayerConfiguration.java``
(the two field-name dialects: ``output_dim``/``nb_filter``/``border_mode``/
``subsample`` vs ``units``/``filters``/``padding``/``strides``).

Each mapper returns ``(layer, weight_fn)`` where ``weight_fn(raw)`` converts
the layer's Keras weight dict to ``(params, states)`` for our layer. Arrays
stay in Keras file order (kernels are HWIO, matching our NHWC convs) — no
transposes needed except the LSTM gate reorder (Keras IFCO → ours IFOG).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.nn.layers import (
    ActivationLayer,
    BatchNormalizationLayer,
    Convolution1DLayer,
    ConvolutionLayer,
    DenseLayer,
    DepthwiseConvolution2DLayer,
    DropoutLayer,
    EmbeddingSequenceLayer,
    GlobalPoolingLayer,
    LSTMLayer,
    SeparableConvolution2DLayer,
    SimpleRnnLayer,
    Subsampling1DLayer,
    SubsamplingLayer,
    Upsampling1DLayer,
    UpsamplingLayer,
    ZeroPadding1DLayer,
    ZeroPaddingLayer,
)
from deeplearning4j_tpu.nn.layers.base import Layer
from deeplearning4j_tpu.nn.layers.recurrent import BidirectionalWrapper, LastTimeStepWrapper

WeightFn = Callable[[Dict[str, np.ndarray]], Tuple[dict, dict]]

# Keras activation name → ours
ACTIVATIONS = {
    "linear": "identity", "relu": "relu", "relu6": "relu6",
    "sigmoid": "sigmoid", "hard_sigmoid": "hardsigmoid", "tanh": "tanh",
    "softmax": "softmax", "softplus": "softplus", "softsign": "softsign",
    "elu": "elu", "selu": "selu", "swish": "swish", "silu": "swish",
    "gelu": "gelu", "exponential": "exp", "leaky_relu": "leakyrelu",
}

# Keras loss name → ours
LOSSES = {
    "categorical_crossentropy": "mcxent",
    "sparse_categorical_crossentropy": "mcxent",
    "binary_crossentropy": "xent",
    "mean_squared_error": "mse", "mse": "mse",
    "mean_absolute_error": "l1", "mae": "l1",
    "kullback_leibler_divergence": "kld", "kld": "kld",
    "poisson": "poisson",
    "cosine_proximity": "cosine_proximity",
    "hinge": "hinge", "squared_hinge": "squared_hinge",
}


def map_activation(name: Optional[str]) -> str:
    if not name:
        return "identity"
    key = str(name).lower()
    if key not in ACTIVATIONS:
        raise UnsupportedKerasConfigurationException(
            f"Unsupported Keras activation {name!r}")
    return ACTIVATIONS[key]


class InvalidKerasConfigurationException(ValueError):
    """Reference: ``exceptions/InvalidKerasConfigurationException.java``."""


# user-registered Lambda layer implementations, keyed by Keras layer name
# (reference: ``KerasLayer.registerLambdaLayer(name, SameDiffLambdaLayer)``)
_LAMBDA_REGISTRY: Dict[str, object] = {}


def register_lambda_layer(name: str, impl) -> None:
    """Register the implementation for a Keras ``Lambda`` layer by its layer
    name, to be picked up at import time. ``impl`` is either a framework
    ``Layer`` or a plain ``fn(x) -> y`` (wrapped in a SameDiffLambdaLayer —
    the same pairing the reference uses)."""
    _LAMBDA_REGISTRY[name] = impl


def clear_lambda_layers() -> None:
    _LAMBDA_REGISTRY.clear()


class UnsupportedKerasConfigurationException(ValueError):
    """Reference: ``exceptions/UnsupportedKerasConfigurationException.java``."""


def _pair(v, default=(1, 1)):
    if v is None:
        return default
    if isinstance(v, int):
        return (v, v)
    return tuple(int(x) for x in v)


def _no_weights(raw):
    return {}, {}


def _dense_weights(raw):
    p = {}
    if "kernel" in raw:
        p["W"] = raw["kernel"]
    elif "W" in raw:
        p["W"] = raw["W"]
    else:  # Keras1 flat names like "dense_1_W"
        for k, v in raw.items():
            if k.endswith("_W") or v.ndim >= 2:
                p["W"] = v
            elif k.endswith("_b") or v.ndim == 1:
                p["b"] = v
    if "bias" in raw:
        p["b"] = raw["bias"]
    elif "b" in raw:
        p["b"] = raw["b"]
    return p, {}


def _bn_weights(raw):
    get = lambda *names: next((raw[n] for n in names if n in raw), None)
    p, s = {}, {}
    gamma = get("gamma")
    beta = get("beta")
    mean = get("moving_mean", "running_mean")
    var = get("moving_variance", "running_std", "running_var")
    if gamma is None or beta is None or mean is None or var is None:
        # Keras1 flat names: <layer>_gamma etc.
        for k, v in raw.items():
            if k.endswith("_gamma"):
                gamma = v
            elif k.endswith("_beta"):
                beta = v
            elif k.endswith("_running_mean"):
                mean = v
            elif k.endswith(("_running_std", "_running_var")):
                var = v
    if gamma is not None:
        p["gamma"] = gamma
    if beta is not None:
        p["beta"] = beta
    if mean is not None:
        s["mean"] = mean
    if var is not None:
        s["var"] = var
    return p, s


def _lstm_reorder(k: np.ndarray, units: int) -> np.ndarray:
    """Keras gate blocks [i|f|c|o] → our [i|f|o|g] along the last axis."""
    i, f, c, o = (k[..., j * units:(j + 1) * units] for j in range(4))
    return np.concatenate([i, f, o, c], axis=-1)


def _lstm_weights_fn(units: int) -> WeightFn:
    def fn(raw):
        get = lambda *names: next((raw[n] for n in names if n in raw), None)
        k = get("kernel", "W")
        rk = get("recurrent_kernel", "U")
        b = get("bias", "b")
        if k is None:
            # Keras1 per-gate names: W_i, W_f, W_c, W_o / U_* / b_*
            def cat(prefix):
                gates = [raw.get(f"{prefix}_{g}") for g in ("i", "f", "o", "c")]
                if any(g is None for g in gates):
                    # also try flat <layer>_W_i style
                    gates = [next((v for n, v in raw.items()
                                   if n.endswith(f"{prefix}_{g}")), None)
                             for g in ("i", "f", "o", "c")]
                if any(g is None for g in gates):
                    return None
                return np.concatenate(gates, axis=-1)
            k_ifog, rk_ifog, b_ifog = cat("W"), cat("U"), cat("b")
            if k_ifog is None:
                raise InvalidKerasConfigurationException(
                    f"cannot locate LSTM weights among {sorted(raw)}")
            return {"W": k_ifog, "RW": rk_ifog, "b": b_ifog}, {}
        p = {"W": _lstm_reorder(k, units), "RW": _lstm_reorder(rk, units)}
        if b is not None:
            if b.ndim == 2:  # CuDNN-style split bias rows
                b = b.sum(axis=0)
            p["b"] = _lstm_reorder(b, units)
        return p, {}
    return fn


def _rnn_weights(raw):
    get = lambda *names: next((raw[n] for n in names if n in raw), None)
    p = {}
    k = get("kernel", "W")
    rk = get("recurrent_kernel", "U")
    b = get("bias", "b")
    if k is None:
        for n, v in raw.items():
            if n.endswith("_W"):
                k = v
            elif n.endswith("_U"):
                rk = v
            elif n.endswith("_b"):
                b = v
    if k is not None:
        p["W"] = k
    if rk is not None:
        p["RW"] = rk
    if b is not None:
        p["b"] = b
    return p, {}


def _embedding_weights(raw):
    get = lambda *names: next((raw[n] for n in names if n in raw), None)
    w = get("embeddings", "W")
    if w is None:
        w = next((v for n, v in raw.items() if v.ndim == 2), None)
    return ({"W": w} if w is not None else {}), {}


def _conv1d_weights(raw):
    p, s = _dense_weights(raw)
    if "W" in p and p["W"].ndim == 3:  # Keras [k,in,out] -> ours [k,1,in,out]
        p["W"] = p["W"][:, None, :, :]
    return p, s


def _sepconv_weights(raw):
    get = lambda *names: next((raw[n] for n in names if n in raw), None)
    p = {}
    dk = get("depthwise_kernel")
    pk = get("pointwise_kernel")
    b = get("bias", "b")
    if dk is not None:
        p["W"] = dk
    if pk is not None:
        p["pW"] = pk
    if b is not None:
        p["b"] = b
    return p, {}


def _depthwise_weights(raw):
    get = lambda *names: next((raw[n] for n in names if n in raw), None)
    p = {}
    dk = get("depthwise_kernel")
    b = get("bias", "b")
    if dk is not None:
        p["W"] = dk
    if b is not None:
        p["b"] = b
    return p, {}


def _bidirectional_weights(inner_fn: WeightFn) -> WeightFn:
    def fn(raw):
        fwd = {k[len("forward_"):] if k.startswith("forward_") else k: v
               for k, v in raw.items() if not k.startswith("backward_")}
        bwd = {k[len("backward_"):]: v for k, v in raw.items()
               if k.startswith("backward_")}
        fp, _ = inner_fn(fwd)
        bp, _ = inner_fn(bwd)
        return ({f"f_{k}": v for k, v in fp.items()} |
                {f"b_{k}": v for k, v in bp.items()}), {}
    return fn


def _one_constraint(spec, scope: str):
    """One serialized Keras constraint → LayerConstraint (keras.constraints:
    MaxNorm/NonNeg/UnitNorm/MinMaxNorm). Keras ``axis`` is the norm's
    reduction axis — the same meaning as our ``dimensions``, and both
    frameworks share the kernel layouts (Dense [in,out], conv HWIO), so it
    maps through unchanged."""
    from deeplearning4j_tpu.nn.constraints import (
        MaxNormConstraint, MinMaxNormConstraint, NonNegativeConstraint,
        UnitNormConstraint)
    if spec is None:
        return None
    # Keras 2 nests {"class_name": ..., "config": {...}}; Keras 1 is FLAT —
    # {"name": "MaxNorm", "m": 2.0, "axis": 0} (constraints.py get_config)
    cls = spec.get("class_name") or spec.get("name", "")
    c = spec.get("config", spec if "class_name" not in spec else {})
    # keras.constraints' own default is axis=0, NOT this framework's
    # all-but-last: for conv kernels (HWIO) those differ ((0,) vs (0,1,2)),
    # so a config that omits the field must get Keras's default.
    ax = c.get("axis", 0)
    dims = None if ax is None else tuple(ax) if isinstance(ax, (list, tuple)) \
        else (int(ax),)
    if cls in ("MaxNorm", "max_norm", "maxnorm"):
        # Keras 1 spells the bound "m", Keras 2 "max_value"
        return MaxNormConstraint(
            max_norm=float(c.get("max_value", c.get("m", 2.0))),
            dimensions=dims, scope=scope)
    if cls in ("MinMaxNorm", "min_max_norm"):
        return MinMaxNormConstraint(min_norm=float(c.get("min_value", 0.0)),
                                    max_norm=float(c.get("max_value", 1.0)),
                                    rate=float(c.get("rate", 1.0)),
                                    dimensions=dims, scope=scope)
    if cls in ("NonNeg", "non_neg", "nonneg"):
        return NonNegativeConstraint(scope=scope)
    if cls in ("UnitNorm", "unit_norm", "unitnorm"):
        return UnitNormConstraint(dimensions=dims, scope=scope)
    raise UnsupportedKerasConfigurationException(
        f"Unsupported Keras constraint: {cls!r} (supported: MaxNorm, "
        "MinMaxNorm, NonNeg, UnitNorm)")


def recurrent_constraints_from_keras_cfg(cfg: dict):
    """Recurrent layers name their targets: kernel→W, recurrent_kernel→RW,
    bias→b (explicit param names rather than scopes)."""
    out = []
    for key, pnames in (("kernel_constraint", ("W",)),
                        ("W_constraint", ("W",)),
                        ("recurrent_constraint", ("RW",)),
                        ("U_constraint", ("RW",)),
                        ("bias_constraint", ("b",)),
                        ("b_constraint", ("b",))):
        c = _one_constraint(cfg.get(key), "weights")
        if c is not None:
            import dataclasses as _dc
            out.append(_dc.replace(c, param_names=pnames))
    return out or None


def constraints_from_keras_cfg(cfg: dict):
    """Map ``kernel_constraint`` / ``bias_constraint`` (and the Keras-1
    ``W_constraint`` / ``b_constraint`` spellings) to our constraint list."""
    out = []
    for key, scope in (("kernel_constraint", "weights"),
                       ("W_constraint", "weights"),
                       ("bias_constraint", "bias"),
                       ("b_constraint", "bias")):
        c = _one_constraint(cfg.get(key), scope)
        if c is not None:
            out.append(c)
    return out or None


def map_keras_layer(class_name: str, cfg: dict) -> Tuple[Optional[Layer], WeightFn]:
    """One Keras layer config → (our layer or None if structural, weight_fn).

    Returns ``(None, _no_weights)`` for layers that vanish in our model
    (Flatten — handled by dense auto-preprocessors; InputLayer).
    """
    name = cfg.get("name")
    act = map_activation(cfg.get("activation")) if "activation" in cfg else None

    if class_name in ("InputLayer", "Flatten", "Masking"):
        return None, _no_weights

    if class_name in ("Dense", "TimeDistributedDense"):
        # Keras-1 TimeDistributedDense == a position-wise Dense; our
        # DenseLayer applies position-wise over [N,T,C] already (the DL4J
        # mapping is Dense + rnn↔ff preprocessors — KerasDense.java:49)
        units = cfg.get("units", cfg.get("output_dim"))
        return DenseLayer(name=name, n_out=int(units), activation=act or "identity",
                          has_bias=cfg.get("use_bias", cfg.get("bias", True)),
                          constraints=constraints_from_keras_cfg(cfg)), _dense_weights

    if class_name in ("Conv2D", "Convolution2D"):
        filters = cfg.get("filters", cfg.get("nb_filter"))
        if "kernel_size" in cfg:
            ks = _pair(cfg["kernel_size"])
        else:
            ks = (int(cfg["nb_row"]), int(cfg["nb_col"]))
        strides = _pair(cfg.get("strides", cfg.get("subsample")), (1, 1))
        pad = cfg.get("padding", cfg.get("border_mode", "valid"))
        mode = "same" if pad == "same" else "truncate"
        return (ConvolutionLayer(name=name, n_out=int(filters), kernel_size=ks,
                                 stride=strides, convolution_mode=mode,
                                 dilation=_pair(cfg.get("dilation_rate"), (1, 1)),
                                 activation=act or "identity",
                                 has_bias=cfg.get("use_bias", cfg.get("bias", True)),
                                 constraints=constraints_from_keras_cfg(cfg)),
                _dense_weights)

    if class_name in ("Conv1D", "Convolution1D"):
        filters = cfg.get("filters", cfg.get("nb_filter"))
        k = cfg.get("kernel_size", cfg.get("filter_length"))
        k = int(k[0]) if isinstance(k, (list, tuple)) else int(k)
        s = cfg.get("strides", cfg.get("subsample_length", 1))
        s = int(s[0]) if isinstance(s, (list, tuple)) else int(s)
        pad = cfg.get("padding", cfg.get("border_mode", "valid"))
        mode = "same" if pad in ("same", "causal") else "truncate"
        return (Convolution1DLayer(name=name, n_out=int(filters),
                                   kernel_size=k, stride=s,
                                   convolution_mode=mode,
                                   activation=act or "identity",
                                   constraints=constraints_from_keras_cfg(cfg)),
                _conv1d_weights)

    if class_name == "SeparableConv2D":
        return (SeparableConvolution2DLayer(
            name=name, n_out=int(cfg.get("filters")),
            kernel_size=_pair(cfg.get("kernel_size")),
            stride=_pair(cfg.get("strides"), (1, 1)),
            depth_multiplier=int(cfg.get("depth_multiplier", 1)),
            convolution_mode="same" if cfg.get("padding") == "same" else "truncate",
            activation=act or "identity",
            constraints=constraints_from_keras_cfg(cfg)), _sepconv_weights)

    if class_name == "DepthwiseConv2D":
        return (DepthwiseConvolution2DLayer(
            name=name,
            kernel_size=_pair(cfg.get("kernel_size")),
            stride=_pair(cfg.get("strides"), (1, 1)),
            depth_multiplier=int(cfg.get("depth_multiplier", 1)),
            convolution_mode="same" if cfg.get("padding") == "same" else "truncate",
            activation=act or "identity",
            constraints=constraints_from_keras_cfg(cfg)), _depthwise_weights)

    if class_name in ("MaxPooling2D", "AveragePooling2D"):
        pt = "max" if class_name.startswith("Max") else "avg"
        ks = _pair(cfg.get("pool_size"), (2, 2))
        strides = _pair(cfg.get("strides"), ks)
        pad = cfg.get("padding", cfg.get("border_mode", "valid"))
        return (SubsamplingLayer(name=name, pooling_type=pt, kernel_size=ks,
                                 stride=strides,
                                 convolution_mode="same" if pad == "same" else "truncate"),
                _no_weights)

    if class_name in ("MaxPooling1D", "AveragePooling1D"):
        pt = "max" if class_name.startswith("Max") else "avg"
        k = cfg.get("pool_size", cfg.get("pool_length", 2))
        k = int(k[0]) if isinstance(k, (list, tuple)) else int(k)
        s = cfg.get("strides", cfg.get("stride")) or k
        s = int(s[0]) if isinstance(s, (list, tuple)) else int(s)
        return (Subsampling1DLayer(name=name, pooling_type=pt,
                                   kernel_size=(k, 1), stride=(s, 1)),
                _no_weights)

    if class_name in ("GlobalMaxPooling1D", "GlobalAveragePooling1D",
                      "GlobalMaxPooling2D", "GlobalAveragePooling2D"):
        pt = "max" if "Max" in class_name else "avg"
        return GlobalPoolingLayer(name=name, pooling_type=pt), _no_weights

    if class_name == "Dropout":
        rate = cfg.get("rate", cfg.get("p", 0.5))
        return DropoutLayer(name=name, dropout=1.0 - float(rate)), _no_weights

    if class_name in ("SpatialDropout1D", "SpatialDropout2D",
                      "SpatialDropout3D"):
        # real channel dropout (keras SpatialDropoutND → nn/dropout.py)
        from deeplearning4j_tpu.nn.dropout import SpatialDropout
        rate = float(cfg.get("rate", cfg.get("p", 0.5)))
        return (DropoutLayer(name=name, dropout=SpatialDropout(p=1.0 - rate)),
                _no_weights)

    if class_name == "GaussianDropout":
        # keras rate IS the reference's rate: noise std = sqrt(rate/(1-rate))
        from deeplearning4j_tpu.nn.dropout import GaussianDropout
        rate = float(cfg.get("rate", cfg.get("p", 0.5)))
        return (DropoutLayer(name=name, dropout=GaussianDropout(rate=rate)),
                _no_weights)

    if class_name == "GaussianNoise":
        from deeplearning4j_tpu.nn.dropout import GaussianNoise
        stddev = float(cfg.get("stddev", cfg.get("sigma", 0.1)))
        return (DropoutLayer(name=name, dropout=GaussianNoise(stddev=stddev)),
                _no_weights)

    if class_name == "AlphaDropout":
        # real SNN dropout (AlphaDropout.java:38), not a plain-dropout stand-in
        from deeplearning4j_tpu.nn.dropout import AlphaDropout
        rate = float(cfg.get("rate", cfg.get("p", 0.5)))
        return (DropoutLayer(name=name, dropout=AlphaDropout(p=1.0 - rate)),
                _no_weights)

    if class_name == "Activation":
        return ActivationLayer(name=name, activation=act or "identity"), _no_weights

    if class_name == "LeakyReLU":
        alpha = float(cfg.get("alpha", cfg.get("negative_slope", 0.3)))
        return ActivationLayer(name=name, activation=("leakyrelu", {"alpha": alpha})), _no_weights

    if class_name == "ELU":
        return ActivationLayer(name=name, activation="elu"), _no_weights

    if class_name == "ThresholdedReLU":
        theta = float(cfg.get("theta", 1.0))
        return (ActivationLayer(name=name,
                                activation=("thresholdedrelu",
                                            {"theta": theta})),
                _no_weights)

    if class_name == "BatchNormalization":
        eps = float(cfg.get("epsilon", 1e-3))
        momentum = float(cfg.get("momentum", 0.99))
        return (BatchNormalizationLayer(name=name, eps=eps, decay=momentum,
                                        activation="identity"), _bn_weights)

    if class_name == "Embedding":
        emb_cs = None
        if cfg.get("embeddings_constraint") is not None:
            import dataclasses as _dc
            c = _one_constraint(cfg["embeddings_constraint"], "weights")
            emb_cs = [_dc.replace(c, param_names=("W",))]
        return (EmbeddingSequenceLayer(name=name,
                                       n_in=int(cfg.get("input_dim")),
                                       n_out=int(cfg.get("output_dim")),
                                       activation="identity", has_bias=False,
                                       constraints=emb_cs),
                _embedding_weights)

    if class_name == "LSTM":
        units = int(cfg.get("units", cfg.get("output_dim")))
        layer = LSTMLayer(
            name=name, n_out=units,
            activation=map_activation(cfg.get("activation", "tanh")),
            gate_activation=map_activation(
                cfg.get("recurrent_activation", cfg.get("inner_activation", "sigmoid"))),
            forget_gate_bias_init=1.0 if cfg.get("unit_forget_bias", True) else 0.0,
            constraints=recurrent_constraints_from_keras_cfg(cfg))
        wf = _lstm_weights_fn(units)
        if not cfg.get("return_sequences", False):
            # LastTimeStepWrapper stores the inner layer's params unprefixed,
            # so the same weight fn applies
            return LastTimeStepWrapper(name=name, layer=layer), wf
        return layer, wf

    if class_name == "GRU":
        from deeplearning4j_tpu.nn.layers import GRULayer

        units = int(cfg.get("units", cfg.get("output_dim")))
        # Keras versions that omit the key (Keras 1 / 2.0-2.1) implement the
        # classic reset-before GRU; reset_after=True appears with Keras 2.2+
        # configs that always serialize the key
        reset_after = bool(cfg.get("reset_after", False))
        layer = GRULayer(
            name=name, n_out=units, reset_after=reset_after,
            activation=map_activation(cfg.get("activation", "tanh")),
            gate_activation=map_activation(
                cfg.get("recurrent_activation",
                        cfg.get("inner_activation", "sigmoid"))),
            constraints=recurrent_constraints_from_keras_cfg(cfg))

        def gru_weights(raw):
            # keras GRU: kernel [C, 3H] (z|r|h), recurrent_kernel [H, 3H],
            # bias [2, 3H] when reset_after else [3H]
            if "kernel" not in raw or "recurrent_kernel" not in raw:
                raise InvalidKerasConfigurationException(
                    f"cannot locate GRU weights among {sorted(raw)} "
                    "(per-gate Keras-1 GRU weight names are not supported)")
            out = {"W": raw["kernel"], "RW": raw["recurrent_kernel"]}
            if "bias" in raw:
                b = np.asarray(raw["bias"])
                if reset_after and b.ndim == 1:
                    b = b.reshape(2, -1)
                out["b"] = b
            # use_bias=False: the layer's zero-initialized bias stands
            return out, {}

        if not cfg.get("return_sequences", False):
            return (LastTimeStepWrapper(name=name, layer=layer), gru_weights)
        return layer, gru_weights

    if class_name == "TimeDistributed":
        # Position-wise inner layers (Dense/Activation/Dropout) broadcast over
        # leading dims natively, so the wrapper is transparent for them.
        # Anything spatial (Conv2D, pooling, …) gets the real rank-5 path:
        # TimeDistributedWrapper folds time into batch around the inner layer.
        # TimeDistributed(Flatten) vanishes — the cnn_seq→rnn auto-preprocessor
        # of the following layer performs the per-step flatten.
        inner_cfg = cfg.get("layer", {})
        inner_cls = inner_cfg.get("class_name")
        inner, wf = map_keras_layer(inner_cls, dict(inner_cfg.get("config", {})))
        if inner is None:
            return None, _no_weights
        inner.name = name
        if inner_cls in ("Dense", "Activation", "Dropout"):
            return inner, wf
        from deeplearning4j_tpu.nn.layers import TimeDistributedWrapper

        # the wrapper stores the inner layer's params unprefixed, so the
        # inner weight fn applies directly
        return TimeDistributedWrapper(name=name, layer=inner), wf

    if class_name == "Lambda":
        impl = _LAMBDA_REGISTRY.get(name)
        if impl is None:
            raise UnsupportedKerasConfigurationException(
                f"Lambda layer {name!r}: arbitrary serialized Python is not "
                "executed; register an implementation first with "
                "modelimport.keras.register_lambda_layer(name, impl)")
        if not isinstance(impl, Layer):
            from deeplearning4j_tpu.nn.layers import SameDiffLambdaLayer

            impl = SameDiffLambdaLayer(name=name, fn=impl)
        impl.name = name
        return impl, _no_weights

    if class_name == "ConvLSTM2D":
        filters = int(cfg.get("filters", cfg.get("nb_filter")))
        if "kernel_size" in cfg:
            ks = _pair(cfg["kernel_size"])
        else:  # Keras 1 dialect
            ks = (int(cfg["nb_row"]), int(cfg["nb_col"]))
        strides = _pair(cfg.get("strides", cfg.get("subsample")), (1, 1))
        pad = cfg.get("padding", cfg.get("border_mode", "valid"))
        if cfg.get("data_format") == "channels_first":
            raise UnsupportedKerasConfigurationException(
                "ConvLSTM2D with channels_first data_format is not supported "
                "(convert the model to channels_last)")
        from deeplearning4j_tpu.nn.layers import ConvLSTM2DLayer

        layer = ConvLSTM2DLayer(
            name=name, n_out=filters, kernel_size=ks, stride=strides,
            dilation=_pair(cfg.get("dilation_rate"), (1, 1)),
            convolution_mode="same" if pad == "same" else "truncate",
            has_bias=cfg.get("use_bias", True),
            forget_gate_bias_init=1.0 if cfg.get("unit_forget_bias", True) else 0.0,
            activation=map_activation(cfg.get("activation", "tanh")),
            gate_activation=map_activation(
                cfg.get("recurrent_activation", "hard_sigmoid")))

        def convlstm_weights(raw):
            # kernel [kh,kw,C,4F], recurrent_kernel [kh,kw,F,4F], bias [4F];
            # Keras gate blocks i|f|c|o → our i|f|o|g along the last axis
            if "kernel" not in raw or "recurrent_kernel" not in raw:
                raise InvalidKerasConfigurationException(
                    f"cannot locate ConvLSTM2D weights among {sorted(raw)}")
            p = {"W": _lstm_reorder(np.asarray(raw["kernel"]), filters),
                 "RW": _lstm_reorder(np.asarray(raw["recurrent_kernel"]), filters)}
            if "bias" in raw:
                p["b"] = _lstm_reorder(np.asarray(raw["bias"]), filters)
            return p, {}

        if not cfg.get("return_sequences", False):
            return LastTimeStepWrapper(name=name, layer=layer), convlstm_weights
        return layer, convlstm_weights

    if class_name == "SimpleRNN":
        units = int(cfg.get("units", cfg.get("output_dim")))
        layer = SimpleRnnLayer(name=name, n_out=units,
                               activation=map_activation(cfg.get("activation", "tanh")),
                               constraints=recurrent_constraints_from_keras_cfg(cfg))
        if not cfg.get("return_sequences", False):
            return LastTimeStepWrapper(name=name, layer=layer), _rnn_weights
        return layer, _rnn_weights

    if class_name == "Bidirectional":
        inner_cfg = cfg["layer"]
        inner, inner_fn = map_keras_layer(inner_cfg["class_name"],
                                          dict(inner_cfg["config"]))
        merge = cfg.get("merge_mode", "concat")
        if merge is None:
            raise UnsupportedKerasConfigurationException(
                "Bidirectional merge_mode=None (two output tensors) is not supported")
        merge = {"sum": "add", "ave": "average"}.get(merge, merge)
        if merge not in ("concat", "add", "mul", "average"):
            raise UnsupportedKerasConfigurationException(
                f"Unsupported Bidirectional merge_mode {merge!r}")
        if isinstance(inner, LastTimeStepWrapper):
            wrapped = BidirectionalWrapper(name=name, layer=inner.layer, mode=merge)
            return (LastTimeStepWrapper(name=name, layer=wrapped),
                    _bidirectional_weights(inner_fn))
        return (BidirectionalWrapper(name=name, layer=inner, mode=merge),
                _bidirectional_weights(inner_fn))

    if class_name == "ZeroPadding2D":
        pad = cfg.get("padding", (1, 1))
        if isinstance(pad, (list, tuple)) and pad and isinstance(pad[0], (list, tuple)):
            (t, b), (l, r) = pad
            return ZeroPaddingLayer(name=name, padding=(t, b, l, r)), _no_weights
        return ZeroPaddingLayer(name=name, padding=_pair(pad)), _no_weights

    if class_name == "ZeroPadding1D":
        pad = cfg.get("padding", 1)
        pad = _pair(pad, (1, 1)) if not isinstance(pad, int) else (pad, pad)
        return ZeroPadding1DLayer(name=name, padding=pad), _no_weights

    if class_name == "UpSampling2D":
        return (UpsamplingLayer(name=name, size=_pair(cfg.get("size"), (2, 2))),
                _no_weights)

    if class_name == "UpSampling1D":
        s = cfg.get("size", cfg.get("length", 2))
        s = int(s[0]) if isinstance(s, (list, tuple)) else int(s)
        return Upsampling1DLayer(name=name, size=s), _no_weights

    if class_name == "LayerNormalization":
        from deeplearning4j_tpu.nn.layers import LayerNormalizationLayer

        axis = cfg.get("axis", -1)
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        if list(axes) != [-1]:
            raise UnsupportedKerasConfigurationException(
                f"LayerNormalization over axis {axis!r} is not supported "
                "(only the last/feature axis)")

        def ln_weights(raw):
            out = {}
            if "gamma" in raw:
                out["gamma"] = raw["gamma"]
            elif "beta" in raw:  # scale=False: identity gamma
                out["gamma"] = np.ones_like(np.asarray(raw["beta"]))
            if "beta" in raw:
                out["beta"] = raw["beta"]
            elif "gamma" in raw:  # center=False: zero beta
                out["beta"] = np.zeros_like(np.asarray(raw["gamma"]))
            return out, {}

        return (LayerNormalizationLayer(name=name,
                                        eps=float(cfg.get("epsilon", 1e-3))),
                ln_weights)

    if class_name == "MultiHeadAttention":
        from deeplearning4j_tpu.nn.layers import SelfAttentionLayer

        heads = int(cfg.get("num_heads", 1))
        key_dim = int(cfg.get("key_dim", 0)) or None
        value_dim = cfg.get("value_dim")
        if value_dim is not None and int(value_dim) != (key_dim or 0):
            raise UnsupportedKerasConfigurationException(
                f"MultiHeadAttention with value_dim ({value_dim}) != key_dim "
                f"({key_dim}) is not supported")
        if cfg.get("output_shape") is not None:
            raise UnsupportedKerasConfigurationException(
                "MultiHeadAttention with an explicit output_shape is not "
                "supported (output dim must equal the model dim)")

        def mha_weights(raw):
            # keras MHA: query/key/value kernels [d_model, H, Dh] + biases
            # [H, Dh]; attention_output kernel [H, Dh, d_model] + bias
            # [d_model]. Pack into SelfAttentionLayer's HEAD-MAJOR fused
            # layout: Wqkv [d_model, H*3*Dh] with each head's q|k|v block
            # contiguous (attention.py param_shapes — the layout that lets
            # tensor-parallel column sharding propagate), Wo [H*Dh, d_model].
            wq = np.asarray(raw["query_kernel"])
            d_model = wq.shape[0]
            inner = wq.shape[1] * wq.shape[2]
            h, dh = wq.shape[1], wq.shape[2]
            kernels = [np.asarray(raw[f"{p}_kernel"])
                       for p in ("query", "key", "value")]     # [D,H,Dh] x3
            # use_bias=False stores no bias datasets: zero bias == no bias
            biases = [np.asarray(raw[f"{p}_bias"])
                      if f"{p}_bias" in raw else np.zeros((h, dh), np.float32)
                      for p in ("query", "key", "value")]      # [H,Dh] x3
            wqkv = np.stack(kernels, axis=2)                   # [D,H,3,Dh]
            bqkv = np.stack([b.reshape(h, dh) for b in biases],
                            axis=1)                            # [H,3,Dh]
            wo = np.asarray(raw["attention_output_kernel"]).reshape(inner, -1)
            bo = (np.asarray(raw["attention_output_bias"])
                  if "attention_output_bias" in raw
                  else np.zeros(wo.shape[1], np.float32))
            return ({"Wqkv": wqkv.reshape(d_model, 3 * inner),
                     "bqkv": bqkv.reshape(3 * inner),
                     "Wo": wo,
                     "bo": bo}, {})

        return (SelfAttentionLayer(name=name, n_heads=heads,
                                   head_size=key_dim, project_input=True,
                                   attn_dropout=float(cfg.get("dropout", 0.0))),
                mha_weights)

    raise UnsupportedKerasConfigurationException(
        f"Unsupported Keras layer type {class_name!r}")


def map_keras_mha_cross(cfg: dict) -> Tuple[Layer, WeightFn]:
    """True cross-attention ``MultiHeadAttention`` (distinct query/value
    inbound tensors) → CrossAttentionLayer. Called by the functional-model
    importer, which knows the inbound arity."""
    from deeplearning4j_tpu.nn.layers import CrossAttentionLayer

    name = cfg.get("name")
    heads = int(cfg.get("num_heads", 1))
    key_dim = int(cfg.get("key_dim", 0)) or None
    value_dim = cfg.get("value_dim")
    if cfg.get("output_shape") is not None:
        raise UnsupportedKerasConfigurationException(
            "MultiHeadAttention with an explicit output_shape is not "
            "supported (output dim must equal the query dim)")

    def weights(raw):
        def proj(prefix):
            kk = np.asarray(raw[f"{prefix}_kernel"])
            d, h, dh = kk.shape
            w = kk.reshape(d, h * dh)
            b = (np.asarray(raw[f"{prefix}_bias"]).reshape(h * dh)
                 if f"{prefix}_bias" in raw else np.zeros(h * dh, np.float32))
            return w, b
        wq, bq = proj("query")
        wk, bk = proj("key")
        wv, bv = proj("value")
        wo_raw = np.asarray(raw["attention_output_kernel"])
        wo = wo_raw.reshape(-1, wo_raw.shape[-1])
        bo = (np.asarray(raw["attention_output_bias"])
              if "attention_output_bias" in raw
              else np.zeros(wo.shape[1], np.float32))
        return ({"Wq": wq, "bq": bq, "Wk": wk, "bk": bk, "Wv": wv, "bv": bv,
                 "Wo": wo, "bo": bo}, {})

    layer = CrossAttentionLayer(
        name=name, n_heads=heads, head_size=key_dim,
        value_size=None if value_dim is None else int(value_dim),
        attn_dropout=float(cfg.get("dropout", 0.0)))
    return layer, weights
