"""Public Keras import facade.

Reference: ``deeplearning4j-modelimport/.../KerasModelImport.java:41``
(``importKerasModelAndWeights:50-194``,
``importKerasSequentialModelAndWeights``, config-only variants).
"""

from __future__ import annotations

import json
from typing import Optional, Union

from deeplearning4j_tpu.modelimport.keras.hdf5 import Hdf5Archive
from deeplearning4j_tpu.modelimport.keras.layers import (
    InvalidKerasConfigurationException,
)
from deeplearning4j_tpu.modelimport.keras.model import (
    KerasModel,
    KerasModelConfig,
    KerasSequentialModel,
)


def _read_configs(archive: Hdf5Archive):
    model_json = archive.read_attribute_as_json("model_config")
    if model_json is None:
        raise InvalidKerasConfigurationException(
            "HDF5 file has no model_config attribute (was it saved with "
            "save_weights only? use the json+weights import variant)")
    training_json = archive.read_attribute_as_json("training_config") or {}
    return model_json, training_json


def _weights_root(archive: Hdf5Archive):
    return ("model_weights",) if "model_weights" in archive.get_groups() else ()


def _is_sequential(model_json: dict) -> bool:
    return model_json.get("class_name") == "Sequential"


class KerasModelImport:
    """Static import API (``KerasModelImport.java``)."""

    @staticmethod
    def import_keras_model_and_weights(h5_path: str,
                                       weights_path: Optional[str] = None):
        """Full-model HDF5 (config + weights) → initialized network; or, with
        ``weights_path``, a model-config JSON file + a save_weights HDF5 (the
        two-file overload, ``KerasModelImport.java:50-194`` — exercised by
        the reference's tfscope fixtures). Returns MultiLayerNetwork for
        Sequential, ComputationGraph otherwise."""
        if weights_path is not None:
            with open(h5_path) as f:
                model_json = json.load(f)
            cfg = KerasModelConfig(model_json)
            km = (KerasSequentialModel(cfg) if _is_sequential(model_json)
                  else KerasModel(cfg))
            net = km.init()
            with Hdf5Archive(weights_path) as a:
                km.copy_weights(net, a, *_weights_root(a))
            return net
        with Hdf5Archive(h5_path) as a:
            model_json, training_json = _read_configs(a)
            cfg = KerasModelConfig(model_json, training_json)
            if _is_sequential(model_json):
                km = KerasSequentialModel(cfg)
            else:
                km = KerasModel(cfg)
            net = km.init()
            km.copy_weights(net, a, *_weights_root(a))
            return net

    @staticmethod
    def import_keras_sequential_model_and_weights(h5_path: str,
                                                  json_path: Optional[str] = None):
        if json_path is not None:
            with open(json_path) as f:
                model_json = json.load(f)
            cfg = KerasModelConfig(model_json)
            km = KerasSequentialModel(cfg)
            net = km.init()
            with Hdf5Archive(h5_path) as a:
                km.copy_weights(net, a, *_weights_root(a))
            return net
        net = KerasModelImport.import_keras_model_and_weights(h5_path)
        return net

    @staticmethod
    def import_keras_model_configuration(json_path: str):
        """Config-only import: returns the (uninitialized) configuration."""
        with open(json_path) as f:
            model_json = json.load(f)
        cfg = KerasModelConfig(model_json)
        if _is_sequential(model_json):
            return KerasSequentialModel(cfg).conf
        return KerasModel(cfg).conf

    @staticmethod
    def import_keras_sequential_configuration(json_path: str):
        """Sequential config-only import
        (``KerasModelImport.importKerasSequentialConfiguration``); rejects
        functional-model JSON loudly."""
        with open(json_path) as f:
            model_json = json.load(f)
        if not _is_sequential(model_json):
            raise ValueError(
                f"{json_path} is not a Sequential model config; use "
                "import_keras_model_configuration")
        return KerasSequentialModel(KerasModelConfig(model_json)).conf

    @staticmethod
    def import_keras_model_from_json(model_json: Union[str, dict],
                                     training_json: Optional[dict] = None):
        """In-memory JSON → built (uninitialized params) Keras model wrapper."""
        if isinstance(model_json, str):
            model_json = json.loads(model_json)
        cfg = KerasModelConfig(model_json, training_json)
        if _is_sequential(model_json):
            return KerasSequentialModel(cfg)
        return KerasModel(cfg)
