"""HDF5 archive reader for Keras files.

Reference: ``deeplearning4j-modelimport/.../Hdf5Archive.java:46`` — the
reference wraps libhdf5 through JavaCPP JNI; here h5py reads the same files
directly (no native binding layer needed).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

import numpy as np


class Hdf5Archive:
    """Thin h5py wrapper matching Hdf5Archive's read API."""

    def __init__(self, path):
        import h5py
        self._f = h5py.File(path, "r")

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()

    @staticmethod
    def _decode(v):
        if isinstance(v, bytes):
            return v.decode("utf-8")
        return v

    def read_attribute_as_string(self, name: str, *groups: str) -> Optional[str]:
        node = self._node(*groups)
        if name not in node.attrs:
            return None
        return self._decode(node.attrs[name])

    def read_attribute_as_json(self, name: str, *groups: str) -> Optional[dict]:
        s = self.read_attribute_as_string(name, *groups)
        return None if s is None else json.loads(s)

    def has_attribute(self, name: str, *groups: str) -> bool:
        return name in self._node(*groups).attrs

    def read_attribute_as_fixed_length_string_list(self, name: str, *groups: str) -> List[str]:
        node = self._node(*groups)
        if name not in node.attrs:
            return []
        return [self._decode(v) for v in node.attrs[name]]

    def read_dataset(self, name: str, *groups: str) -> np.ndarray:
        return np.asarray(self._node(*groups)[name])

    def get_data_sets(self, *groups: str) -> List[str]:
        import h5py
        node = self._node(*groups)
        return [k for k in node.keys() if isinstance(node[k], h5py.Dataset)]

    def get_groups(self, *groups: str) -> List[str]:
        import h5py
        node = self._node(*groups)
        return [k for k in node.keys() if isinstance(node[k], h5py.Group)]

    def _node(self, *groups: str):
        node = self._f
        for g in groups:
            node = node[g]
        return node


def read_weights_for_layer(archive: Hdf5Archive, layer_name: str,
                           *root: str) -> Dict[str, np.ndarray]:
    """Collect every dataset under the layer's weight group, flattened to
    ``{basename: array}`` (handles both Keras1 flat names and Keras2
    ``layer/variable:0`` nesting)."""
    out: Dict[str, np.ndarray] = {}

    _MHA_PROJ = {"query", "key", "value", "attention_output"}

    def walk(groups, prefix):
        for ds in archive.get_data_sets(*groups):
            base = prefix + ds.split(":")[0]
            out[base] = archive.read_dataset(ds, *groups)
        subs = archive.get_groups(*groups)
        # MultiHeadAttention nests its four projections as SIBLING groups;
        # require at least three of them together before treating the names
        # as MHA projections, so an ordinary layer named e.g. "value" keeps
        # flat basenames
        sub_bases = {s.split(":")[0] for s in subs}
        is_mha_level = len(_MHA_PROJ & sub_bases) >= 3
        for sub in subs:
            # Bidirectional wrappers encode direction in the group path
            # (forward_lstm/..., backward_lstm/...); MHA projections surface
            # as name prefixes so their basenames don't collide
            sub_prefix = prefix
            base = sub.split(":")[0]
            if sub.startswith("forward"):
                sub_prefix = "forward_"
            elif sub.startswith("backward"):
                sub_prefix = "backward_"
            elif is_mha_level and base in _MHA_PROJ:
                sub_prefix = prefix + base + "_"
            walk(list(groups) + [sub], sub_prefix)

    walk(list(root) + [layer_name], "")
    return out
