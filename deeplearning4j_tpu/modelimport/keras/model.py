"""Keras model-level import: Sequential → MultiLayerNetwork, functional
Model → ComputationGraph.

Reference: ``deeplearning4j-modelimport/.../KerasModel.java`` /
``KerasSequentialModel.java`` (config parsing, topology build, weight
copy-in) and ``utils/KerasModelBuilder.java``. The reference reads configs
either from a standalone JSON or from the ``model_config`` attribute of a
full-model HDF5; weights live under ``model_weights`` (full save) or at the
file root (save_weights).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.modelimport.keras.hdf5 import Hdf5Archive, read_weights_for_layer
from deeplearning4j_tpu.modelimport.keras.layers import (
    LOSSES,
    InvalidKerasConfigurationException,
    UnsupportedKerasConfigurationException,
    map_keras_layer,
)
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.layers import DenseLayer, LossLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.vertices import ElementWiseVertex, MergeVertex, PreprocessorVertex


def _reshape_spec(conf: dict) -> str:
    """Keras Reshape target_shape → ``reshape:`` preprocessor spec. A -1
    wildcard dim needs the upstream element count to resolve, which the
    import-time spec cannot carry — rejected loudly rather than emitting a
    corrupt negative-size InputType."""
    target = conf.get("target_shape") or ()
    dims = [int(d) for d in target]
    if any(d < 0 for d in dims):
        raise UnsupportedKerasConfigurationException(
            f"Reshape target_shape {tuple(target)} contains a -1 wildcard; "
            "re-save the model with explicit dimensions")
    return "reshape:" + ",".join(str(d) for d in dims)


def _channels_first(layer_configs) -> bool:
    """True when the model declares theano dim ordering / channels_first —
    then rank-3 input shapes are [C,H,W] and must be re-interpreted for
    this framework's NHWC layout (KerasLayer.getDimOrder role).

    A model whose LAYOUT-BEARING layers (conv/pooling — the ones whose
    data_format decides how spatial inputs are interpreted) mix both
    orderings is rejected loudly: one whole-model flag cannot honestly
    re-interpret per-branch input shapes, and silently picking either
    ordering would mis-map the other branch's [H,W,C]/[C,H,W] inputs.
    Pass-through layers that merely serialize a data_format field
    (Flatten, a lone default-format pooling after channels_first convs…)
    follow the conv layers' ordering and do not create a conflict."""
    bearing, other = set(), set()
    for lc in layer_configs:
        cls = lc.get("class_name") or ""
        c = lc.get("config", {})
        fmt = c.get("dim_ordering") or c.get("data_format")
        if fmt in ("th", "channels_first"):
            fmt = "channels_first"
        elif fmt in ("tf", "channels_last"):
            fmt = "channels_last"
        else:
            continue
        if "Conv" in cls or "Pooling" in cls:
            bearing.add(fmt)
        else:
            other.add(fmt)
    if len(bearing) > 1:
        raise UnsupportedKerasConfigurationException(
            "model mixes channels_first and channels_last conv/pooling "
            "layers; re-save with a single data_format")
    decisive = bearing or other
    return "channels_first" in decisive and len(decisive) == 1


def _input_type_from_shape(shape, channels_first: bool = False) -> InputType:
    """Keras input_shape/batch_input_shape (batch dim stripped) → InputType.
    Layout is channels_last (NHWC), the TPU-native layout; a channels-first
    model's [C,H,W] input shape maps to the equivalent NHWC type."""
    shape = tuple(shape)
    if channels_first and len(shape) == 3:
        c, h, w = shape
        return InputType.convolutional(h, w, c)
    if len(shape) == 1:
        return InputType.feed_forward(shape[0])
    if len(shape) == 2:
        t, features = shape  # (timesteps-or-None, features)
        return InputType.recurrent(features, t)
    if len(shape) == 3:
        h, w, c = shape
        return InputType.convolutional(h, w, c)
    if len(shape) == 4:
        t, h, w, c = shape  # image sequence (ConvLSTM2D / TimeDistributed conv)
        return InputType.recurrent_convolutional(h, w, c, t)
    raise UnsupportedKerasConfigurationException(f"Unsupported input shape {shape}")


def _to_loss(loss_name: Optional[str]) -> Optional[str]:
    if not loss_name:
        return None
    return LOSSES.get(str(loss_name).lower())


class KerasModelConfig:
    """Parsed top-level Keras config."""

    def __init__(self, config_json: dict, training_json: Optional[dict] = None):
        self.class_name = config_json.get("class_name")
        self.config = config_json.get("config")
        self.training = training_json or {}

    @property
    def loss(self) -> Optional[str]:
        return _to_loss(self.training.get("loss"))

    @property
    def layer_configs(self) -> List[dict]:
        if isinstance(self.config, list):  # Keras 1 Sequential
            return self.config
        return self.config.get("layers", [])


class KerasSequentialModel:
    """Sequential import (``KerasSequentialModel.java``)."""

    def __init__(self, model_config: KerasModelConfig):
        self.cfg = model_config
        self.layer_names: List[str] = []
        self.weight_fns: Dict[str, object] = {}
        self._build()

    def _build(self):
        input_type: Optional[InputType] = None
        layers = []
        explicit_pre: Dict[int, str] = {}
        ch_first = _channels_first(self.cfg.layer_configs)
        for lc in self.cfg.layer_configs:
            cls = lc["class_name"]
            conf = dict(lc.get("config", {}))
            if input_type is None:
                shape = conf.get("batch_input_shape") or conf.get("batch_shape")
                if shape is not None and cls == "Embedding":
                    # token-index sequence [N, T] (T may be None — the imdb
                    # fixtures declare [None, None]); never a raw ff size
                    input_type = InputType.recurrent(
                        1, shape[1] if len(shape) > 1 else None)
                elif shape is not None:
                    input_type = _input_type_from_shape(shape[1:], ch_first)
                elif "input_shape" in conf:
                    input_type = _input_type_from_shape(conf["input_shape"],
                                                        ch_first)
                elif "input_dim" in conf and cls in ("Dense", "Embedding"):
                    if cls == "Embedding":
                        input_type = InputType.recurrent(
                            1, conf.get("input_length"))
                    else:
                        input_type = InputType.feed_forward(int(conf["input_dim"]))
            if cls == "Reshape":
                # KerasReshape.java: a Reshape layer IS an input preprocessor
                # on the next layer (raw row-major reshape after batch)
                explicit_pre[len(layers)] = _reshape_spec(conf)
                continue
            if cls == "Flatten" and len(layers) in explicit_pre:
                # Reshape→Flatten→Dense: the flatten normally rides the
                # dense layer's AUTO preprocessor, but an explicit spec
                # replaces auto inference — compose the flatten matching
                # the reshape target's RANK. Keras Flatten is a row-major
                # collapse of the per-example dims: rank-3 [H,W,C] →
                # cnn_to_ff ([N,H*W*C], same memory order); rank-2 [T,C] →
                # a raw reshape to [N, T*C] (NOT rnn_to_ff, which is the
                # per-timestep [N*T,C] view and changes the batch size);
                # rank-1 is already flat.
                spec = explicit_pre[len(layers)]
                tail = spec.rsplit("|", 1)[-1]
                if not tail.startswith("reshape:"):
                    # only tails KNOWN to produce flat per-example output
                    # make the Flatten a no-op (cnn_to_ff/rnn_to_ff collapse
                    # to [*, C]); a rank-raising or unknown tail silently
                    # dropping the Flatten would corrupt the topology
                    if tail in ("cnn_to_ff", "rnn_to_ff"):
                        continue
                    raise UnsupportedKerasConfigurationException(
                        f"Flatten after explicit preprocessor {spec!r}: "
                        f"tail {tail!r} is not known to produce flat "
                        "output, so the Flatten cannot be composed or "
                        "skipped safely")
                dims = [int(d) for d in
                        tail[len("reshape:"):].split(",")]
                if len(dims) > 1:
                    # Keras Flatten = row-major collapse of the per-example
                    # dims, i.e. a raw reshape to prod(dims) for ANY rank
                    # (identical to cnn_to_ff at rank 3)
                    total = 1
                    for d in dims:
                        total *= d
                    explicit_pre[len(layers)] += f"|reshape:{total}"
                continue
            layer, wf = map_keras_layer(cls, conf)
            if layer is None:
                continue
            lname = conf.get("name") or f"layer_{len(layers)}"
            layer.name = lname
            self.layer_names.append(lname)
            self.weight_fns[lname] = wf
            layers.append(layer)
        if input_type is None:
            raise InvalidKerasConfigurationException(
                "Sequential model config declares no input shape")
        if not layers:
            raise InvalidKerasConfigurationException("model has no layers")

        # attach the training loss: final Dense becomes an OutputLayer,
        # otherwise a LossLayer caps the stack (KerasLoss.java behavior)
        loss = self.cfg.loss
        if loss is not None:
            last = layers[-1]
            if type(last) is DenseLayer:
                out = OutputLayer(name=last.name, n_in=last.n_in, n_out=last.n_out,
                                  activation=last.activation, has_bias=last.has_bias,
                                  loss=loss)
                layers[-1] = out
            elif not last.has_loss():
                layers.append(LossLayer(name="keras_loss", loss=loss,
                                        activation="identity"))

        b = NeuralNetConfiguration.builder().list()
        for l in layers:
            b.layer(l)
        for idx, spec in explicit_pre.items():
            if idx >= len(layers):
                raise UnsupportedKerasConfigurationException(
                    "Reshape as the final layer has no consumer to attach to")
            b.input_pre_processor(idx, spec)
        self.conf = b.set_input_type(input_type).build()

    def init(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf).init()

    def copy_weights(self, net: MultiLayerNetwork, archive: Hdf5Archive,
                     *root: str) -> None:
        by_name = {l.name: i for i, l in enumerate(net.layers)}
        import jax.numpy as jnp
        for lname in self.layer_names:
            if lname not in by_name:
                continue
            raw = read_weights_for_layer(archive, lname, *root)
            if not raw:
                continue
            params, states = self.weight_fns[lname](raw)
            i = by_name[lname]
            self._check_and_set(net.params[i], params, lname)
            for k, v in states.items():
                net.states[i][k] = jnp.asarray(np.asarray(v))

    @staticmethod
    def _check_and_set(target: dict, src: dict, lname: str) -> None:
        import jax.numpy as jnp
        for k, v in src.items():
            if k not in target:
                raise InvalidKerasConfigurationException(
                    f"layer {lname!r}: imported param {k!r} not in model params "
                    f"{sorted(target)}")
            if tuple(target[k].shape) != tuple(np.shape(v)):
                raise InvalidKerasConfigurationException(
                    f"layer {lname!r} param {k!r}: shape {np.shape(v)} does not "
                    f"match model {tuple(target[k].shape)}")
            target[k] = jnp.asarray(np.asarray(v))


class KerasModel:
    """Functional-API import (``KerasModel.java``) → ComputationGraph."""

    MERGE_LAYERS = {"Concatenate", "Merge"}
    ELEMENTWISE = {"Add": "add", "Average": "average", "Subtract": "subtract",
                   "Multiply": "product", "Maximum": "max"}

    def __init__(self, model_config: KerasModelConfig):
        self.cfg = model_config
        self.layer_names: List[str] = []
        self.weight_fns: Dict[str, object] = {}
        self._build()

    @staticmethod
    def _inbound(lc: dict) -> List[str]:
        nodes = lc.get("inbound_nodes") or []
        if not nodes:
            return []
        node = nodes[0]
        names = []
        if isinstance(node, dict):  # Keras 3 style {"args": [...]}
            def walk(o):
                if isinstance(o, dict):
                    if o.get("class_name") == "__keras_tensor__":
                        names.append(o["config"]["keras_history"][0])
                    else:
                        for v in o.values():
                            walk(v)
                elif isinstance(o, (list, tuple)):
                    for v in o:
                        walk(v)
            walk(node)
        else:
            for entry in node:
                names.append(entry[0])
        return names

    def _build(self):
        conf = self.cfg.config
        layer_confs = conf["layers"]

        def names_of(specs) -> List[str]:
            # Keras 2: [["name", 0, 0], ...]; Keras 3 single output: ["name", 0, 0]
            if (isinstance(specs, (list, tuple)) and len(specs) == 3
                    and isinstance(specs[0], str) and isinstance(specs[1], int)):
                return [specs[0]]
            return [s[0] if isinstance(s, (list, tuple)) else s for s in specs]

        input_names = names_of(conf.get("input_layers", []))
        output_names = names_of(conf.get("output_layers", []))

        g = NeuralNetConfiguration.builder().graph_builder()
        input_types: List[InputType] = []
        ch_first = _channels_first(layer_confs)
        for lc in layer_confs:
            cls = lc["class_name"]
            c = dict(lc.get("config", {}))
            lname = lc.get("name") or c.get("name")
            inputs = self._inbound(lc)
            if cls == "InputLayer":
                shape = c.get("batch_input_shape") or c.get("batch_shape")
                input_types.append(_input_type_from_shape(shape[1:], ch_first))
                g.add_inputs(lname)
                continue
            if cls in self.MERGE_LAYERS:
                g.add_vertex(lname, MergeVertex(), *inputs)
                continue
            if cls in self.ELEMENTWISE:
                g.add_vertex(lname, ElementWiseVertex(op=self.ELEMENTWISE[cls]),
                             *inputs)
                continue
            if cls == "Flatten":
                g.add_vertex(lname, PreprocessorVertex(preprocessor="cnn_to_ff"),
                             *inputs)
                continue
            if cls == "Reshape":
                g.add_vertex(lname,
                             PreprocessorVertex(preprocessor=_reshape_spec(c)),
                             *inputs)
                continue
            if cls == "MultiHeadAttention":
                # self-attention calls mha(x, x[, x]): collapse identical
                # inbound tensors to one input. Distinct query/value sources =
                # true cross-attention → CrossAttentionLayer, keeping the
                # inbound tensors separate (Keras call order [query, value(,
                # key)]) via the graph's multi-input layer protocol.
                uniq = list(dict.fromkeys(inputs))
                if len(uniq) > 1:
                    from deeplearning4j_tpu.modelimport.keras.layers import (
                        map_keras_mha_cross)

                    layer, wf = map_keras_mha_cross(c)
                    layer.name = lname
                    self.layer_names.append(lname)
                    self.weight_fns[lname] = wf
                    g.add_layer(lname, layer, *inputs)
                    continue
                inputs = uniq
            layer, wf = map_keras_layer(cls, c)
            if layer is None:
                # structural no-op (Masking): pass-through vertex
                g.add_vertex(lname, PreprocessorVertex(preprocessor="identity"),
                             *inputs)
                continue
            layer.name = lname
            self.layer_names.append(lname)
            self.weight_fns[lname] = wf
            g.add_layer(lname, layer, *inputs)

        loss = self.cfg.loss
        if loss is not None:
            final_outputs = []
            for on in output_names:
                loss_name = f"{on}_loss"
                g.add_layer(loss_name, LossLayer(loss=loss, activation="identity"), on)
                final_outputs.append(loss_name)
            output_names = final_outputs
        g.set_outputs(*output_names)
        g.set_input_types(*input_types)
        self.conf = g.build()

    def init(self) -> ComputationGraph:
        return ComputationGraph(self.conf).init()

    def copy_weights(self, net: ComputationGraph, archive: Hdf5Archive,
                     *root: str) -> None:
        import jax.numpy as jnp
        for lname in self.layer_names:
            if lname not in net.params:
                continue
            raw = read_weights_for_layer(archive, lname, *root)
            if not raw:
                continue
            params, states = self.weight_fns[lname](raw)
            KerasSequentialModel._check_and_set(net.params[lname], params, lname)
            for k, v in states.items():
                net.states[lname][k] = jnp.asarray(np.asarray(v))
