"""Keras HDF5/JSON model import.

Reference: ``deeplearning4j-modelimport/`` (``KerasModelImport.java:41``,
``Hdf5Archive.java:46``, per-layer mappers under ``layers/``). The HDF5
native binding is replaced by h5py; layouts stay channels-last (NHWC).
"""

from deeplearning4j_tpu.modelimport.keras.hdf5 import Hdf5Archive
from deeplearning4j_tpu.modelimport.keras.importer import KerasModelImport
from deeplearning4j_tpu.modelimport.keras.layers import (
    InvalidKerasConfigurationException,
    UnsupportedKerasConfigurationException,
    clear_lambda_layers,
    register_lambda_layer,
)
from deeplearning4j_tpu.modelimport.keras.model import (
    KerasModel,
    KerasModelConfig,
    KerasSequentialModel,
)

__all__ = [
    "Hdf5Archive", "KerasModelImport", "KerasModel", "KerasModelConfig",
    "KerasSequentialModel", "InvalidKerasConfigurationException",
    "UnsupportedKerasConfigurationException", "register_lambda_layer",
    "clear_lambda_layers",
]
