"""ND4J binary array format — reader/writer for ``coefficients.bin``.

The reference's ``ModelSerializer.writeModel`` (``util/ModelSerializer.java:51``)
stores the network's single flattened parameter vector via
``Nd4j.write(params, dataOutputStream)``, and ``restoreMultiLayerNetwork``
(``:182``) reads it back via ``Nd4j.read``. ND4J itself is an external Maven
dependency (SURVEY.md L0), so the byte format is implemented here from the
ND4J 0.9.x wire layout:

``Nd4j.write`` emits two ``DataBuffer.write`` records back to back —
shape-information buffer, then data buffer. Each record is::

    writeUTF(allocationMode)   # java modified-UTF8: u16 BE length + bytes
                               # ("HEAP" | "DIRECT" | "JAVACPP" | ...)
    writeInt(length)           # element count, int32 BE
    writeUTF(dataTypeName)     # "INT" | "LONG" | "FLOAT" | "DOUBLE" | "HALF"
    <length elements, big-endian>

The shape-information buffer is the classic ND4J shapeInfo vector::

    [rank, shape_0..r-1, stride_0..r-1, offset, elementWiseStride, order]

with ``order`` the ordering character code (99='c', 102='f'). INT shape
buffers are the 0.x layout; LONG is accepted for 1.0-era files.

The writer exists to build migration fixtures and to round-trip-test the
reader; it emits the 0.9.x layout byte-for-byte (HEAP mode, INT shape
buffer, FLOAT/DOUBLE data).
"""

from __future__ import annotations

import io
import struct
from typing import BinaryIO

import numpy as np

_DTYPES_READ = {
    "FLOAT": (">f4", np.float32),
    "DOUBLE": (">f8", np.float64),
    "HALF": (">f2", np.float16),
    "INT": (">i4", np.int32),
    "LONG": (">i8", np.int64),
}


def _read_utf(f: BinaryIO) -> str:
    """java DataOutputStream.writeUTF counterpart (length-prefixed)."""
    raw = f.read(2)
    if len(raw) < 2:
        raise ValueError("truncated ND4J buffer: missing UTF length")
    (n,) = struct.unpack(">H", raw)
    data = f.read(n)
    if len(data) < n:
        raise ValueError("truncated ND4J buffer: short UTF payload")
    # java modified UTF-8 ~= utf-8 for the ASCII names used here
    return data.decode("utf-8")


def _write_utf(f: BinaryIO, s: str) -> None:
    b = s.encode("utf-8")
    f.write(struct.pack(">H", len(b)))
    f.write(b)


def _read_buffer(f: BinaryIO) -> np.ndarray:
    """One DataBuffer.write record → 1-d numpy array (native byte order)."""
    mode = _read_utf(f)
    if not mode.isupper():
        raise ValueError(f"bad ND4J allocation mode {mode!r} — not an "
                         "Nd4j.write stream?")
    raw = f.read(4)
    if len(raw) < 4:
        raise ValueError("truncated ND4J buffer: missing length")
    (length,) = struct.unpack(">i", raw)
    if length < 0:
        raise ValueError(f"bad ND4J buffer length {length}")
    dtype_name = _read_utf(f)
    if dtype_name not in _DTYPES_READ:
        raise ValueError(f"unsupported ND4J data type {dtype_name!r}")
    wire, out = _DTYPES_READ[dtype_name]
    nbytes = length * np.dtype(wire).itemsize
    data = f.read(nbytes)
    if len(data) < nbytes:
        raise ValueError(f"truncated ND4J buffer: wanted {nbytes} data bytes, "
                         f"got {len(data)}")
    return np.frombuffer(data, dtype=wire).astype(out, copy=False)


def _write_buffer(f: BinaryIO, arr: np.ndarray, dtype_name: str) -> None:
    wire, _ = _DTYPES_READ[dtype_name]
    _write_utf(f, "HEAP")
    f.write(struct.pack(">i", arr.size))
    _write_utf(f, dtype_name)
    f.write(np.ascontiguousarray(arr, dtype=wire).tobytes())


def read_nd4j_array(f: BinaryIO) -> np.ndarray:
    """``Nd4j.read``: shapeInfo buffer + data buffer → numpy array with the
    recorded shape and ordering applied."""
    shape_info = _read_buffer(f).astype(np.int64)
    if shape_info.size < 1:
        raise ValueError("empty ND4J shape-information buffer")
    rank = int(shape_info[0])
    if rank < 0 or shape_info.size < 2 * rank + 4:
        raise ValueError(
            f"bad ND4J shapeInfo: rank {rank}, {shape_info.size} elements")
    shape = tuple(int(s) for s in shape_info[1:1 + rank])
    order = chr(int(shape_info[2 * rank + 3])) or "c"
    data = _read_buffer(f)
    n = int(np.prod(shape)) if rank else data.size
    if data.size != n:
        raise ValueError(f"ND4J data buffer has {data.size} elements, "
                         f"shape {shape} wants {n}")
    return data.reshape(shape, order="F" if order == "f" else "C")


def read_nd4j_array_from_bytes(b: bytes) -> np.ndarray:
    return read_nd4j_array(io.BytesIO(b))


def write_nd4j_array(f: BinaryIO, arr: np.ndarray, order: str = "c") -> None:
    """``Nd4j.write`` counterpart (0.9.x layout) — fixture/round-trip use."""
    arr = np.asarray(arr)
    if arr.dtype == np.float64:
        dtype_name = "DOUBLE"
    elif arr.dtype == np.float16:
        dtype_name = "HALF"
    else:
        arr = arr.astype(np.float32, copy=False)
        dtype_name = "FLOAT"
    rank = arr.ndim
    shape = arr.shape
    # c-order strides in elements (ND4J convention); 'f' flips the build
    strides = [0] * rank
    acc = 1
    idx = range(rank - 1, -1, -1) if order == "c" else range(rank)
    for i in idx:
        strides[i] = acc
        acc *= shape[i]
    shape_info = np.array(
        [rank, *shape, *strides, 0, 1, ord(order)], dtype=np.int32)
    _write_buffer(f, shape_info, "INT")
    flat = arr.flatten(order="F" if order == "f" else "C")
    _write_buffer(f, flat, dtype_name)


def nd4j_array_to_bytes(arr: np.ndarray, order: str = "c") -> bytes:
    buf = io.BytesIO()
    write_nd4j_array(buf, arr, order)
    return buf.getvalue()
