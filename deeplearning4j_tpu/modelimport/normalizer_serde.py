"""ND4J ``NormalizerSerializer`` stream — ``normalizer.bin`` in model zips.

The reference appends a fitted normalizer to every model zip that has one
(``util/ModelSerializer.java:40`` ``NORMALIZER_BIN``, write at ``:165-168``,
``addNormalizerToModel:654``, restore at ``restoreNormalizerFromFile:707``).
The serializer itself (``org.nd4j.linalg.dataset.api.preprocessor.serializer.
NormalizerSerializer``) lives in ND4J, an external Maven dependency outside
the reference snapshot, so — exactly like ``coefficients.bin`` and
``updaterState.bin`` in ``nd4j_binary.py`` — the byte layout is implemented
here from the ND4J 1.0 wire format and verified by round-trip
self-consistency (``tests/test_normalizer_serde.py``; the honest limits of
that verification are documented in ``tests/test_dl4j_legacy_formats.py``).

Stream layout (all java ``DataOutputStream`` primitives, big-endian)::

    writeUTF("NORMALIZER")          # header magic
    writeInt(1)                     # header version
    writeUTF(type)                  # NormalizerType enum name
    [writeUTF(customClass)]         # only when type == CUSTOM

followed by the strategy payload:

``STANDARDIZE`` (NormalizerStandardize)::

    writeBoolean(fitLabel)
    Nd4j.write(mean); Nd4j.write(std)
    [Nd4j.write(labelMean); Nd4j.write(labelStd)]   # iff fitLabel

``MIN_MAX`` (NormalizerMinMaxScaler)::

    writeBoolean(fitLabel)
    writeDouble(targetMin); writeDouble(targetMax)
    Nd4j.write(min); Nd4j.write(max)
    [Nd4j.write(labelMin); Nd4j.write(labelMax)]    # iff fitLabel

``IMAGE_MIN_MAX`` (ImagePreProcessingScaler)::

    writeDouble(minRange); writeDouble(maxRange); writeDouble(maxPixelVal)

``IMAGE_VGG16`` (VGG16ImagePreProcessor): empty payload (stateless).

``MULTI_STANDARDIZE`` / ``MULTI_MIN_MAX`` (MultiNormalizer*)::

    writeBoolean(fitLabel)
    writeInt(numInputs)
    writeInt(fitLabel ? numOutputs : -1)
    [writeDouble(targetMin); writeDouble(targetMax)]   # MULTI_MIN_MAX only
    per input:  Nd4j.write(stat_a); Nd4j.write(stat_b)  # mean/std or min/max
    per output (iff fitLabel): the same pair for labels

``MULTI_HYBRID`` (per-input strategy mix) and ``CUSTOM`` strategies are
rejected loudly — they carry arbitrary class names whose payloads cannot be
interpreted without the class.
"""

from __future__ import annotations

import io
import struct
from typing import BinaryIO, Union

import numpy as np

from deeplearning4j_tpu.modelimport.nd4j_binary import (
    _read_utf, _write_utf, read_nd4j_array, write_nd4j_array)

HEADER_MAGIC = "NORMALIZER"
HEADER_VERSION = 1


class UnsupportedNormalizerException(ValueError):
    """Strategy exists in ND4J but cannot be represented here."""


# ---------------------------------------------------------------------------
# java DataOutputStream primitives on top of nd4j_binary's UTF helpers

def _read_bool(f: BinaryIO) -> bool:
    b = f.read(1)
    if len(b) < 1:
        raise ValueError("truncated normalizer stream: missing boolean")
    return b != b"\x00"


def _read_i32(f: BinaryIO) -> int:
    raw = f.read(4)
    if len(raw) < 4:
        raise ValueError("truncated normalizer stream: missing int")
    return struct.unpack(">i", raw)[0]


def _read_f64(f: BinaryIO) -> float:
    raw = f.read(8)
    if len(raw) < 8:
        raise ValueError("truncated normalizer stream: missing double")
    return struct.unpack(">d", raw)[0]


def _write_bool(f: BinaryIO, v: bool) -> None:
    f.write(b"\x01" if v else b"\x00")


def _write_i32(f: BinaryIO, v: int) -> None:
    f.write(struct.pack(">i", v))


def _write_f64(f: BinaryIO, v: float) -> None:
    f.write(struct.pack(">d", v))


def _read_vec(f: BinaryIO) -> np.ndarray:
    """ND4J stores normalizer stats as [1, n] row vectors; flatten."""
    return np.asarray(read_nd4j_array(f), np.float32).reshape(-1)


def _write_vec(f: BinaryIO, v: np.ndarray) -> None:
    write_nd4j_array(f, np.asarray(v, np.float32).reshape(1, -1), order="c")


# ---------------------------------------------------------------------------
# write

def write_normalizer(normalizer, f: BinaryIO) -> None:
    """``NormalizerSerializer.getDefault().write`` counterpart
    (``ModelSerializer.java:168`` call site)."""
    from deeplearning4j_tpu.datasets import normalizers as N

    _write_utf(f, HEADER_MAGIC)
    _write_i32(f, HEADER_VERSION)

    if isinstance(normalizer, N.NormalizerStandardize):
        if normalizer.mean is None:
            raise UnsupportedNormalizerException(
                "cannot serialize an unfitted NormalizerStandardize")
        _write_utf(f, "STANDARDIZE")
        fit_label = bool(normalizer.fit_label
                         and normalizer.label_mean is not None)
        _write_bool(f, fit_label)
        _write_vec(f, normalizer.mean)
        _write_vec(f, normalizer.std)
        if fit_label:
            _write_vec(f, normalizer.label_mean)
            _write_vec(f, normalizer.label_std)
    elif isinstance(normalizer, N.NormalizerMinMaxScaler):
        if normalizer.data_min is None:
            raise UnsupportedNormalizerException(
                "cannot serialize an unfitted NormalizerMinMaxScaler")
        _write_utf(f, "MIN_MAX")
        fit_label = bool(normalizer.fit_label
                         and normalizer.label_min is not None)
        _write_bool(f, fit_label)
        _write_f64(f, normalizer.min_range)
        _write_f64(f, normalizer.max_range)
        _write_vec(f, normalizer.data_min)
        _write_vec(f, normalizer.data_max)
        if fit_label:
            _write_vec(f, normalizer.label_min)
            _write_vec(f, normalizer.label_max)
    elif isinstance(normalizer, N.ImagePreProcessingScaler):
        _write_utf(f, "IMAGE_MIN_MAX")
        _write_f64(f, normalizer.min_range)
        _write_f64(f, normalizer.max_range)
        _write_f64(f, normalizer.max_pixel)
    elif isinstance(normalizer, N.VGG16ImagePreProcessor):
        _write_utf(f, "IMAGE_VGG16")
    elif isinstance(normalizer, N.MultiNormalizer):
        _write_multi(f, normalizer)
    else:
        raise UnsupportedNormalizerException(
            f"no NormalizerSerializer strategy for "
            f"{type(normalizer).__name__} — DL4J would need a CUSTOM "
            "strategy class, which has no portable byte layout")


def _write_multi(f: BinaryIO, m) -> None:
    if not m.children:
        raise UnsupportedNormalizerException(
            "cannot serialize an unfitted MultiNormalizer")
    standardize = m.kind == "standardize"
    _write_utf(f, "MULTI_STANDARDIZE" if standardize else "MULTI_MIN_MAX")
    fit_label = bool(m.label_children)
    _write_bool(f, fit_label)
    _write_i32(f, len(m.children))
    _write_i32(f, len(m.label_children) if fit_label else -1)
    if not standardize:
        child0 = m.children[0]
        _write_f64(f, child0.min_range)
        _write_f64(f, child0.max_range)
    for c in m.children + m.label_children:
        if standardize:
            _write_vec(f, c.mean)
            _write_vec(f, c.std)
        else:
            _write_vec(f, c.data_min)
            _write_vec(f, c.data_max)


def normalizer_to_bytes(normalizer) -> bytes:
    buf = io.BytesIO()
    write_normalizer(normalizer, buf)
    return buf.getvalue()


# ---------------------------------------------------------------------------
# read

def read_normalizer(f: BinaryIO):
    """``NormalizerSerializer.getDefault().restore`` counterpart
    (``ModelSerializer.java:715`` call site)."""
    from deeplearning4j_tpu.datasets import normalizers as N

    magic = _read_utf(f)
    if magic != HEADER_MAGIC:
        raise ValueError(
            f"not a NormalizerSerializer stream (magic {magic!r}); "
            "pre-0.9 zips used raw Java object serialization "
            "(ModelSerializer.java:759 deprecated path), which is not "
            "portable")
    version = _read_i32(f)
    if version != HEADER_VERSION:
        raise ValueError(f"unsupported normalizer header version {version}")
    ntype = _read_utf(f)

    if ntype == "STANDARDIZE":
        n = N.NormalizerStandardize()
        fit_label = _read_bool(f)
        n.mean = _read_vec(f)
        n.std = _read_vec(f)
        if fit_label:
            n.fit_label = True
            n.label_mean = _read_vec(f)
            n.label_std = _read_vec(f)
        return n
    if ntype == "MIN_MAX":
        fit_label = _read_bool(f)
        n = N.NormalizerMinMaxScaler(_read_f64(f), _read_f64(f))
        n.data_min = _read_vec(f)
        n.data_max = _read_vec(f)
        if fit_label:
            n.fit_label = True
            n.label_min = _read_vec(f)
            n.label_max = _read_vec(f)
        return n
    if ntype == "IMAGE_MIN_MAX":
        return N.ImagePreProcessingScaler(
            _read_f64(f), _read_f64(f), _read_f64(f))
    if ntype == "IMAGE_VGG16":
        return N.VGG16ImagePreProcessor()
    if ntype in ("MULTI_STANDARDIZE", "MULTI_MIN_MAX"):
        return _read_multi(f, ntype)
    if ntype == "CUSTOM":
        cls_name = _read_utf(f)
        raise UnsupportedNormalizerException(
            f"normalizer.bin uses a CUSTOM serializer strategy "
            f"({cls_name}); its payload is defined by that class and "
            "cannot be interpreted here")
    if ntype == "MULTI_HYBRID":
        raise UnsupportedNormalizerException(
            "MULTI_HYBRID normalizers mix per-input strategies; only "
            "uniform MULTI_STANDARDIZE / MULTI_MIN_MAX are supported")
    raise ValueError(f"unknown NormalizerType {ntype!r}")


def _read_multi(f: BinaryIO, ntype: str):
    from deeplearning4j_tpu.datasets import normalizers as N

    standardize = ntype == "MULTI_STANDARDIZE"
    fit_label = _read_bool(f)
    n_inputs = _read_i32(f)
    n_outputs = _read_i32(f)
    if n_inputs < 0 or n_inputs > 10_000:
        raise ValueError(f"implausible normalizer input count {n_inputs}")
    # n_outputs is a -1 sentinel when fitLabel is false, so the bound is
    # conditional; a corrupt fitLabel stream must fail fast here rather
    # than loop reading label children until a truncation error
    if fit_label and (n_outputs < 0 or n_outputs > 10_000):
        raise ValueError(f"implausible normalizer output count {n_outputs}")
    kwargs = {}
    if not standardize:
        kwargs = {"min_range": _read_f64(f), "max_range": _read_f64(f)}
    m = N.MultiNormalizer("standardize" if standardize else "minmax",
                          **kwargs)

    def read_child():
        c = m._new_child()
        a, b = _read_vec(f), _read_vec(f)
        if standardize:
            c.mean, c.std = a, b
        else:
            c.data_min, c.data_max = a, b
        return c

    m.children = [read_child() for _ in range(n_inputs)]
    if fit_label:
        m.fit_label = True
        m.label_children = [read_child() for _ in range(n_outputs)]
    return m


def normalizer_from_bytes(b: bytes):
    return read_normalizer(io.BytesIO(b))
