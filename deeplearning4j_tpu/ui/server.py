"""UI server: training dashboard + remote stats receiver.

Replaces the reference's Play-framework server
(`deeplearning4j-play/.../PlayUIServer.java:53`) and its remote receiver
module (`ui/module/remote/RemoteReceiverModule.java`) with a dependency-free
stdlib ``http.server``: JSON endpoints backed by a :class:`StatsStorage`, a
single-page HTML dashboard with inline SVG charts, and a POST endpoint that
ingests remote :class:`Persistable` records.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional
from urllib.parse import urlparse

from deeplearning4j_tpu.ui.stats import TYPE_ID
from deeplearning4j_tpu.ui.storage import Persistable, StatsStorage

_DASHBOARD_HTML = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>deeplearning4j_tpu training UI</title>
<style>
 body{font-family:sans-serif;margin:20px;background:#fafafa}
 h1{font-size:20px} h2{font-size:16px;margin-top:24px}
 .chart{border:1px solid #ccc;background:#fff;margin:4px}
 table{border-collapse:collapse;font-size:13px}
 td,th{border:1px solid #ddd;padding:4px 8px}
</style></head>
<body>
<h1>deeplearning4j_tpu training UI</h1>
<div id="sessions"></div>
<h2>Score vs iteration</h2><svg id="score" class="chart" width="720" height="260"></svg>
<h2>Parameter mean magnitudes</h2><svg id="params" class="chart" width="720" height="260"></svg>
<h2>Latest stats</h2><div id="latest"></div>
<h2 data-i18n="train.model.title">Model: per-layer detail</h2>
<div><span data-i18n="train.model.layer">Layer</span>:
 <select id="layersel"></select></div>
<h3 data-i18n="train.model.paramhist">Parameter magnitudes over time</h3>
<svg id="layerparams" class="chart" width="720" height="220"></svg>
<h3 data-i18n="train.model.ratio">Update:parameter ratio (log10)</h3>
<svg id="layerratio" class="chart" width="720" height="220"></svg>
<script>
const SVGNS = "http://www.w3.org/2000/svg";
function polyline(svg, xs, ys, color){
  if (xs.length < 2) return;
  const w = svg.width.baseVal.value, h = svg.height.baseVal.value, pad = 30;
  const xmin=Math.min(...xs), xmax=Math.max(...xs), ymin=Math.min(...ys), ymax=Math.max(...ys);
  const sx = x => pad + (w-2*pad) * (x - xmin) / Math.max(xmax - xmin, 1e-9);
  const sy = y => h - pad - (h-2*pad) * (y - ymin) / Math.max(ymax - ymin, 1e-9);
  const pl = document.createElementNS(SVGNS, "polyline");
  pl.setAttribute("points", xs.map((x,i)=>sx(x)+","+sy(ys[i])).join(" "));
  pl.setAttribute("fill","none"); pl.setAttribute("stroke",color); pl.setAttribute("stroke-width","1.5");
  svg.appendChild(pl);
}
async function refresh(){
  const sessions = await (await fetch("/train/sessions")).json();
  document.getElementById("sessions").textContent = "Sessions: " + sessions.join(", ");
  if (!sessions.length) return;
  const sid = sessions[sessions.length-1];
  const data = await (await fetch("/train/overview/" + sid)).json();
  const svg = document.getElementById("score"); svg.innerHTML = "";
  polyline(svg, data.iterations, data.scores, "#1565c0");
  const psvg = document.getElementById("params"); psvg.innerHTML = "";
  const colors = ["#1565c0","#c62828","#2e7d32","#f9a825","#6a1b9a","#00838f"];
  let ci = 0;
  for (const [name, series] of Object.entries(data.param_mean_magnitudes)){
    polyline(psvg, data.iterations.slice(-series.length), series, colors[ci++ % colors.length]);
  }
  const latest = data.latest || {};
  document.getElementById("latest").innerHTML =
    "<table><tr><th>iteration</th><td>"+latest.iteration+"</td></tr>" +
    "<tr><th>score</th><td>"+latest.score+"</td></tr>" +
    "<tr><th>minibatch</th><td>"+latest.minibatch_size+"</td></tr></table>";
  await refreshModel(sid);
}
async function refreshModel(sid){
  const model = await (await fetch("/train/model/" + sid)).json();
  const sel = document.getElementById("layersel");
  const current = sel.value;
  sel.innerHTML = "";
  for (const n of model.layer_names){
    const o = document.createElement("option"); o.value = n; o.textContent = n;
    sel.appendChild(o);
  }
  if (model.layer_names.includes(current)) sel.value = current;
  if (!sel.value) return;
  const det = await (await fetch("/train/model/" + sid + "/" + sel.value)).json();
  const colors = ["#1565c0","#c62828","#2e7d32","#f9a825","#6a1b9a","#00838f"];
  const ps = document.getElementById("layerparams"); ps.innerHTML = "";
  let ci = 0;
  for (const [p, s] of Object.entries(det.param_mean_magnitudes))
    polyline(ps, det.iterations.slice(-s.length), s, colors[ci++ % colors.length]);
  const rs = document.getElementById("layerratio"); rs.innerHTML = "";
  ci = 0;
  for (const [p, pairs] of Object.entries(det.update_param_ratio_log10))
    polyline(rs, pairs.map(x=>x[0]), pairs.map(x=>x[1]), colors[ci++ % colors.length]);
}
async function applyI18n(lang){
  const t = await (await fetch("/i18n/" + lang)).json();
  for (const el of document.querySelectorAll("[data-i18n]")){
    const k = el.getAttribute("data-i18n");
    if (t[k]) el.textContent = t[k];
  }
}
applyI18n((new URLSearchParams(location.search)).get("lang") || "en");
document.getElementById("layersel").addEventListener("change", () => refresh());
refresh(); setInterval(refresh, 3000);
</script></body></html>
"""


# i18n string tables (reference: deeplearning4j-play i18n resources /
# DefaultI18N): the dashboard fetches /i18n/<lang> and re-labels headings.
I18N = {
    "en": {
        "train.title": "deeplearning4j_tpu training UI",
        "train.sessions": "Sessions",
        "train.score.title": "Score vs iteration",
        "train.params.title": "Parameter mean magnitudes",
        "train.latest.title": "Latest stats",
        "train.model.title": "Model: per-layer detail",
        "train.model.layer": "Layer",
        "train.model.paramhist": "Parameter magnitudes over time",
        "train.model.ratio": "Update:parameter ratio (log10)",
        "train.iteration": "iteration",
        "train.score": "score",
        "train.minibatch": "minibatch",
    },
    "de": {
        "train.title": "deeplearning4j_tpu Trainings-UI",
        "train.sessions": "Sitzungen",
        "train.score.title": "Score pro Iteration",
        "train.params.title": "Mittlere Parameterbeträge",
        "train.latest.title": "Aktuelle Statistiken",
        "train.model.title": "Modell: Schicht-Detail",
        "train.model.layer": "Schicht",
        "train.model.paramhist": "Parameterbeträge über die Zeit",
        "train.model.ratio": "Update:Parameter-Verhältnis (log10)",
        "train.iteration": "Iteration",
        "train.score": "Score",
        "train.minibatch": "Minibatch",
    },
    "ja": {
        "train.title": "deeplearning4j_tpu トレーニングUI",
        "train.sessions": "セッション",
        "train.score.title": "スコア対イテレーション",
        "train.params.title": "パラメータ平均絶対値",
        "train.latest.title": "最新の統計",
        "train.model.title": "モデル: レイヤー詳細",
        "train.model.layer": "レイヤー",
        "train.model.paramhist": "パラメータ絶対値の推移",
        "train.model.ratio": "更新:パラメータ比 (log10)",
        "train.iteration": "イテレーション",
        "train.score": "スコア",
        "train.minibatch": "ミニバッチ",
    },
}


def _split_param_key(key: str):
    """'0_W' / 'lstm1_RW' flat stat keys → (layer, param)."""
    if "_" in key:
        layer, param = key.rsplit("_", 1)
        return layer, param
    return "model", key


class RemoteReceiverModule:
    """Accepts POSTed Persistable JSON into a storage router
    (``RemoteReceiverModule.java``). Enable/disable mirrors the reference."""

    def __init__(self, router=None, enabled: bool = True):
        self.router = router
        self.enabled = enabled

    def receive(self, body: bytes) -> bool:
        if not self.enabled or self.router is None:
            return False
        rec = json.loads(body.decode("utf-8"))
        p = Persistable(rec["session_id"], rec["type_id"], rec["worker_id"],
                        rec["timestamp"], rec["data"])
        if rec.get("static"):
            self.router.put_static_info(p)
        else:
            self.router.put_update(p)
        return True


class UIServer:
    """Serves the dashboard + JSON API for one or more attached
    StatsStorage instances (``UIServer.getInstance().attach(ss)`` pattern)."""

    _instance: Optional["UIServer"] = None

    def __init__(self, port: int = 9000, metrics=None):
        self.port = port
        self._storages: List[StatsStorage] = []
        self.remote = RemoteReceiverModule(router=None, enabled=False)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        # optional shared observability core (observe.metrics registry):
        # request count/latency land beside the model-serving series
        self._observe = None
        if metrics is not None:
            from deeplearning4j_tpu.observe.metrics import instrument_http
            self._observe = instrument_http(metrics, "ui")

    @classmethod
    def get_instance(cls, port: int = 9000) -> "UIServer":
        if cls._instance is None:
            cls._instance = UIServer(port)
        return cls._instance

    # -- storage attachment ---------------------------------------------
    def attach(self, storage: StatsStorage) -> None:
        if storage not in self._storages:
            self._storages.append(storage)

    def detach(self, storage: StatsStorage) -> None:
        if storage in self._storages:
            self._storages.remove(storage)

    def enable_remote_listener(self, router=None) -> None:
        """Route POST /remote into the given router (default: first attached
        storage)."""
        self.remote.router = router or (self._storages[0] if self._storages else None)
        self.remote.enabled = self.remote.router is not None

    # -- data assembly ---------------------------------------------------
    def _sessions(self) -> List[str]:
        out = []
        for s in self._storages:
            out.extend(s.list_session_ids())
        return sorted(set(out))

    def _overview(self, sid: str) -> dict:
        updates: List[Persistable] = []
        for s in self._storages:
            for wid in s.list_worker_ids_for_session(sid, TYPE_ID):
                updates.extend(s.get_all_updates_after(sid, TYPE_ID, -1.0, wid))
        updates.sort(key=lambda p: (p.data.get("iteration", 0), p.timestamp))
        iterations = [p.data.get("iteration", 0) for p in updates]
        scores = [p.data.get("score", 0.0) for p in updates]
        pmm: dict = {}
        for p in updates:
            for name, st in (p.data.get("param_stats") or {}).items():
                pmm.setdefault(name, []).append(st.get("mean_magnitude", 0.0))
        return {
            "session": sid,
            "iterations": iterations,
            "scores": scores,
            "param_mean_magnitudes": pmm,
            "latest": updates[-1].data if updates else None,
        }

    def _updates(self, sid: str) -> List[Persistable]:
        updates: List[Persistable] = []
        for s in self._storages:
            for wid in s.list_worker_ids_for_session(sid, TYPE_ID):
                updates.extend(s.get_all_updates_after(sid, TYPE_ID, -1.0, wid))
        updates.sort(key=lambda p: (p.data.get("iteration", 0), p.timestamp))
        return updates

    def _model(self, sid: str) -> dict:
        """Per-layer summary (the reference TrainModule 'model' tab): layer
        list with each parameter's latest stats and learning rate."""
        updates = self._updates(sid)
        layers: dict = {}
        latest = updates[-1].data if updates else {}
        for key, st in (latest.get("param_stats") or {}).items():
            layer, param = _split_param_key(key)
            layers.setdefault(layer, {"params": {}, "learning_rates": {}})
            layers[layer]["params"][param] = st
        for key, lr in (latest.get("learning_rates") or {}).items():
            layer, param = _split_param_key(key)
            layers.setdefault(layer, {"params": {}, "learning_rates": {}})
            layers[layer]["learning_rates"][param] = lr
        # numeric-aware ordering: MLN layer indices sort 0,1,2,...,10 — not
        # lexicographically
        names = sorted(layers, key=lambda n: (0, int(n)) if n.isdigit()
                       else (1, n))
        return {"session": sid, "layers": layers, "layer_names": names}

    def _layer_detail(self, sid: str, layer: str) -> dict:
        """Drill-down time series for one layer: per-param mean-magnitude
        series for params/gradients/updates, the update:param ratio (the
        reference's headline training-health chart), and latest histograms
        when the listener collects them."""
        updates = self._updates(sid)
        iterations, series, gseries, ratio = [], {}, {}, {}
        hist = {}
        for p in updates:
            it = p.data.get("iteration", 0)
            ps = p.data.get("param_stats") or {}
            gs = p.data.get("gradient_stats") or {}
            us = p.data.get("update_stats") or {}
            touched = False
            for key, st in ps.items():
                lname, param = _split_param_key(key)
                if lname != layer:
                    continue
                touched = True
                series.setdefault(param, []).append(st.get("mean_magnitude", 0.0))
                if "histogram" in st:
                    hist[param] = st["histogram"]
                u = us.get(key)
                if u is not None:
                    import math
                    pm = st.get("mean_magnitude", 0.0)
                    um = u.get("mean_magnitude", 0.0)
                    # [iteration, value] pairs: update stats may be reported
                    # intermittently, so the ratio carries its own x-values
                    ratio.setdefault(param, []).append(
                        [it, math.log10(max(um, 1e-12) / max(pm, 1e-12))])
            for key, st in gs.items():
                lname, param = _split_param_key(key)
                if lname == layer:
                    gseries.setdefault(param, []).append(
                        st.get("mean_magnitude", 0.0))
            if touched:
                iterations.append(it)
        return {"session": sid, "layer": layer, "iterations": iterations,
                "param_mean_magnitudes": series,
                "gradient_mean_magnitudes": gseries,
                "update_param_ratio_log10": ratio,
                "histograms": hist}

    # -- http -------------------------------------------------------------
    def start(self) -> int:
        """Start serving on self.port (0 → ephemeral); returns the bound port."""
        ui = self

        from deeplearning4j_tpu.observe.metrics import HTTPObserverMixin

        class Handler(HTTPObserverMixin, BaseHTTPRequestHandler):
            observe = ui._observe

            @staticmethod
            def route_label(path):
                # first two path segments only (bounded cardinality:
                # session/layer ids stay out of labels)
                return "/" + "/".join([p for p in path.split("/") if p][:2])

            def _json(self, obj, code=200):
                body = json.dumps(obj).encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _try_modules(self, path, method) -> bool:
                for prefix, module in getattr(ui, "_modules", {}).items():
                    if path == prefix or path.startswith(prefix + "/"):
                        body = None
                        if method == "POST":
                            n = int(self.headers.get("Content-Length", "0"))
                            body = self.rfile.read(n)
                        try:
                            code, payload = module.handle(path, method, body)
                        except (KeyError, ValueError, TypeError) as e:
                            self._json({"error": str(e)}, 400)  # bad request
                            return True
                        except Exception as e:  # module bug → server error,
                            self._json({"error": str(e)}, 500)  # not a
                            return True                         # dropped conn
                        self._json(payload, code)
                        return True
                return False

            def do_GET(self):
                path = urlparse(self.path).path
                if self._try_modules(path, "GET"):
                    return
                if path in ("/", "/train", "/train/overview"):
                    body = _DASHBOARD_HTML.encode("utf-8")
                    self.send_response(200)
                    self.send_header("Content-Type", "text/html; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif path == "/train/sessions":
                    self._json(ui._sessions())
                elif path.startswith("/train/overview/"):
                    sid = path.rsplit("/", 1)[-1]
                    self._json(ui._overview(sid))
                elif path.startswith("/train/model/"):
                    parts = [p for p in path.split("/") if p][2:]
                    if len(parts) == 1:
                        self._json(ui._model(parts[0]))
                    elif len(parts) == 2:
                        self._json(ui._layer_detail(parts[0], parts[1]))
                    else:
                        self._json({"error": "not found"}, 404)
                elif path == "/i18n" or path == "/i18n/":
                    self._json(sorted(I18N))
                elif path.startswith("/i18n/"):
                    lang = path.rsplit("/", 1)[-1]
                    self._json(I18N.get(lang, I18N["en"]))
                else:
                    self._json({"error": "not found"}, 404)

            def do_POST(self):
                path = urlparse(self.path).path
                if self._try_modules(path, "POST"):
                    return
                if path == "/remote":
                    n = int(self.headers.get("Content-Length", "0"))
                    try:
                        ok = ui.remote.receive(self.rfile.read(n))
                    except (KeyError, ValueError, UnicodeDecodeError) as e:
                        self._json({"error": str(e)}, 400)
                        return
                    self._json({"status": "ok" if ok else "disabled"},
                               200 if ok else 403)
                else:
                    self._json({"error": "not found"}, 404)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
