"""Standalone chart/report components.

Parity with ``deeplearning4j-ui-components`` (2.2k LoC of Java component
classes + bundled JS renderers): serializable building blocks — line/scatter
charts, histograms, tables, text — that render to JSON (for a frontend) or
directly to self-contained SVG/HTML (no JS dependency), and compose into a
page. Used standalone or embedded in the UIServer dashboard.
"""

from __future__ import annotations

import html
import json
from typing import List, Optional, Sequence, Tuple

_PALETTE = ("#1565c0", "#c62828", "#2e7d32", "#f9a825", "#6a1b9a", "#00838f")


class StyleChart:
    """Chart styling (``StyleChart.java``): sizes, margins, colors."""

    def __init__(self, width: int = 640, height: int = 300, margin: int = 40,
                 colors: Sequence[str] = _PALETTE, title_size: int = 14):
        self.width = width
        self.height = height
        self.margin = margin
        self.colors = list(colors)
        self.title_size = title_size

    def to_dict(self):
        return {"width": self.width, "height": self.height,
                "margin": self.margin, "colors": self.colors}


class Component:
    """Base component: JSON for frontends, SVG/HTML for standalone use."""

    component_type = "component"

    def to_dict(self) -> dict:
        raise NotImplementedError

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    def render(self) -> str:
        raise NotImplementedError


class _Chart(Component):
    def __init__(self, title: str = "", style: Optional[StyleChart] = None):
        self.title = title
        self.style = style or StyleChart()

    def _svg_open(self) -> List[str]:
        s = self.style
        parts = [f'<svg xmlns="http://www.w3.org/2000/svg" width="{s.width}" '
                 f'height="{s.height}" font-family="sans-serif">']
        if self.title:
            parts.append(
                f'<text x="{s.width / 2}" y="{s.title_size + 4}" '
                f'text-anchor="middle" font-size="{s.title_size}">'
                f'{html.escape(self.title)}</text>')
        return parts

    def _scales(self, xmin, xmax, ymin, ymax):
        s = self.style
        m = s.margin
        xr = max(xmax - xmin, 1e-12)
        yr = max(ymax - ymin, 1e-12)
        sx = lambda x: m + (s.width - 2 * m) * (x - xmin) / xr
        sy = lambda y: s.height - m - (s.height - 2 * m) * (y - ymin) / yr
        return sx, sy

    def _axes(self, sx, sy, xmin, xmax, ymin, ymax) -> List[str]:
        s = self.style
        out = [f'<line x1="{sx(xmin)}" y1="{sy(ymin)}" x2="{sx(xmax)}" '
               f'y2="{sy(ymin)}" stroke="#888"/>',
               f'<line x1="{sx(xmin)}" y1="{sy(ymin)}" x2="{sx(xmin)}" '
               f'y2="{sy(ymax)}" stroke="#888"/>']
        for frac in (0.0, 0.5, 1.0):
            xv = xmin + frac * (xmax - xmin)
            yv = ymin + frac * (ymax - ymin)
            out.append(f'<text x="{sx(xv)}" y="{s.height - s.margin + 14}" '
                       f'font-size="10" text-anchor="middle">{xv:.3g}</text>')
            out.append(f'<text x="{s.margin - 4}" y="{sy(yv) + 3}" '
                       f'font-size="10" text-anchor="end">{yv:.3g}</text>')
        return out


class ChartLine(_Chart):
    """Multi-series line chart (``ChartLine.java``)."""

    component_type = "chart_line"

    def __init__(self, title: str = "", style: Optional[StyleChart] = None):
        super().__init__(title, style)
        self.series: List[Tuple[str, List[float], List[float]]] = []

    def add_series(self, name: str, x: Sequence[float],
                   y: Sequence[float]) -> "ChartLine":
        if len(x) != len(y):
            raise ValueError("x and y lengths differ")
        self.series.append((name, [float(v) for v in x], [float(v) for v in y]))
        return self

    def to_dict(self):
        return {"type": self.component_type, "title": self.title,
                "style": self.style.to_dict(),
                "series": [{"name": n, "x": x, "y": y}
                           for n, x, y in self.series]}

    def _marks(self, sx, sy, x, y, color) -> List[str]:
        pts = " ".join(f"{sx(a):.1f},{sy(b):.1f}" for a, b in zip(x, y))
        return [f'<polyline points="{pts}" fill="none" stroke="{color}" '
                f'stroke-width="1.5"/>']

    def render(self) -> str:
        parts = self._svg_open()
        xs = [v for _, x, _ in self.series for v in x]
        ys = [v for _, _, y in self.series for v in y]
        if xs and ys:
            sx, sy = self._scales(min(xs), max(xs), min(ys), max(ys))
            parts += self._axes(sx, sy, min(xs), max(xs), min(ys), max(ys))
            for i, (name, x, y) in enumerate(self.series):
                color = self.style.colors[i % len(self.style.colors)]
                parts += self._marks(sx, sy, x, y, color)
                parts.append(f'<text x="{self.style.width - self.style.margin}"'
                             f' y="{self.style.margin + 12 * i}" font-size="10"'
                             f' text-anchor="end" fill="{color}">'
                             f'{html.escape(name)}</text>')
        parts.append("</svg>")
        return "".join(parts)


class ChartScatter(ChartLine):
    """Scatter chart (``ChartScatter.java``): same frame/legend as ChartLine,
    point marks instead of a polyline."""

    component_type = "chart_scatter"

    def _marks(self, sx, sy, x, y, color) -> List[str]:
        return [f'<circle cx="{sx(a):.1f}" cy="{sy(b):.1f}" r="2.5" '
                f'fill="{color}"/>' for a, b in zip(x, y)]


class ChartHistogram(_Chart):
    """Histogram from (low, high, count) bins (``ChartHistogram.java``)."""

    component_type = "chart_histogram"

    def __init__(self, title: str = "", style: Optional[StyleChart] = None):
        super().__init__(title, style)
        self.bins: List[Tuple[float, float, float]] = []

    def add_bin(self, low: float, high: float, count: float) -> "ChartHistogram":
        self.bins.append((float(low), float(high), float(count)))
        return self

    @classmethod
    def from_values(cls, values, n_bins: int = 20, title: str = "",
                    style: Optional[StyleChart] = None) -> "ChartHistogram":
        import numpy as np
        counts, edges = np.histogram(np.asarray(values).ravel(), bins=n_bins)
        chart = cls(title, style)
        for i, c in enumerate(counts):
            chart.add_bin(edges[i], edges[i + 1], float(c))
        return chart

    def to_dict(self):
        return {"type": self.component_type, "title": self.title,
                "style": self.style.to_dict(),
                "bins": [{"low": l, "high": h, "count": c}
                         for l, h, c in self.bins]}

    def render(self) -> str:
        parts = self._svg_open()
        if self.bins:
            xmin = min(b[0] for b in self.bins)
            xmax = max(b[1] for b in self.bins)
            ymax = max(b[2] for b in self.bins)
            sx, sy = self._scales(xmin, xmax, 0.0, ymax)
            parts += self._axes(sx, sy, xmin, xmax, 0.0, ymax)
            color = self.style.colors[0]
            for low, high, count in self.bins:
                x0, x1 = sx(low), sx(high)
                y0, y1 = sy(count), sy(0.0)
                parts.append(
                    f'<rect x="{x0:.1f}" y="{y0:.1f}" width="{x1 - x0:.1f}" '
                    f'height="{y1 - y0:.1f}" fill="{color}" fill-opacity="0.8"'
                    f' stroke="#fff" stroke-width="0.5"/>')
        parts.append("</svg>")
        return "".join(parts)


class ChartHorizontalBar(_Chart):
    """Horizontal bar chart (``ChartHorizontalBar.java``): one bar per
    named category."""

    component_type = "chart_horizontal_bar"

    def __init__(self, title: str = "", style: Optional[StyleChart] = None):
        super().__init__(title, style)
        self.bars: List[Tuple[str, float]] = []

    def add_bar(self, name: str, value: float) -> "ChartHorizontalBar":
        self.bars.append((name, float(value)))
        return self

    def to_dict(self):
        return {"type": self.component_type, "title": self.title,
                "style": self.style.to_dict(),
                "bars": [{"name": n, "value": v} for n, v in self.bars]}

    def render(self) -> str:
        parts = self._svg_open()
        if self.bars:
            s = self.style
            m = s.margin
            vmin = min(0.0, min(v for _, v in self.bars))
            vmax = max(0.0, max(v for _, v in self.bars))
            vr = max(vmax - vmin, 1e-12)
            band = (s.height - 2 * m) / len(self.bars)
            x_of = lambda v: m + (s.width - 2 * m) * (v - vmin) / vr
            for i, (name, v) in enumerate(self.bars):
                color = s.colors[i % len(s.colors)]
                y0 = m + i * band
                x0, x1 = sorted((x_of(0.0), x_of(v)))
                parts.append(
                    f'<rect x="{x0:.1f}" y="{y0:.1f}" width="{x1 - x0:.1f}" '
                    f'height="{band * 0.8:.1f}" fill="{color}"/>')
                parts.append(
                    f'<text x="{m - 4}" y="{y0 + band * 0.5:.1f}" '
                    f'font-size="10" text-anchor="end">{html.escape(name)}'
                    f"</text>")
                parts.append(
                    f'<text x="{x1 + 4:.1f}" y="{y0 + band * 0.5:.1f}" '
                    f'font-size="10">{v:.3g}</text>')
        parts.append("</svg>")
        return "".join(parts)


class ChartStackedArea(_Chart):
    """Stacked area chart (``ChartStackedArea.java``): series share an
    x-axis and stack additively."""

    component_type = "chart_stacked_area"

    def __init__(self, title: str = "", style: Optional[StyleChart] = None):
        super().__init__(title, style)
        self.x: List[float] = []
        self.series: List[Tuple[str, List[float]]] = []

    def set_x_values(self, x: Sequence[float]) -> "ChartStackedArea":
        self.x = [float(v) for v in x]
        return self

    def add_series(self, name: str, y: Sequence[float]) -> "ChartStackedArea":
        if len(y) != len(self.x):
            raise ValueError("series length must match x values")
        self.series.append((name, [float(v) for v in y]))
        return self

    def to_dict(self):
        return {"type": self.component_type, "title": self.title,
                "style": self.style.to_dict(), "x": self.x,
                "series": [{"name": n, "y": y} for n, y in self.series]}

    def render(self) -> str:
        parts = self._svg_open()
        if self.x and self.series:
            totals = [sum(y[i] for _, y in self.series)
                      for i in range(len(self.x))]
            sx, sy = self._scales(min(self.x), max(self.x), 0.0, max(totals))
            parts += self._axes(sx, sy, min(self.x), max(self.x), 0.0,
                                max(totals))
            base = [0.0] * len(self.x)
            for i, (name, y) in enumerate(self.series):
                color = self.style.colors[i % len(self.style.colors)]
                top = [b + v for b, v in zip(base, y)]
                fwd = [f"{sx(a):.1f},{sy(b):.1f}"
                       for a, b in zip(self.x, top)]
                back = [f"{sx(a):.1f},{sy(b):.1f}"
                        for a, b in zip(reversed(self.x), reversed(base))]
                parts.append(f'<polygon points="{" ".join(fwd + back)}" '
                             f'fill="{color}" fill-opacity="0.7" '
                             f'stroke="{color}"/>')
                parts.append(
                    f'<text x="{self.style.width - self.style.margin}" '
                    f'y="{self.style.margin + 12 * i}" font-size="10" '
                    f'text-anchor="end" fill="{color}">{html.escape(name)}'
                    f"</text>")
                base = top
        parts.append("</svg>")
        return "".join(parts)


class ChartTimeline(_Chart):
    """Swim-lane timeline (``ChartTimeline.java``): one lane per named
    track, entries are (start, end, label) spans."""

    component_type = "chart_timeline"

    def __init__(self, title: str = "", style: Optional[StyleChart] = None):
        super().__init__(title, style)
        self.lanes: List[Tuple[str, List[Tuple[float, float, str]]]] = []

    def add_lane(self, name: str,
                 entries: Sequence[Tuple[float, float, str]]) -> "ChartTimeline":
        self.lanes.append(
            (name, [(float(a), float(b), str(l)) for a, b, l in entries]))
        return self

    def to_dict(self):
        return {"type": self.component_type, "title": self.title,
                "style": self.style.to_dict(),
                "lanes": [{"name": n,
                           "entries": [{"start": a, "end": b, "label": l}
                                       for a, b, l in es]}
                          for n, es in self.lanes]}

    def render(self) -> str:
        parts = self._svg_open()
        spans = [e for _, es in self.lanes for e in es]
        if spans:
            s = self.style
            m = s.margin
            tmin = min(a for a, _, _ in spans)
            tmax = max(b for _, b, _ in spans)
            tr = max(tmax - tmin, 1e-12)
            band = (s.height - 2 * m) / len(self.lanes)
            x_of = lambda t: m + (s.width - 2 * m) * (t - tmin) / tr
            for i, (name, entries) in enumerate(self.lanes):
                y0 = m + i * band
                parts.append(f'<text x="{m - 4}" y="{y0 + band * 0.5:.1f}" '
                             f'font-size="10" text-anchor="end">'
                             f"{html.escape(name)}</text>")
                for j, (a, b, label) in enumerate(entries):
                    color = s.colors[j % len(s.colors)]
                    parts.append(
                        f'<rect x="{x_of(a):.1f}" y="{y0:.1f}" '
                        f'width="{max(x_of(b) - x_of(a), 1.0):.1f}" '
                        f'height="{band * 0.8:.1f}" fill="{color}" '
                        f'fill-opacity="0.8"><title>{html.escape(label)}'
                        f"</title></rect>")
        parts.append("</svg>")
        return "".join(parts)


class DecoratorAccordion(Component):
    """Collapsible section wrapping child components
    (``DecoratorAccordion.java``); renders as <details>/<summary>."""

    component_type = "decorator_accordion"

    def __init__(self, title: str = "", default_collapsed: bool = False,
                 *children: Component):
        self.title = title
        self.default_collapsed = default_collapsed
        self.children = list(children)

    def add(self, child: Component) -> "DecoratorAccordion":
        self.children.append(child)
        return self

    def to_dict(self):
        return {"type": self.component_type, "title": self.title,
                "default_collapsed": self.default_collapsed,
                "children": [c.to_dict() for c in self.children]}

    def render(self) -> str:
        open_attr = "" if self.default_collapsed else " open"
        inner = "".join(c.render() for c in self.children)
        return (f"<details{open_attr}><summary>{html.escape(self.title)}"
                f"</summary>{inner}</details>")


class ComponentTable(Component):
    """Simple table (``ComponentTable.java``)."""

    component_type = "component_table"

    def __init__(self, header: Sequence[str], rows: Sequence[Sequence]):
        self.header = list(header)
        self.rows = [list(r) for r in rows]

    def to_dict(self):
        return {"type": self.component_type, "header": self.header,
                "rows": [[str(c) for c in r] for r in self.rows]}

    def render(self) -> str:
        th = "".join(f"<th>{html.escape(str(h))}</th>" for h in self.header)
        trs = "".join(
            "<tr>" + "".join(f"<td>{html.escape(str(c))}</td>" for c in r)
            + "</tr>" for r in self.rows)
        return (f'<table border="1" cellspacing="0" cellpadding="4">'
                f"<tr>{th}</tr>{trs}</table>")


class ComponentText(Component):
    """Text block (``ComponentText.java``)."""

    component_type = "component_text"

    def __init__(self, text: str):
        self.text = text

    def to_dict(self):
        return {"type": self.component_type, "text": self.text}

    def render(self) -> str:
        return f"<p>{html.escape(self.text)}</p>"


class ComponentDiv(Component):
    """Container composing children into one HTML page
    (``ComponentDiv.java``)."""

    component_type = "component_div"

    def __init__(self, *children: Component):
        self.children = list(children)

    def add(self, child: Component) -> "ComponentDiv":
        self.children.append(child)
        return self

    def to_dict(self):
        return {"type": self.component_type,
                "children": [c.to_dict() for c in self.children]}

    def render(self) -> str:
        return "<div>" + "".join(c.render() for c in self.children) + "</div>"

    def render_page(self, title: str = "report") -> str:
        return (f"<!DOCTYPE html><html><head><meta charset='utf-8'>"
                f"<title>{html.escape(title)}</title></head><body>"
                f"{self.render()}</body></html>")
