"""Extra UI modules: t-SNE view, convolutional activations, timeline export.

Parity with the reference's Play UI modules beyond train/overview
(`ui/module/tsne/TsneModule.java` — upload/serve t-SNE coordinate sets;
`ui/module/convolutional/ConvolutionalListenerModule.java` +
`deeplearning4j-ui-remote-iterationlisteners/.../RemoteConvolutionalIterationListener.java`
— stream layer activations during training; `spark/stats/StatsUtils.java` —
exportable timeline HTML). Each module plugs into :class:`UIServer` via
``register_module`` and answers under its own path prefix.
"""

from __future__ import annotations

import json
from typing import Dict, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.optimize.listeners import TrainingListener
from deeplearning4j_tpu.ui.components import (
    ChartLine,
    ChartScatter,
    ComponentDiv,
    ComponentTable,
    ComponentText,
)


class TsneModule:
    """Holds named 2-D coordinate sets and serves them as JSON or an SVG page
    (``TsneModule.java`` upload/list/get routes)."""

    prefix = "/tsne"

    def __init__(self):
        self._sets: Dict[str, dict] = {}

    def upload(self, name: str, coords, labels: Optional[Sequence[str]] = None):
        coords = np.asarray(coords, np.float32)
        if coords.ndim != 2 or coords.shape[1] != 2:
            raise ValueError(f"expected [n, 2] coordinates, got {coords.shape}")
        self._sets[name] = {
            "coords": coords.tolist(),
            "labels": list(labels) if labels is not None else None,
        }

    def handle(self, path: str, method: str = "GET",
               body: Optional[bytes] = None):
        """(status, payload) for a request under the module prefix."""
        sub = path[len(self.prefix):].strip("/")
        if method == "POST":
            rec = json.loads((body or b"{}").decode())
            self.upload(rec["name"], rec["coords"], rec.get("labels"))
            return 200, {"status": "ok"}
        if not sub:  # list sessions
            return 200, sorted(self._sets)
        if sub in self._sets:
            return 200, self._sets[sub]
        return 404, {"error": f"no t-SNE set {sub!r}"}

    def render_svg(self, name: str) -> str:
        data = self._sets[name]
        coords = np.asarray(data["coords"])
        chart = ChartScatter(title=f"t-SNE: {name}")
        labels = data["labels"]
        if labels:
            for lab in sorted(set(labels)):
                idx = [i for i, l in enumerate(labels) if l == lab]
                chart.add_series(str(lab), coords[idx, 0].tolist(),
                                 coords[idx, 1].tolist())
        else:
            chart.add_series("points", coords[:, 0].tolist(),
                             coords[:, 1].tolist())
        return chart.render()


class ConvolutionalListenerModule(TrainingListener):
    """Captures per-layer activation summaries during training and serves
    them (``ConvolutionalListenerModule.java`` role; the reference streams
    PNG grids — here compact per-channel statistics cross the wire, not
    pixels). Attach to ``net.listeners`` and register with the UIServer."""

    prefix = "/activations"

    def __init__(self, sample_input=None, frequency: int = 10,
                 max_channels: int = 16):
        self.sample_input = sample_input
        self.frequency = max(1, frequency)
        self.max_channels = max_channels
        self.latest: Dict[str, dict] = {}

    def iteration_done(self, model, iteration: int, epoch: int) -> None:
        if iteration % self.frequency != 0 or self.sample_input is None:
            return
        try:
            acts = model.feed_forward(self.sample_input)
        except Exception as e:
            from deeplearning4j_tpu.optimize.listeners import OneTimeLogger
            OneTimeLogger.warn(
                "ConvolutionalListenerModule: feed_forward on the sample "
                "input failed (%s); activations will stay empty", e)
            return
        layers = getattr(model, "layers", [])
        summary = {}
        for i, a in enumerate(acts[1:]):
            a = np.asarray(a)
            name = (layers[i].name if i < len(layers) and layers[i].name
                    else f"layer{i}")
            entry = {"shape": list(a.shape), "mean": float(a.mean()),
                     "std": float(a.std())}
            if a.ndim == 4:  # [N,H,W,C]: per-channel mean magnitude
                per_ch = np.abs(a[0]).mean(axis=(0, 1))
                entry["channel_means"] = per_ch[:self.max_channels].tolist()
            summary[name] = entry
        self.latest = {"iteration": iteration, "layers": summary}

    def handle(self, path: str, method: str = "GET",
               body: Optional[bytes] = None):
        return 200, self.latest


class CalibrationModule:
    """Serves an :class:`~deeplearning4j_tpu.eval.calibration.EvaluationCalibration`
    as JSON + a rendered panel — the calibration views the reference's
    train UI builds from ``EvaluationCalibration``'s per-class reliability
    diagrams, residual plots, and probability histograms.

    Routes under ``/calibration``:
      ``/calibration``            → summary (ECE, classes, label counts)
      ``/calibration/reliability/<c>`` → per-class reliability diagram JSON
      ``/calibration/residual``   / ``/residual/<c>`` → residual histograms
      ``/calibration/probabilities`` / ``/probabilities/<c>`` → prob hists
      ``/calibration/panel``      → standalone SVG/HTML panel
    """

    prefix = "/calibration"

    def __init__(self, calibration=None):
        self._cal = calibration

    def attach(self, calibration) -> None:
        self._cal = calibration

    def handle(self, path: str, method: str = "GET",
               body: Optional[bytes] = None):
        cal = self._cal
        if cal is None or cal.num_classes < 0:
            return 404, {"error": "no calibration evaluation attached"}
        sub = path[len(self.prefix):].strip("/")
        parts = sub.split("/") if sub else []
        if not parts:
            return 200, {
                "num_classes": cal.num_classes,
                "expected_calibration_error": cal.expected_calibration_error(),
                "label_counts": [int(v) for v in cal.label_counts],
                "prediction_counts": [int(v) for v in cal.prediction_counts],
            }
        kind = parts[0]
        cls = int(parts[1]) if len(parts) > 1 else None
        if cls is not None and not (0 <= cls < cal.num_classes):
            return 404, {"error": f"class index {cls} out of range "
                                  f"[0, {cal.num_classes})"}
        if kind == "reliability" and cls is not None:
            return 200, cal.get_reliability_diagram(cls).to_dict()
        if kind == "residual":
            h = (cal.get_residual_plot(cls) if cls is not None
                 else cal.get_residual_plot_all_classes())
            return 200, h.to_dict()
        if kind == "probabilities":
            h = (cal.get_probability_histogram(cls) if cls is not None
                 else cal.get_probability_histogram_all_classes())
            return 200, h.to_dict()
        if kind == "panel":
            return 200, {"html": self.render_panel()}
        return 404, {"error": f"unknown calibration route {sub!r}"}

    def render_panel(self) -> str:
        """Standalone page: reliability curves + per-class histograms."""
        from deeplearning4j_tpu.ui.components import ChartHistogram
        cal = self._cal
        page = ComponentDiv(ComponentText(
            f"Calibration — ECE {cal.expected_calibration_error():.4f}"))
        rel = ChartLine(title="Reliability (all classes pooled)")
        mean_p, obs = cal.reliability_diagram()
        rel.add_series("observed", [float(v) for v in mean_p],
                       [float(v) for v in obs])
        rel.add_series("ideal", [0.0, 1.0], [0.0, 1.0])
        page.add(rel)
        for c in range(cal.num_classes):
            d = cal.get_reliability_diagram(c)
            line = ChartLine(title=d.title)
            line.add_series(f"class {c}",
                            [float(v) for v in d.mean_predicted_value],
                            [float(v) for v in d.frac_positives])
            page.add(line)
            h = cal.get_probability_histogram(c)
            hist = ChartHistogram(title=h.title)
            edges = h.bin_edges
            for i, count in enumerate(h.counts):
                hist.add_bin(edges[i], edges[i + 1], float(count))
            page.add(hist)
            r = cal.get_residual_plot(c)
            rh = ChartHistogram(title=r.title)
            redges = r.bin_edges
            for i, count in enumerate(r.counts):
                rh.add_bin(redges[i], redges[i + 1], float(count))
            page.add(rh)
        return page.render_page("calibration")


class EvaluationModule:
    """Serves an :class:`~deeplearning4j_tpu.eval.evaluation.Evaluation`
    with its metadata-backed error drilldown — the per-record inspection
    the reference exposes via ``Evaluation.getPredictionErrors`` wired to
    a UI surface (the round-2 verdict's "no error-drilldown source" note).

    Routes under ``/evaluation``:
      ``/evaluation``                       → summary metrics + confusion
      ``/evaluation/errors``                → misclassified records (with
                                              RecordMetaData locations)
      ``/evaluation/by-actual/<c>``         → predictions for true class c
      ``/evaluation/by-predicted/<c>``      → predictions predicted as c
      ``/evaluation/cell/<a>/<p>``          → one confusion cell's records
      ``/evaluation/panel``                 → standalone HTML panel
    """

    prefix = "/evaluation"

    def __init__(self, evaluation=None):
        self._eval = evaluation

    def attach(self, evaluation) -> None:
        self._eval = evaluation

    @staticmethod
    def _pred_json(preds):
        out = []
        for p in preds or []:
            meta = p.record_meta_data
            loc = (meta.get_location() if hasattr(meta, "get_location")
                   else str(meta))
            out.append({"actual": p.actual, "predicted": p.predicted,
                        "record": loc})
        return out

    def handle(self, path: str, method: str = "GET",
               body: Optional[bytes] = None):
        ev = self._eval
        if ev is None or ev.confusion is None:
            return 404, {"error": "no evaluation attached"}
        sub = path[len(self.prefix):].strip("/")
        parts = sub.split("/") if sub else []
        if not parts:
            return 200, {
                "num_classes": ev.num_classes,
                "accuracy": ev.accuracy(),
                "top_n": ev.top_n,
                "top_n_accuracy": ev.top_n_accuracy(),
                "precision": ev.precision(),
                "recall": ev.recall(),
                "f1": ev.f1(),
                "confusion": ev.confusion.tolist(),
                "has_metadata": ev.confusion_meta is not None,
            }
        kind = parts[0]
        if kind == "errors":
            errs = ev.get_prediction_errors()
            if errs is None:
                return 404, {"error": "evaluate with collect_meta_data=True "
                                      "to record per-example predictions"}
            return 200, {"errors": self._pred_json(errs)}
        if kind == "by-actual" and len(parts) > 1:
            preds = ev.get_predictions_by_actual_class(int(parts[1]))
            if preds is None:
                return 404, {"error": "no metadata recorded"}
            return 200, {"predictions": self._pred_json(preds)}
        if kind == "by-predicted" and len(parts) > 1:
            preds = ev.get_prediction_by_predicted_class(int(parts[1]))
            if preds is None:
                return 404, {"error": "no metadata recorded"}
            return 200, {"predictions": self._pred_json(preds)}
        if kind == "cell" and len(parts) > 2:
            preds = ev.get_predictions(int(parts[1]), int(parts[2]))
            if preds is None:
                return 404, {"error": "no metadata recorded"}
            return 200, {"predictions": self._pred_json(preds)}
        if kind == "panel":
            return 200, {"html": self.render_panel()}
        return 404, {"error": f"unknown evaluation route {sub!r}"}

    def render_panel(self) -> str:
        """Confusion matrix + error-drilldown table as a standalone page."""
        ev = self._eval
        page = ComponentDiv(ComponentText(
            f"Evaluation — accuracy {ev.accuracy():.4f}, "
            f"F1 {ev.f1():.4f}"
            + (f", top-{ev.top_n} {ev.top_n_accuracy():.4f}"
               if ev.top_n > 1 else "")))
        header = ["actual \\ predicted"] + [str(i) for i
                                            in range(ev.num_classes)]
        rows = [[str(a)] + [int(v) for v in ev.confusion[a]]
                for a in range(ev.num_classes)]
        page.add(ComponentTable(header, rows))
        errs = ev.get_prediction_errors()
        if errs is not None:
            erows = [[p.actual, p.predicted,
                      (p.record_meta_data.get_location()
                       if hasattr(p.record_meta_data, "get_location")
                       else str(p.record_meta_data))] for p in errs[:200]]
            page.add(ComponentText(f"{len(errs)} misclassified records"
                                   + (" (first 200)" if len(errs) > 200
                                      else "")))
            page.add(ComponentTable(["actual", "predicted", "record"],
                                    erows))
        return page.render_page("evaluation")


def timeline_html(stats, title: str = "training timeline") -> str:
    """Exportable timeline page from a TrainingStats (``StatsUtils.java``
    exportTimelineHtml role): per-phase durations as charts + a table."""
    page = ComponentDiv(ComponentText(title))
    rows = []
    for phase, times in stats.phase_times.items():
        rows.append([phase, len(times), f"{sum(times):.4f}",
                     f"{max(times):.4f}" if times else "-"])
        chart = ChartLine(title=f"{phase} duration per call (s)")
        chart.add_series(phase, list(range(len(times))), times)
        page.add(chart)
    page.children.insert(
        1, ComponentTable(["phase", "calls", "total_s", "max_s"], rows))
    return page.render_page(title)


def register_module(server, module) -> None:
    """Attach a module to a UIServer: requests under ``module.prefix`` are
    routed to ``module.handle``."""
    if not hasattr(server, "_modules"):
        server._modules = {}
    server._modules[module.prefix] = module
