"""Remote stats posting: client side of the remote receiver.

Parity with ``RemoteUIStatsStorageRouter`` and
``deeplearning4j-ui-remote-iterationlisteners/.../WebReporter.java``: a
StatsStorageRouter that POSTs each record to a UIServer's ``/remote``
endpoint over HTTP (urllib, retry with backoff), so a training process can
report to a dashboard running elsewhere.
"""

from __future__ import annotations

import json
import logging
import time
import urllib.error
import urllib.request
from typing import Optional

from deeplearning4j_tpu.ui.storage import Persistable, StatsStorageRouter

log = logging.getLogger(__name__)


class UiConnectionInfo:
    """Address builder for a remote UI endpoint
    (``deeplearning4j-core/.../ui/UiConnectionInfo.java``): scheme +
    host:port + path, with a session id query and optional login
    credentials."""

    def __init__(self, address: str = "localhost", port: int = 8080,
                 path: str = "", use_https: bool = False,
                 session_id: Optional[str] = None,
                 login: Optional[str] = None, password: Optional[str] = None):
        import uuid
        self.address = address
        self.port = int(port)
        self.path = path
        self.use_https = use_https
        self.session_id = session_id or str(uuid.uuid4())
        self.login = login
        self.password = password

    def get_first_part(self) -> str:
        scheme = "https" if self.use_https else "http"
        return f"{scheme}://{self.address}:{self.port}"

    def get_second_part(self, n_path: str = "") -> str:
        import re
        out = ""
        if self.path:
            out += (self.path if self.path.startswith("/")
                    else "/" + self.path) + "/"
        if n_path:
            n_path = n_path.lstrip("/")
            out += "/" + n_path + "/"
        return re.sub(r"/{2,}", "/", out)

    def get_full_address(self, n_path: str = "") -> str:
        if not n_path:
            return self.get_first_part() + self.get_second_part()
        return (self.get_first_part() + self.get_second_part(n_path)
                + f"?sid={self.session_id}")


class WebReporter:
    """POST a JSON payload to a URL with retries (``WebReporter.java``)."""

    @staticmethod
    def report_to_url(url: str, payload: dict, retries: int = 3,
                      timeout: float = 5.0, backoff: float = 0.2) -> bool:
        body = json.dumps(payload).encode("utf-8")
        last_err: Optional[Exception] = None
        for attempt in range(retries):
            try:
                req = urllib.request.Request(
                    url, data=body, headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=timeout) as resp:
                    return 200 <= resp.status < 300
            except (urllib.error.URLError, OSError) as e:
                last_err = e
                time.sleep(backoff * (2 ** attempt))
        raise ConnectionError(f"Failed to POST to {url}: {last_err}")


class RemoteUIStatsStorageRouter(StatsStorageRouter):
    """Router that ships records to a remote UIServer ``/remote`` endpoint.

    By default a dashboard outage logs a warning and DROPS the record — a
    stats reporter must never kill training (the reference router behaves the
    same). Set ``raise_on_error=True`` to surface failures instead.
    """

    def __init__(self, url: str, retries: int = 3, timeout: float = 5.0,
                 raise_on_error: bool = False):
        if not url.endswith("/remote"):
            url = url.rstrip("/") + "/remote"
        self.url = url
        self.retries = retries
        self.timeout = timeout
        self.raise_on_error = raise_on_error
        self._warned = False

    def _send(self, p: Persistable, static: bool) -> None:
        payload = {"session_id": p.session_id, "type_id": p.type_id,
                   "worker_id": p.worker_id, "timestamp": p.timestamp,
                   "static": static, "data": p.data}
        try:
            WebReporter.report_to_url(self.url, payload, self.retries,
                                      self.timeout)
        except ConnectionError:
            if self.raise_on_error:
                raise
            if not self._warned:
                self._warned = True
                log.warning("Dropping stats record: cannot reach %s "
                            "(further drops are silent)", self.url)

    def put_static_info(self, p: Persistable) -> None:
        self._send(p, static=True)

    def put_update(self, p: Persistable) -> None:
        self._send(p, static=False)
