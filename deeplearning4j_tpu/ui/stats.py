"""StatsListener: per-iteration training statistics.

Parity with ``deeplearning4j-ui-model/.../stats/BaseStatsListener.java``
(score, learning rates, per-layer parameter / gradient / update histograms,
mean magnitudes and stdevs, memory and runtime info, ``:355-400``), redesigned
so all tensor statistics are computed **on device in one jitted call** per
report and only a few scalars per parameter cross the host boundary — the
reference pulls every histogram through the JVM heap.
"""

from __future__ import annotations

import os
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.optimize.listeners import TrainingListener
from deeplearning4j_tpu.ui.storage import Persistable, StatsStorageRouter

TYPE_ID = "StatsListener"


@dataclass
class StatsUpdateConfiguration:
    """What to collect, how often (``StatsUpdateConfiguration.java``)."""

    report_iterations: int = 1
    collect_score: bool = True
    collect_learning_rates: bool = True
    collect_parameter_stats: bool = True
    collect_gradient_stats: bool = True
    collect_update_stats: bool = True
    collect_histograms: bool = False
    histogram_bin_count: int = 20
    collect_memory: bool = True


@dataclass
class StatsReport:
    """One iteration's stats (the update Persistable payload)."""

    iteration: int
    epoch: int
    timestamp: float
    score: float
    duration_ms: float = 0.0
    minibatch_size: int = 0
    learning_rates: Dict[str, float] = field(default_factory=dict)
    param_stats: Dict[str, Dict[str, float]] = field(default_factory=dict)
    gradient_stats: Dict[str, Dict[str, float]] = field(default_factory=dict)
    update_stats: Dict[str, Dict[str, float]] = field(default_factory=dict)
    histograms: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    memory: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {k: getattr(self, k) for k in (
            "iteration", "epoch", "timestamp", "score", "duration_ms",
            "minibatch_size", "learning_rates", "param_stats",
            "gradient_stats", "update_stats", "histograms", "memory")}


def _tensor_stats_fn(histogram_bins: int, with_hist: bool):
    import jax
    import jax.numpy as jnp

    @jax.jit
    def stats(tree):
        def one(a):
            a = a.astype(jnp.float32)
            out = {
                "mean": jnp.mean(a),
                "stdev": jnp.std(a),
                "mean_magnitude": jnp.mean(jnp.abs(a)),
                "min": jnp.min(a),
                "max": jnp.max(a),
                "norm2": jnp.linalg.norm(a.reshape(-1)),
            }
            if with_hist:
                counts, edges = jnp.histogram(a.reshape(-1), bins=histogram_bins)
                out["hist_counts"] = counts
                out["hist_edges"] = edges
            return out
        return jax.tree_util.tree_map(one, tree,
                                      is_leaf=lambda x: hasattr(x, "shape"))
    return stats


class StatsListener(TrainingListener):
    """Collects stats each ``report_iterations`` and routes them to a
    :class:`StatsStorageRouter` (``BaseStatsListener`` behaviour)."""

    def __init__(self, router: StatsStorageRouter,
                 update_config: Optional[StatsUpdateConfiguration] = None,
                 session_id: Optional[str] = None, worker_id: Optional[str] = None):
        self.router = router
        self.cfg = update_config or StatsUpdateConfiguration()
        self.session_id = session_id or uuid.uuid4().hex[:12]
        self.worker_id = worker_id or f"pid-{os.getpid()}"
        self._stats_fn = None
        self._last_time = None
        self._static_posted = False

    # -- helpers ---------------------------------------------------------
    def _flatten(self, tree) -> Dict[str, Any]:
        """[{'W': .., 'b': ..}, ...] layer list → {'0_W': ..} flat names."""
        out = {}
        if isinstance(tree, (list, tuple)):
            for i, layer in enumerate(tree):
                if isinstance(layer, dict):
                    for n, v in layer.items():
                        if hasattr(v, "shape"):
                            out[f"{i}_{n}"] = v
        elif isinstance(tree, dict):
            for n, v in tree.items():
                if hasattr(v, "shape"):
                    out[n] = v
        return out

    def _compute(self, named: Dict[str, Any]) -> Dict[str, Dict[str, float]]:
        if not named:
            return {}
        if self._stats_fn is None:
            self._stats_fn = _tensor_stats_fn(self.cfg.histogram_bin_count,
                                              self.cfg.collect_histograms)
        raw = self._stats_fn(named)
        out = {}
        for name, st in raw.items():
            entry = {k: float(v) for k, v in st.items()
                     if k not in ("hist_counts", "hist_edges")}
            if self.cfg.collect_histograms:
                entry_h = {"counts": np.asarray(st["hist_counts"]).tolist(),
                           "edges": np.asarray(st["hist_edges"]).tolist()}
                entry["histogram"] = entry_h
            out[name] = entry
        return out

    def _memory_info(self) -> Dict[str, Any]:
        """Host RSS plus JAX device memory when the backend exposes it.

        Device stats aggregate over ALL local devices (the reference's
        per-worker memory report covered every GPU) with a per-device
        breakdown; every probe is guarded per device, so CPU-only CI —
        where ``memory_stats()`` is None or unsupported — reports host
        memory exactly as before."""
        info: Dict[str, Any] = {}
        try:
            import resource
            info["max_rss_kb"] = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        except Exception:
            pass
        try:
            import jax
            devices = jax.local_devices()
        except Exception:
            return info
        per_device = []
        total_in_use = total_limit = 0
        for d in devices:
            try:
                ms = d.memory_stats()
            except Exception:  # backend without memory introspection
                ms = None
            if not ms:
                continue
            in_use = int(ms.get("bytes_in_use", 0))
            limit = int(ms.get("bytes_limit", 0))
            total_in_use += in_use
            total_limit += limit
            entry = {"device": str(d), "bytes_in_use": in_use,
                     "bytes_limit": limit}
            if "peak_bytes_in_use" in ms:
                entry["peak_bytes_in_use"] = int(ms["peak_bytes_in_use"])
            per_device.append(entry)
        if per_device:
            info["device_bytes_in_use"] = total_in_use
            info["device_bytes_limit"] = total_limit
            info["device_count"] = len(per_device)
            info["devices"] = per_device
        return info

    # -- listener hooks --------------------------------------------------
    def iteration_done(self, model, iteration: int, epoch: int) -> None:
        if iteration % max(1, self.cfg.report_iterations) != 0:
            return
        now = time.time()
        if not self._static_posted:
            self._post_static(model, now)
        report = StatsReport(
            iteration=iteration, epoch=epoch, timestamp=now,
            score=float(model.score_) if self.cfg.collect_score else 0.0,
            minibatch_size=getattr(model, "last_batch_size", 0) or 0,
        )
        if self._last_time is not None:
            report.duration_ms = (now - self._last_time) * 1000.0
        self._last_time = now
        if self.cfg.collect_learning_rates:
            report.learning_rates = self._learning_rates(model, iteration, epoch)
        if self.cfg.collect_parameter_stats and getattr(model, "params", None) is not None:
            report.param_stats = self._compute(self._flatten(model.params))
        # gradient/update stats are collected when the model exposes them
        # (the jitted train step keeps gradients on device unless asked)
        grads = getattr(model, "last_gradients", None)
        if self.cfg.collect_gradient_stats and grads is not None:
            report.gradient_stats = self._compute(self._flatten(grads))
        upds = getattr(model, "last_updates", None)
        if self.cfg.collect_update_stats and upds is not None:
            report.update_stats = self._compute(self._flatten(upds))
        if self.cfg.collect_memory:
            report.memory = self._memory_info()
        self.router.put_update(Persistable(
            self.session_id, TYPE_ID, self.worker_id, now, report.to_dict()))

    def _learning_rates(self, model, iteration, epoch) -> Dict[str, float]:
        out = {}
        updaters = getattr(model, "_updaters", None)
        if not updaters:
            return out
        for i, layer_upd in enumerate(updaters):
            if isinstance(layer_upd, dict):
                for n, u in layer_upd.items():
                    try:
                        out[f"{i}_{n}"] = float(u.lr_at(iteration, epoch))
                    except Exception:
                        pass
        return out

    def _post_static(self, model, now: float) -> None:
        self._static_posted = True
        info = {
            "model_class": type(model).__name__,
            "n_layers": len(getattr(model, "layers", []) or []),
            "n_params": 0,
        }
        try:
            info["n_params"] = int(model.conf.num_params())
        except Exception:
            pass
        try:
            import jax
            info["backend"] = jax.default_backend()
            info["devices"] = [str(d) for d in jax.devices()]
        except Exception:
            pass
        self.router.put_static_info(Persistable(
            self.session_id, TYPE_ID, self.worker_id, now, info))
