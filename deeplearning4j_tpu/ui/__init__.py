"""Observability / UI layer (SURVEY.md L6).

Re-design of the reference's stats pipeline — ``BaseStatsListener`` →
SBE-encoded ``Persistable`` → ``StatsStorageRouter`` → storage →
Play web modules (`deeplearning4j-ui-model/.../stats/BaseStatsListener.java`,
`deeplearning4j-core/.../api/storage/StatsStorage.java`,
`deeplearning4j-play/.../PlayUIServer.java:53`) — as plain JSON reports over
a storage SPI served by a dependency-free stdlib HTTP dashboard. Per-layer
parameter/gradient statistics are computed on device in one jitted call and
transferred as a handful of scalars, not whole tensors.
"""

from deeplearning4j_tpu.ui.storage import (  # noqa: F401
    FileStatsStorage,
    InMemoryStatsStorage,
    Persistable,
    StatsStorage,
    StatsStorageEvent,
    StatsStorageListener,
    StatsStorageRouter,
)
from deeplearning4j_tpu.ui.stats import (  # noqa: F401
    StatsListener,
    StatsReport,
    StatsUpdateConfiguration,
)
from deeplearning4j_tpu.ui.server import RemoteReceiverModule, UIServer  # noqa: F401
from deeplearning4j_tpu.ui.remote import RemoteUIStatsStorageRouter, UiConnectionInfo, WebReporter  # noqa: F401
