"""Stats storage SPI and implementations.

Parity with ``deeplearning4j-core/.../api/storage/StatsStorage.java`` (the
transport-agnostic persistence SPI: sessions → type IDs → worker IDs →
timestamped updates, plus static per-session info and change listeners) and
the impls in ``deeplearning4j-ui-model`` (`InMemoryStatsStorage.java`,
`MapDBStatsStorage.java` → here a JSON-lines file store, no native DB).
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, List, Optional


class Persistable:
    """A JSON-serializable record identified by (session, type, worker,
    timestamp) — the reference's SBE-encoded Persistable, minus SBE."""

    def __init__(self, session_id: str, type_id: str, worker_id: str,
                 timestamp: float, data: Dict[str, Any]):
        self.session_id = session_id
        self.type_id = type_id
        self.worker_id = worker_id
        self.timestamp = float(timestamp)
        self.data = data

    def to_json(self) -> str:
        return json.dumps({"session_id": self.session_id, "type_id": self.type_id,
                           "worker_id": self.worker_id, "timestamp": self.timestamp,
                           "data": self.data})

    @staticmethod
    def from_json(s: str) -> "Persistable":
        d = json.loads(s)
        return Persistable(d["session_id"], d["type_id"], d["worker_id"],
                           d["timestamp"], d["data"])


class StatsStorageEvent:
    NEW_SESSION = "new_session"
    NEW_TYPE_ID = "new_type_id"
    NEW_WORKER_ID = "new_worker_id"
    POST_STATIC_INFO = "post_static_info"
    POST_UPDATE = "post_update"

    def __init__(self, kind: str, session_id: str, type_id: Optional[str] = None,
                 worker_id: Optional[str] = None, timestamp: Optional[float] = None):
        self.kind = kind
        self.session_id = session_id
        self.type_id = type_id
        self.worker_id = worker_id
        self.timestamp = timestamp


class StatsStorageListener:
    def notify(self, event: StatsStorageEvent) -> None:  # pragma: no cover
        pass


class StatsStorageRouter:
    """Write-side SPI (``StatsStorageRouter.java``)."""

    def put_static_info(self, p: Persistable) -> None:
        raise NotImplementedError

    def put_update(self, p: Persistable) -> None:
        raise NotImplementedError


class StatsStorage(StatsStorageRouter):
    """Read+write storage (``StatsStorage.java:28``)."""

    def __init__(self):
        self._static: Dict[tuple, Persistable] = {}
        self._updates: Dict[tuple, List[Persistable]] = {}
        self._listeners: List[StatsStorageListener] = []
        self._lock = threading.Lock()
        self._closed = False

    # -- write ----------------------------------------------------------
    def put_static_info(self, p: Persistable) -> None:
        with self._lock:
            is_new_session = not self._session_exists_unlocked(p.session_id)
            self._static[(p.session_id, p.type_id, p.worker_id)] = p
            self._persist(p, static=True)
        if is_new_session:
            self._notify(StatsStorageEvent(StatsStorageEvent.NEW_SESSION, p.session_id))
        self._notify(StatsStorageEvent(StatsStorageEvent.POST_STATIC_INFO,
                                       p.session_id, p.type_id, p.worker_id))

    def put_update(self, p: Persistable) -> None:
        with self._lock:
            is_new_session = not self._session_exists_unlocked(p.session_id)
            key = (p.session_id, p.type_id, p.worker_id)
            self._updates.setdefault(key, []).append(p)
            self._persist(p, static=False)
        if is_new_session:
            self._notify(StatsStorageEvent(StatsStorageEvent.NEW_SESSION, p.session_id))
        self._notify(StatsStorageEvent(StatsStorageEvent.POST_UPDATE,
                                       p.session_id, p.type_id, p.worker_id,
                                       p.timestamp))

    def _persist(self, p: Persistable, static: bool) -> None:
        pass  # overridden by file-backed storage

    # -- read -----------------------------------------------------------
    def _session_exists_unlocked(self, sid: str) -> bool:
        return (any(k[0] == sid for k in self._static)
                or any(k[0] == sid for k in self._updates))

    def list_session_ids(self) -> List[str]:
        with self._lock:
            out = {k[0] for k in self._static} | {k[0] for k in self._updates}
        return sorted(out)

    def session_exists(self, sid: str) -> bool:
        with self._lock:
            return self._session_exists_unlocked(sid)

    def list_type_ids_for_session(self, sid: str) -> List[str]:
        with self._lock:
            out = ({k[1] for k in self._static if k[0] == sid}
                   | {k[1] for k in self._updates if k[0] == sid})
        return sorted(out)

    def list_worker_ids_for_session(self, sid: str,
                                    type_id: Optional[str] = None) -> List[str]:
        with self._lock:
            keys = list(self._static) + list(self._updates)
            out = {k[2] for k in keys
                   if k[0] == sid and (type_id is None or k[1] == type_id)}
        return sorted(out)

    def get_static_info(self, sid: str, type_id: str, worker_id: str) -> Optional[Persistable]:
        with self._lock:
            return self._static.get((sid, type_id, worker_id))

    def get_all_static_infos(self, sid: str, type_id: str) -> List[Persistable]:
        with self._lock:
            return [p for k, p in self._static.items()
                    if k[0] == sid and k[1] == type_id]

    def get_num_update_records_for(self, sid: str, type_id: Optional[str] = None,
                                   worker_id: Optional[str] = None) -> int:
        with self._lock:
            return sum(len(v) for k, v in self._updates.items()
                       if k[0] == sid and (type_id is None or k[1] == type_id)
                       and (worker_id is None or k[2] == worker_id))

    def get_latest_update(self, sid: str, type_id: str, worker_id: str) -> Optional[Persistable]:
        with self._lock:
            lst = self._updates.get((sid, type_id, worker_id))
            return lst[-1] if lst else None

    def get_latest_update_all_workers(self, sid: str, type_id: str) -> List[Persistable]:
        with self._lock:
            return [v[-1] for k, v in self._updates.items()
                    if k[0] == sid and k[1] == type_id and v]

    def get_all_updates_after(self, sid: str, type_id: str,
                              timestamp: float,
                              worker_id: Optional[str] = None) -> List[Persistable]:
        with self._lock:
            out = []
            for k, v in self._updates.items():
                if k[0] == sid and k[1] == type_id and \
                        (worker_id is None or k[2] == worker_id):
                    out.extend(p for p in v if p.timestamp > timestamp)
        return sorted(out, key=lambda p: p.timestamp)

    def get_all_update_times(self, sid: str, type_id: str, worker_id: str) -> List[float]:
        with self._lock:
            return [p.timestamp for p in self._updates.get((sid, type_id, worker_id), [])]

    # -- listeners / lifecycle -------------------------------------------
    def register_stats_storage_listener(self, l: StatsStorageListener) -> None:
        self._listeners.append(l)

    def deregister_stats_storage_listener(self, l: StatsStorageListener) -> None:
        self._listeners.remove(l)

    def remove_all_listeners(self) -> None:
        self._listeners.clear()

    def _notify(self, event: StatsStorageEvent) -> None:
        for l in list(self._listeners):
            l.notify(event)

    def close(self) -> None:
        self._closed = True

    def is_closed(self) -> bool:
        return self._closed


class InMemoryStatsStorage(StatsStorage):
    """Pure in-memory storage (``InMemoryStatsStorage.java``)."""


class FileStatsStorage(StatsStorage):
    """Append-only JSON-lines file storage — the durable, inspectable
    replacement for the reference's MapDB-backed store
    (``MapDBStatsStorage.java``). Reloads existing records on open."""

    def __init__(self, path: str):
        super().__init__()
        self.path = str(path)
        if os.path.exists(self.path):
            with open(self.path, "r", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    rec = json.loads(line)
                    p = Persistable(rec["session_id"], rec["type_id"],
                                    rec["worker_id"], rec["timestamp"], rec["data"])
                    key = (p.session_id, p.type_id, p.worker_id)
                    if rec.get("static"):
                        self._static[key] = p
                    else:
                        self._updates.setdefault(key, []).append(p)
        self._fh = open(self.path, "a", encoding="utf-8")

    def _persist(self, p: Persistable, static: bool) -> None:
        rec = {"session_id": p.session_id, "type_id": p.type_id,
               "worker_id": p.worker_id, "timestamp": p.timestamp,
               "static": static, "data": p.data}
        self._fh.write(json.dumps(rec) + "\n")
        self._fh.flush()

    def close(self) -> None:
        super().close()
        self._fh.close()
