"""Dimensionality-reduction / visualization (parity:
``deeplearning4j-core/.../plot/`` — ``BarnesHutTsne.java:65``)."""

from .tsne import BarnesHutTsne, Tsne

__all__ = ["BarnesHutTsne", "Tsne"]
