"""t-SNE (parity: ``deeplearning4j-core/.../plot/BarnesHutTsne.java:65``).

Two execution paths, selected like the reference selects exact-vs-BH via
``theta``:

- ``theta == 0`` → **exact t-SNE fully on device**: the (N, N) affinity and
  gradient are jitted matmul/broadcast work, the iteration loop is
  ``lax.fori_loop`` — the TPU-native fast path.
- ``theta > 0`` → **Barnes-Hut on host**: sparse input affinities from
  device k-NN (:class:`~..clustering.bruteforce.BruteForceNearestNeighbors`),
  per-iteration :class:`~..clustering.sptree.SpTree` forces on CPU, matching
  the reference algorithm for N too large for the quadratic path.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..clustering.bruteforce import BruteForceNearestNeighbors, pairwise_distance


# -- shared: perplexity calibration (BarnesHutTsne.computeGaussianPerplexity) --

def _binary_search_betas(d2: np.ndarray, perplexity: float,
                         tol: float = 1e-5, iters: int = 50) -> np.ndarray:
    """Per-row precision (beta) so row entropy == log(perplexity).

    d2: (N, K) squared distances to the considered neighbors (self excluded).
    Vectorized over rows (the reference does a per-row scalar loop).
    """
    n = d2.shape[0]
    beta = np.ones(n)
    beta_min = np.full(n, -np.inf)
    beta_max = np.full(n, np.inf)
    log_u = np.log(perplexity)
    p = np.zeros_like(d2)
    for _ in range(iters):
        p = np.exp(-d2 * beta[:, None])
        sum_p = np.maximum(p.sum(1), 1e-12)
        h = np.log(sum_p) + beta * (d2 * p).sum(1) / sum_p
        diff = h - log_u
        done = np.abs(diff) < tol
        if done.all():
            break
        hi = diff > 0
        beta_min = np.where(hi & ~done, beta, beta_min)
        beta_max = np.where(~hi & ~done, beta, beta_max)
        beta = np.where(
            hi & ~done,
            np.where(np.isinf(beta_max), beta * 2, (beta + beta_max) / 2),
            np.where(~hi & ~done,
                     np.where(np.isinf(beta_min), beta / 2, (beta + beta_min) / 2),
                     beta))
    return p / np.maximum(p.sum(1, keepdims=True), 1e-12)


# -- exact path (device) ------------------------------------------------------

@partial(jax.jit, static_argnames=("n_iter", "stop_lying_iter"))
def _exact_tsne_run(p: jax.Array, y0: jax.Array, n_iter: int,
                    stop_lying_iter: int, momentum_switch: int,
                    learning_rate: float):
    """Full exact t-SNE optimization as one compiled fori_loop."""

    def grad_kl(y, pmat):
        d2 = pairwise_distance(y, y, "sqeuclidean")
        num = 1.0 / (1.0 + d2)
        num = num * (1.0 - jnp.eye(y.shape[0]))
        q = num / jnp.maximum(num.sum(), 1e-12)
        pq = (pmat - q) * num
        return 4.0 * ((jnp.diag(pq.sum(1)) - pq) @ y)

    def body(i, carry):
        y, vel, gains = carry
        pmat = jnp.where(i < stop_lying_iter, p * 4.0, p)  # early exaggeration
        g = grad_kl(y, pmat)
        same_sign = jnp.sign(g) == jnp.sign(vel)
        gains = jnp.clip(jnp.where(same_sign, gains * 0.8, gains + 0.2), 0.01)
        mom = jnp.where(i < momentum_switch, 0.5, 0.8)
        vel = mom * vel - learning_rate * gains * g
        y = y + vel
        return y - y.mean(0), vel, gains

    y, _, _ = jax.lax.fori_loop(
        0, n_iter, body, (y0, jnp.zeros_like(y0), jnp.ones_like(y0)))
    return y


class Tsne:
    """Exact t-SNE, device-resident (role of the non-BH path in
    ``BarnesHutTsne.java`` when ``theta == 0``)."""

    def __init__(self, n_components: int = 2, perplexity: float = 30.0,
                 learning_rate="auto", n_iter: int = 1000,
                 stop_lying_iteration: int = 100, momentum_switch: int = 100,
                 seed: int = 0):
        self.n_components = n_components
        self.perplexity = perplexity
        self.learning_rate = learning_rate
        self.n_iter = n_iter
        self.stop_lying_iteration = stop_lying_iteration
        self.momentum_switch = momentum_switch
        self.seed = seed
        self.y: Optional[np.ndarray] = None

    def fit_transform(self, x) -> np.ndarray:
        x = np.asarray(x, np.float32)
        n = x.shape[0]
        d2 = np.array(pairwise_distance(jnp.asarray(x), jnp.asarray(x),
                                        "sqeuclidean"))
        np.fill_diagonal(d2, np.inf)
        p_cond = _binary_search_betas(
            np.where(np.isinf(d2), 1e12, d2),
            min(self.perplexity, (n - 1) / 3.0))
        p = (p_cond + p_cond.T) / (2.0 * n)
        p = np.maximum(p, 1e-12)
        rng = np.random.default_rng(self.seed)
        y0 = (rng.standard_normal((n, self.n_components)) * 1e-4).astype(np.float32)
        lr = (max(n / 16.0, 50.0) if self.learning_rate == "auto"
              else float(self.learning_rate))
        y = _exact_tsne_run(jnp.asarray(p, jnp.float32), jnp.asarray(y0),
                            self.n_iter, self.stop_lying_iteration,
                            self.momentum_switch, lr)
        self.y = np.asarray(y)
        return self.y


class BarnesHutTsne:
    """Barnes-Hut t-SNE (``BarnesHutTsne.java:65``; builder defaults
    ``theta=0.5``, ``perplexity=30``, 3*perplexity neighbors).

    ``theta=0`` falls back to the exact device path.
    """

    def __init__(self, n_components: int = 2, theta: float = 0.5,
                 perplexity: float = 30.0, learning_rate="auto",
                 n_iter: int = 1000, stop_lying_iteration: int = 100,
                 momentum_switch: int = 100, seed: int = 0):
        self.n_components = n_components
        self.theta = float(theta)
        self.perplexity = perplexity
        self.learning_rate = learning_rate
        self.n_iter = n_iter
        self.stop_lying_iteration = stop_lying_iteration
        self.momentum_switch = momentum_switch
        self.seed = seed
        self.y: Optional[np.ndarray] = None

    def fit_transform(self, x) -> np.ndarray:
        if self.theta == 0.0:
            inner = Tsne(self.n_components, self.perplexity,
                         self.learning_rate, self.n_iter,
                         self.stop_lying_iteration, self.momentum_switch,
                         self.seed)
            self.y = inner.fit_transform(x)
            return self.y

        from ..clustering.sptree import SpTree

        x = np.asarray(x, np.float32)
        n = x.shape[0]
        k = min(int(3 * self.perplexity), n - 1)
        # sparse symmetric P from device k-NN
        index = BruteForceNearestNeighbors(x, "euclidean")
        nd, ni = index.search_excluding_self(k)
        p_cond = _binary_search_betas((nd ** 2).astype(np.float64),
                                      min(self.perplexity, k / 3.0))
        p = {}
        for i in range(n):
            for j_pos in range(k):
                j = int(ni[i, j_pos])
                v = p_cond[i, j_pos]
                p[(i, j)] = p.get((i, j), 0.0) + v
                p[(j, i)] = p.get((j, i), 0.0) + v
        total = sum(p.values())
        # CSR triplets
        rows = np.zeros(n + 1, np.int64)
        for (i, _), _v in p.items():
            rows[i + 1] += 1
        rows = np.cumsum(rows)
        cols = np.zeros(len(p), np.int64)
        vals = np.zeros(len(p), np.float64)
        fill = rows[:-1].copy()
        for (i, j), v in p.items():
            cols[fill[i]] = j
            vals[fill[i]] = max(v / total, 1e-12)
            fill[i] += 1

        lr = (max(n / 48.0, 50.0) if self.learning_rate == "auto"
              else float(self.learning_rate))
        rng = np.random.default_rng(self.seed)
        y = (rng.standard_normal((n, self.n_components)) * 1e-4)
        vel = np.zeros_like(y)
        gains = np.ones_like(y)
        for it in range(self.n_iter):
            exagg = 12.0 if it < self.stop_lying_iteration else 1.0
            tree = SpTree(y)
            pos_f = np.zeros_like(y)
            neg_f = np.zeros_like(y)
            tree.compute_edge_forces(rows, cols, vals * exagg, pos_f)
            sum_q = 0.0
            for i in range(n):
                row_neg = np.zeros(self.n_components)
                sum_q += tree.compute_non_edge_forces(i, self.theta, row_neg)
                neg_f[i] = row_neg
            g = pos_f - neg_f / max(sum_q, 1e-12)
            same = np.sign(g) == np.sign(vel)
            gains = np.clip(np.where(same, gains * 0.8, gains + 0.2), 0.01, None)
            mom = 0.5 if it < self.momentum_switch else 0.8
            vel = mom * vel - lr * gains * g
            y = y + vel
            y = y - y.mean(0)
        self.y = y.astype(np.float32)
        return self.y

    # reference-style aliases (BarnesHutTsne.fit / getData)
    fit = fit_transform

    def get_data(self) -> Optional[np.ndarray]:
        return self.y
