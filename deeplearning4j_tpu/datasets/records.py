"""Record readers and the record → DataSet bridge.

The reference consumes records through the external DataVec library and
bridges them in ``deeplearning4j-core/.../datasets/datavec/``
(`RecordReaderDataSetIterator.java:86`, `SequenceRecordReaderDataSetIterator.java`,
`RecordReaderMultiDataSetIterator.java`). This module provides both sides
natively: a small RecordReader SPI (CSV / line / collection / sequence
readers) and the iterators that assemble batched, padded, masked ``DataSet`` /
``MultiDataSet`` objects ready for jitted training (fixed shapes per batch;
variable-length sequences become padding + mask, never ragged arrays).
"""

from __future__ import annotations

import dataclasses
import glob as _glob
import os
from typing import Iterable, List, Optional, Sequence, Tuple, Union

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, DataSetIterator, MultiDataSet

Record = List  # a record is a list of values (DataVec "Writable"s)


@dataclasses.dataclass(frozen=True)
class RecordMetaData:
    """Provenance of one record (DataVec ``RecordMetaDataLine`` /
    ``RecordMetaDataIndex``): where it came from, so an evaluation error can
    be traced back to — and the original record reloaded from — its source.
    """

    index: int                      # position within the reader
    uri: Optional[str] = None       # source file, when file-backed
    reader_class: str = ""

    def get_location(self) -> str:
        base = self.uri or "<memory>"
        return f"{base}:{self.index}"


# --------------------------------------------------------------------------
# record readers
# --------------------------------------------------------------------------
class RecordReader:
    """SPI: iterate records (lists of values). Mirrors DataVec's RecordReader
    as used by the bridge iterators, including the metadata face
    (``nextRecord()`` → Record-with-meta, ``loadFromMetaData``)."""

    def has_next(self) -> bool:
        raise NotImplementedError

    def next_record(self) -> Record:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    # ------------------------------------------------------------- metadata
    def _meta_uri(self) -> Optional[str]:
        paths = getattr(self, "_paths", None)
        return paths[0] if paths else None

    def next_record_with_meta(self):
        """(record, RecordMetaData) — DataVec ``RecordReader.nextRecord()``.
        The index is the reader-global record position (multi-file readers
        concatenate; the uri is the first source path)."""
        idx = int(getattr(self, "_pos", -1))
        return self.next_record(), RecordMetaData(
            index=idx, uri=self._meta_uri(), reader_class=type(self).__name__)

    def _record_at(self, index: int) -> Record:
        raise NotImplementedError(
            f"{type(self).__name__} does not support loadFromMetaData")

    def load_from_meta_data(self, metas) -> List[Record]:
        """Reload the original records for the given metadata
        (DataVec ``RecordReader.loadFromMetaData``) — the error-drilldown
        path: Evaluation.get_prediction_errors() → back to source records."""
        if isinstance(metas, RecordMetaData):
            metas = [metas]
        return [self._record_at(m.index) for m in metas]

    def __iter__(self):
        self.reset()
        while self.has_next():
            yield self.next_record()


class CollectionRecordReader(RecordReader):
    """Records from an in-memory collection."""

    def __init__(self, records: Sequence[Record]):
        self._records = [list(r) for r in records]
        self.reset()

    def reset(self):
        self._pos = 0

    def has_next(self):
        return self._pos < len(self._records)

    def next_record(self):
        r = self._records[self._pos]
        self._pos += 1
        return list(r)

    def _record_at(self, index):
        return list(self._records[index])


class LineRecordReader(RecordReader):
    """One record per line: ``[line]``. Files are read once at construction;
    ``reset()`` only rewinds."""

    def __init__(self, path: Union[str, Sequence[str]]):
        self._paths = _expand_paths(path)
        self._lines: List[str] = []
        for p in self._paths:
            with open(p, "r", encoding="utf-8") as f:
                self._lines.extend(ln.rstrip("\n") for ln in f)
        self.reset()

    def reset(self):
        self._pos = 0

    def has_next(self):
        return self._pos < len(self._lines)

    def next_record(self):
        r = [self._lines[self._pos]]
        self._pos += 1
        return r

    def _record_at(self, index):
        return [self._lines[index]]


class CSVRecordReader(RecordReader):
    """Delimited text records; numeric fields are parsed to float."""

    def __init__(self, path: Union[str, Sequence[str]], skip_lines: int = 0,
                 delimiter: str = ","):
        self._paths = _expand_paths(path)
        self.skip_lines = skip_lines
        self.delimiter = delimiter
        # parse once; reset() only rewinds (multi-epoch training would
        # otherwise re-read + re-parse the whole corpus every epoch)
        self._records: List[Record] = []
        for p in self._paths:
            with open(p, "r", encoding="utf-8") as f:
                for i, line in enumerate(f):
                    if i < self.skip_lines:
                        continue
                    line = line.strip()
                    if line:
                        self._records.append(
                            [_parse_field(v) for v in line.split(self.delimiter)])
        self.reset()

    def reset(self):
        self._pos = 0

    def has_next(self):
        return self._pos < len(self._records)

    def next_record(self):
        r = self._records[self._pos]
        self._pos += 1
        return list(r)

    def _record_at(self, index):
        return list(self._records[index])


class ImageRecordReader(RecordReader):
    """Image directory reader (DataVec's ``ImageRecordReader`` role — the
    external dependency the reference's datavec bridge consumes; not in the
    reference snapshot itself). Walks a directory tree, decodes each image
    to a ``[height, width, channels]`` float32 array (0-255, PIL-backed,
    bilinear resize), and labels from the PARENT DIRECTORY name
    (ParentPathLabelGenerator semantics: one subdirectory per class,
    label indices assigned in sorted directory order).

    Records are ``[image_array, label_index]`` — feed to
    :class:`RecordReaderDataSetIterator` with ``label_index=1`` and
    ``num_possible_labels=len(reader.labels)``; scale with
    :class:`~deeplearning4j_tpu.datasets.normalizers.ImagePreProcessingScaler`.
    """

    EXTENSIONS = (".png", ".jpg", ".jpeg", ".bmp", ".gif")

    def __init__(self, height: int, width: int, channels: int = 3,
                 path: Optional[str] = None):
        self.height = int(height)
        self.width = int(width)
        self.channels = int(channels)
        self._files: List[Tuple[str, int]] = []
        self.labels: List[str] = []
        self._pos = 0
        if path is not None:
            self.initialize(path)

    def initialize(self, path: str) -> "ImageRecordReader":
        """Collect (file, label) pairs from ``path/<label>/<image>``; files
        directly under ``path`` get label 0 with a single '' class."""
        entries = []
        for root, _dirs, files in os.walk(path):
            for f in sorted(files):
                if f.lower().endswith(self.EXTENSIONS):
                    rel = os.path.relpath(root, path)
                    # ParentPathLabelGenerator: the file's IMMEDIATE parent
                    # directory names the class (root/a/b/x.png -> "b")
                    label = "" if rel == "." else os.path.basename(root)
                    entries.append((os.path.join(root, f), label))
        self.labels = sorted({lab for _, lab in entries})
        idx = {lab: i for i, lab in enumerate(self.labels)}
        entries.sort(key=lambda e: (e[1], e[0]))
        self._files = [(p, idx[lab]) for p, lab in entries]
        self.reset()
        return self

    def reset(self):
        self._pos = 0

    def has_next(self):
        return self._pos < len(self._files)

    def _decode(self, path: str) -> np.ndarray:
        from PIL import Image

        with Image.open(path) as im:
            im = im.convert("L" if self.channels == 1 else "RGB")
            im = im.resize((self.width, self.height), Image.BILINEAR)
            arr = np.asarray(im, np.float32)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr

    def next_record(self):
        path, label = self._files[self._pos]
        self._pos += 1
        return [self._decode(path), label]

    def next_record_with_meta(self):
        idx = self._pos
        path, _ = self._files[idx]
        rec = self.next_record()
        return rec, RecordMetaData(index=idx, uri=path,
                                   reader_class=type(self).__name__)

    def _record_at(self, index):
        path, label = self._files[index]
        return [self._decode(path), label]


class SequenceRecordReader:
    """SPI: iterate sequences (lists of records), with the same metadata
    face as RecordReader (``SequenceRecordReader.nextSequence()`` /
    ``loadSequenceFromMetaData``)."""

    def has_next(self) -> bool:
        raise NotImplementedError

    def next_sequence(self) -> List[Record]:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    def _meta_uri(self) -> Optional[str]:
        paths = getattr(self, "_paths", None)
        return paths[0] if paths else None

    def next_sequence_with_meta(self):
        idx = int(getattr(self, "_pos", -1))
        return self.next_sequence(), RecordMetaData(
            index=idx, uri=self._meta_uri(),
            reader_class=type(self).__name__)

    def _sequence_at(self, index: int) -> List[Record]:
        raise NotImplementedError(
            f"{type(self).__name__} does not support loadSequenceFromMetaData")

    def load_sequence_from_meta_data(self, metas):
        if isinstance(metas, RecordMetaData):
            metas = [metas]
        return [self._sequence_at(m.index) for m in metas]

    def __iter__(self):
        self.reset()
        while self.has_next():
            yield self.next_sequence()


class CollectionSequenceRecordReader(SequenceRecordReader):
    def __init__(self, sequences: Sequence[Sequence[Record]]):
        self._seqs = [[list(r) for r in s] for s in sequences]
        self.reset()

    def reset(self):
        self._pos = 0

    def has_next(self):
        return self._pos < len(self._seqs)

    def next_sequence(self):
        s = self._seqs[self._pos]
        self._pos += 1
        return [list(r) for r in s]

    def _sequence_at(self, index):
        return [list(r) for r in self._seqs[index]]


class CSVSequenceRecordReader(SequenceRecordReader):
    """One sequence per file (DataVec CSVSequenceRecordReader): each line of a
    file is one time step."""

    def __init__(self, path: Union[str, Sequence[str]], skip_lines: int = 0,
                 delimiter: str = ","):
        self._paths = _expand_paths(path)
        self.skip_lines = skip_lines
        self.delimiter = delimiter
        self.reset()

    def reset(self):
        self._pos = 0

    def has_next(self):
        return self._pos < len(self._paths)

    def next_sequence(self):
        seq = self._sequence_at(self._pos)
        self._pos += 1
        return seq

    def next_sequence_with_meta(self):
        idx = self._pos
        return self.next_sequence(), RecordMetaData(
            index=idx, uri=self._paths[idx],
            reader_class=type(self).__name__)

    def _sequence_at(self, index):
        seq = []
        with open(self._paths[index], "r", encoding="utf-8") as f:
            for i, line in enumerate(f):
                if i < self.skip_lines:
                    continue
                line = line.strip()
                if line:
                    seq.append([_parse_field(v)
                                for v in line.split(self.delimiter)])
        return seq


def _expand_paths(path: Union[str, Sequence[str]]) -> List[str]:
    if isinstance(path, (list, tuple)):
        return [str(p) for p in path]
    path = str(path)
    if os.path.isdir(path):
        return sorted(os.path.join(path, f) for f in os.listdir(path)
                      if os.path.isfile(os.path.join(path, f)))
    if any(c in path for c in "*?["):
        return sorted(_glob.glob(path))
    return [path]


def _parse_field(v: str):
    v = v.strip()
    try:
        return float(v)
    except ValueError:
        return v


# --------------------------------------------------------------------------
# record → DataSet bridge
# --------------------------------------------------------------------------
class RecordReaderDataSetIterator(DataSetIterator):
    """Batches records into DataSets (`RecordReaderDataSetIterator.java:86`).

    - classification: ``label_index`` holds an integer class, one-hot encoded
      to ``num_possible_labels`` outputs;
    - regression: label columns ``label_index..label_index_to`` inclusive
      (``.regression(from, to)`` builder in the reference);
    - ``label_index < 0``: features-only DataSets (labels == features, the
      autoencoder convention).
    """

    def __init__(self, record_reader: RecordReader, batch_size: int,
                 label_index: int = -1, num_possible_labels: int = -1,
                 label_index_to: int = -1, regression: bool = False,
                 max_num_batches: int = -1, preprocessor=None,
                 collect_meta_data: bool = False):
        self.reader = record_reader
        self.batch_size = batch_size
        self.label_index = label_index
        self.label_index_to = label_index_to if label_index_to >= 0 else label_index
        self.num_possible_labels = num_possible_labels
        self.regression = regression
        self.max_num_batches = max_num_batches
        self.preprocessor = preprocessor
        # setCollectMetaData(true) parity: emitted DataSets carry per-example
        # RecordMetaData, the source Evaluation's error drilldown reads
        self.collect_meta_data = collect_meta_data
        if regression and label_index >= 0 and num_possible_labels > 0:
            raise ValueError("regression=True is incompatible with "
                             "num_possible_labels (one-hot classification)")

    def reset(self):
        self.reader.reset()

    def _split(self, rec: Record):
        # tensor-valued records (ImageRecordReader: [array, label]) pass
        # the array through as the feature block (NDArrayWritable role)
        if rec and isinstance(rec[0], np.ndarray) and rec[0].ndim > 1:
            f = np.asarray(rec[0], np.float32)
            if self.label_index < 0:
                return f, f
            cls = int(float(rec[self.label_index]))
            if self.regression:
                return f, np.asarray([float(rec[i]) for i in
                                      range(self.label_index,
                                            self.label_index_to + 1)],
                                     np.float32)
            if not 0 <= cls < self.num_possible_labels:
                raise ValueError(
                    f"Label {cls} outside [0, {self.num_possible_labels})")
            l = np.zeros(self.num_possible_labels, np.float32)
            l[cls] = 1.0
            return f, l
        if self.label_index < 0:
            f = np.asarray([float(v) for v in rec], np.float32)
            return f, f
        lo, hi = self.label_index, self.label_index_to
        feats = [float(v) for i, v in enumerate(rec) if not lo <= i <= hi]
        f = np.asarray(feats, np.float32)
        if self.regression:
            l = np.asarray([float(rec[i]) for i in range(lo, hi + 1)], np.float32)
        else:
            cls = int(float(rec[self.label_index]))
            if not 0 <= cls < self.num_possible_labels:
                raise ValueError(
                    f"Label {cls} outside [0, {self.num_possible_labels})")
            l = np.zeros(self.num_possible_labels, np.float32)
            l[cls] = 1.0
        return f, l

    def __iter__(self):
        self.reset()
        batches = 0
        feats, labels, metas = [], [], []
        while self.reader.has_next():
            if self.collect_meta_data:
                rec, meta = self.reader.next_record_with_meta()
                metas.append(meta)
            else:
                rec = self.reader.next_record()
            f, l = self._split(rec)
            feats.append(f)
            labels.append(l)
            if len(feats) == self.batch_size:
                yield self._emit(feats, labels, metas)
                feats, labels, metas = [], [], []
                batches += 1
                if 0 < self.max_num_batches <= batches:
                    return
        if feats:
            yield self._emit(feats, labels, metas)

    def _emit(self, feats, labels, metas=()):
        ds = DataSet(np.stack(feats), np.stack(labels),
                     example_meta_data=list(metas) or None)
        if self.preprocessor is not None:
            from deeplearning4j_tpu.datasets.dataset import apply_preprocessor
            ds = apply_preprocessor(self.preprocessor, ds)
        return ds

    def load_from_meta_data(self, metas) -> DataSet:
        """Rebuild a DataSet from recorded metadata
        (``RecordReaderDataSetIterator.loadFromMetaData``) — fetches the
        original records and re-applies the feature/label split."""
        if isinstance(metas, RecordMetaData):
            metas = [metas]
        recs = self.reader.load_from_meta_data(metas)
        feats, labels = zip(*(self._split(r) for r in recs))
        return self._emit(list(feats), list(labels), metas)


class AlignmentMode:
    """Sequence alignment for two-reader iteration
    (SequenceRecordReaderDataSetIterator.AlignmentMode)."""

    EQUAL_LENGTH = "equal_length"
    ALIGN_START = "align_start"
    ALIGN_END = "align_end"


class SequenceRecordReaderDataSetIterator(DataSetIterator):
    """Sequence records → padded+masked [N, T, C] DataSets
    (`SequenceRecordReaderDataSetIterator.java`).

    One reader: label column inside each time-step record. Two readers:
    features and labels read separately, aligned per AlignmentMode (padding +
    masks make every batch rectangular — the jit-friendly encoding of ragged
    sequences).
    """

    def __init__(self, features_reader: SequenceRecordReader, batch_size: int,
                 num_possible_labels: int = -1, label_index: int = -1,
                 regression: bool = False,
                 labels_reader: Optional[SequenceRecordReader] = None,
                 alignment_mode: str = AlignmentMode.ALIGN_START,
                 collect_meta_data: bool = False):
        self.features_reader = features_reader
        self.labels_reader = labels_reader
        self.batch_size = batch_size
        self.num_possible_labels = num_possible_labels
        self.label_index = label_index
        self.regression = regression
        self.alignment_mode = alignment_mode
        self.collect_meta_data = collect_meta_data

    def reset(self):
        self.features_reader.reset()
        if self.labels_reader is not None:
            self.labels_reader.reset()

    def _one_hot(self, v) -> np.ndarray:
        cls = int(float(v))
        if not 0 <= cls < self.num_possible_labels:
            raise ValueError(f"Label {cls} outside [0, {self.num_possible_labels})")
        out = np.zeros(self.num_possible_labels, np.float32)
        out[cls] = 1.0
        return out

    def __iter__(self):
        self.reset()
        fs, ls, metas = [], [], []
        lab_iter = iter(self.labels_reader) if self.labels_reader is not None else None
        while self.features_reader.has_next():
            if self.collect_meta_data:
                seq, meta = self.features_reader.next_sequence_with_meta()
                metas.append(meta)
            else:
                seq = self.features_reader.next_sequence()
            if lab_iter is not None:
                try:
                    lseq = next(lab_iter)
                except StopIteration:
                    raise ValueError(
                        "labels reader exhausted before features reader: "
                        "sequence counts differ") from None
                f = np.asarray([[float(v) for v in r] for r in seq], np.float32)
                if self.regression:
                    l = np.asarray([[float(v) for v in r] for r in lseq], np.float32)
                else:
                    l = np.stack([self._one_hot(r[0]) for r in lseq])
            else:
                idx = self.label_index
                f = np.asarray([[float(v) for i, v in enumerate(r) if i != idx]
                                for r in seq], np.float32)
                if self.regression:
                    l = np.asarray([[float(r[idx])] for r in seq], np.float32)
                else:
                    l = np.stack([self._one_hot(r[idx]) for r in seq])
            fs.append(f)
            ls.append(l)
            if len(fs) == self.batch_size:
                yield self._emit(fs, ls, metas)
                fs, ls, metas = [], [], []
        if fs:
            yield self._emit(fs, ls, metas)

    def _emit(self, fs, ls, metas=()):
        n = len(fs)
        tf = max(f.shape[0] for f in fs)
        tl = max(l.shape[0] for l in ls)
        t = max(tf, tl)
        fdim, ldim = fs[0].shape[1], ls[0].shape[1]
        x = np.zeros((n, t, fdim), np.float32)
        y = np.zeros((n, t, ldim), np.float32)
        fm = np.zeros((n, t), np.float32)
        lm = np.zeros((n, t), np.float32)
        for i, (f, l) in enumerate(zip(fs, ls)):
            if self.alignment_mode == AlignmentMode.ALIGN_END:
                fo, lo = t - f.shape[0], t - l.shape[0]
            else:
                if (self.alignment_mode == AlignmentMode.EQUAL_LENGTH
                        and f.shape[0] != l.shape[0]):
                    raise ValueError(
                        f"EQUAL_LENGTH alignment but lengths differ: "
                        f"{f.shape[0]} vs {l.shape[0]}")
                fo, lo = 0, 0
            x[i, fo:fo + f.shape[0]] = f
            fm[i, fo:fo + f.shape[0]] = 1.0
            y[i, lo:lo + l.shape[0]] = l
            lm[i, lo:lo + l.shape[0]] = 1.0
        all_f = bool(np.all(fm == 1.0))
        all_l = bool(np.all(lm == 1.0))
        return DataSet(x, y, None if all_f else fm, None if all_l else lm,
                       example_meta_data=list(metas) or None)


class RecordReaderMultiDataSetIterator:
    """Multiple named readers → MultiDataSet (builder-style API of
    `RecordReaderMultiDataSetIterator.java`): declare which column ranges of
    which reader become which input/output arrays."""

    class Builder:
        def __init__(self, batch_size: int):
            self.batch_size = batch_size
            self._readers = {}
            self._inputs = []   # (reader_name, col_from, col_to)
            self._outputs = []  # (reader_name, col_from, col_to, one_hot_n)

        def add_reader(self, name: str, reader: RecordReader) -> "RecordReaderMultiDataSetIterator.Builder":
            self._readers[name] = reader
            return self

        def add_input(self, name: str, col_from: int = 0,
                      col_to: int = -1) -> "RecordReaderMultiDataSetIterator.Builder":
            self._inputs.append((name, col_from, col_to))
            return self

        def add_output(self, name: str, col_from: int = 0,
                       col_to: int = -1) -> "RecordReaderMultiDataSetIterator.Builder":
            self._outputs.append((name, col_from, col_to, -1))
            return self

        def add_output_one_hot(self, name: str, column: int,
                               num_classes: int) -> "RecordReaderMultiDataSetIterator.Builder":
            self._outputs.append((name, column, column, num_classes))
            return self

        def build(self) -> "RecordReaderMultiDataSetIterator":
            return RecordReaderMultiDataSetIterator(self)

    def __init__(self, builder: "RecordReaderMultiDataSetIterator.Builder"):
        self._b = builder
        for name, *_ in builder._inputs + [o[:3] for o in builder._outputs]:
            if name not in builder._readers:
                raise ValueError(f"No reader named {name!r}")

    def reset(self):
        for r in self._b._readers.values():
            r.reset()

    def __iter__(self):
        self.reset()
        b = self._b
        names = list(b._readers)
        iters = {n: iter(b._readers[n]) for n in names}
        while True:
            rows = {n: [] for n in names}
            exhausted = False
            for _ in range(b.batch_size):
                # one record from EVERY reader per row (all-or-nothing, so
                # readers can never go out of alignment mid-batch)
                rec_per = {}
                for n in names:
                    try:
                        rec_per[n] = next(iters[n])
                    except StopIteration:
                        exhausted = True
                        break
                if exhausted:
                    break
                for n in names:
                    rows[n].append(rec_per[n])
            if rows[names[0]]:
                yield self._emit(rows)
            if exhausted:
                return

    def _emit(self, rows) -> MultiDataSet:
        b = self._b

        def cols(rec, lo, hi):
            hi = len(rec) - 1 if hi < 0 else hi
            return [float(v) for v in rec[lo:hi + 1]]

        features = []
        for name, lo, hi in b._inputs:
            features.append(np.asarray([cols(r, lo, hi) for r in rows[name]],
                                       np.float32))
        labels = []
        for name, lo, hi, one_hot in b._outputs:
            if one_hot > 0:
                arr = np.zeros((len(rows[name]), one_hot), np.float32)
                for i, r in enumerate(rows[name]):
                    cls = int(float(r[lo]))
                    if not 0 <= cls < one_hot:
                        raise ValueError(f"Label {cls} outside [0, {one_hot})")
                    arr[i, cls] = 1.0
            else:
                arr = np.asarray([cols(r, lo, hi) for r in rows[name]], np.float32)
            labels.append(arr)
        return MultiDataSet(features, labels)
