"""Iterator utilities — async prefetch and composition.

Reference: ``deeplearning4j-nn/.../datasets/iterator/`` (27 files):
``AsyncDataSetIterator.java:30`` (background prefetch thread feeding the fit
loop at ``MultiLayerNetwork.java:1267``), ``MultipleEpochsIterator``,
``EarlyTerminationDataSetIterator``, ``SamplingDataSetIterator``,
``DataSetIteratorSplitter``, ``IteratorDataSetIterator``,
``AsyncMultiDataSetIterator``.

The async iterator is the ETL/compute overlap mechanism: the host thread
prepares (and optionally device-puts) batch N+1 while the device runs batch N.
With jit dispatch being async already, one prefetch slot mainly hides numpy
preprocessing cost.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterator, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, DataSetIterator


class AsyncDataSetIterator(DataSetIterator):
    """Background-thread prefetch wrapper (AsyncDataSetIterator.java:30)."""

    _END = object()

    def __init__(self, base: DataSetIterator, queue_size: int = 2,
                 device_put: Optional[Callable] = None):
        self.base = base
        self.queue_size = max(1, queue_size)
        self.device_put = device_put

    def reset(self) -> None:
        # plain lists/generators have no reset; the fit loops re-iterate them
        if hasattr(self.base, "reset"):
            self.base.reset()

    def __iter__(self) -> Iterator[DataSet]:
        q: "queue.Queue" = queue.Queue(maxsize=self.queue_size)
        err: List[BaseException] = []
        stop = threading.Event()

        def put_unless_stopped(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            try:
                for ds in self.base:
                    if self.device_put is not None:
                        ds = self.device_put(ds)
                    if not put_unless_stopped(ds):
                        return
            except BaseException as e:  # surface in consumer
                err.append(e)
            finally:
                put_unless_stopped(self._END)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is self._END:
                    break
                yield item
        finally:
            # Consumer may stop early (EarlyTermination*, break in fit loop):
            # unblock and retire the producer instead of leaking it.
            stop.set()
            t.join()
        if err:
            raise err[0]


class AsyncMultiDataSetIterator(AsyncDataSetIterator):
    """Same prefetch for MultiDataSet streams (AsyncMultiDataSetIterator)."""


def device_put_batch(ds):
    """Async-stage device put: moves one DataSet/MultiDataSet's arrays
    (features, labels, masks) onto the accelerator and returns it — the
    ``device_put`` callable the prefetch pipeline hands to
    :class:`AsyncDataSetIterator`, so the host→device transfer of batch N+1
    overlaps the device computing batch N."""
    import jax

    put = lambda a: jax.device_put(np.asarray(a))  # noqa: E731
    if hasattr(ds, "features_masks"):  # MultiDataSet face
        ds.features = [put(f) for f in ds.features]
        ds.labels = [put(l) for l in ds.labels]
        if ds.features_masks is not None:
            ds.features_masks = [None if m is None else put(m)
                                 for m in ds.features_masks]
        if ds.labels_masks is not None:
            ds.labels_masks = [None if m is None else put(m)
                               for m in ds.labels_masks]
        return ds
    ds.features = put(ds.features)
    ds.labels = put(ds.labels)
    if ds.features_mask is not None:
        ds.features_mask = put(ds.features_mask)
    if ds.labels_mask is not None:
        ds.labels_mask = put(ds.labels_mask)
    return ds


def wrap_for_prefetch(iterator, prefetch_depth, device_put=device_put_batch):
    """Auto-wrap a fit() data source in async host→device prefetch.

    Returns ``iterator`` unchanged when prefetch cannot help or is refused:
    depth <= 0, a single-batch list, an iterator that is already an
    :class:`AsyncDataSetIterator`, or one that opts out via
    ``async_supported = False`` (:class:`AsyncShieldDataSetIterator` — the
    reference's contract at ``MultiLayerNetwork.java:1267``). Everything
    else gets a producer thread with ``prefetch_depth`` queue slots and a
    device-put stage, so batch N+1 is host-prepared AND device-resident
    while the device runs batch N."""
    depth = 2 if prefetch_depth is None else int(prefetch_depth)
    if depth <= 0:
        return iterator
    if isinstance(iterator, AsyncDataSetIterator):
        return iterator  # caller already chose its own prefetch config
    if not getattr(iterator, "async_supported", True):
        return iterator
    if isinstance(iterator, (list, tuple)) and len(iterator) <= 1:
        return iterator  # nothing to overlap with
    return AsyncDataSetIterator(iterator, queue_size=depth,
                                device_put=device_put)


class MultipleEpochsIterator(DataSetIterator):
    """Replays the base iterator N times as one pass (MultipleEpochsIterator)."""

    def __init__(self, base: DataSetIterator, n_epochs: int):
        self.base = base
        self.n_epochs = n_epochs

    def reset(self) -> None:
        self.base.reset()

    def __iter__(self) -> Iterator[DataSet]:
        for e in range(self.n_epochs):
            if e > 0:
                self.base.reset()
            yield from self.base


class EarlyTerminationDataSetIterator(DataSetIterator):
    """Caps the number of minibatches per pass (EarlyTerminationDataSetIterator)."""

    def __init__(self, base: DataSetIterator, max_minibatches: int):
        if max_minibatches <= 0:
            raise ValueError("max_minibatches must be > 0")
        self.base = base
        self.max_minibatches = max_minibatches

    def reset(self) -> None:
        self.base.reset()

    def __iter__(self) -> Iterator[DataSet]:
        for i, ds in enumerate(self.base):
            if i >= self.max_minibatches:
                break
            yield ds


class SamplingDataSetIterator(DataSetIterator):
    """Random with-replacement sampling of a DataSet (SamplingDataSetIterator)."""

    def __init__(self, data: DataSet, batch_size: int, total_batches: int,
                 seed: int = 0):
        self.data = data
        self.batch_size = batch_size
        self.total_batches = total_batches
        self.seed = seed
        self._epoch = 0

    def reset(self) -> None:
        self._epoch += 1

    def __iter__(self) -> Iterator[DataSet]:
        rng = np.random.default_rng(self.seed + self._epoch)
        f = np.asarray(self.data.features)
        l = np.asarray(self.data.labels)
        n = f.shape[0]
        for _ in range(self.total_batches):
            idx = rng.integers(0, n, size=self.batch_size)
            yield DataSet(f[idx], l[idx])


class DataSetIteratorSplitter:
    """Split one iterator stream into train/test partitions
    (DataSetIteratorSplitter.java): first ``ratio`` of ``total_batches``
    goes to train, rest to test.

    The window of ``total_batches`` is materialized from ONE pass over the
    base iterator and shared by both parts, so a shuffling base cannot leak
    test batches into train across resets (re-iterating the base per part
    would re-shuffle the example→batch assignment each pass).
    """

    def __init__(self, base: DataSetIterator, total_batches: int, ratio: float):
        self.base = base
        self.total_batches = total_batches
        self.n_train = int(total_batches * ratio)
        self._window: Optional[List[DataSet]] = None

    def _batches(self) -> List[DataSet]:
        if self._window is None:
            w: List[DataSet] = []
            for i, ds in enumerate(self.base):
                if i >= self.total_batches:
                    break
                w.append(ds)
            self._window = w
        return self._window

    @property
    def train(self) -> DataSetIterator:
        return _SplitPart(self, 0, self.n_train)

    @property
    def test(self) -> DataSetIterator:
        return _SplitPart(self, self.n_train, self.total_batches)


class _SplitPart(DataSetIterator):
    def __init__(self, splitter: DataSetIteratorSplitter, start: int, end: int):
        self.splitter = splitter
        self.start, self.end = start, end

    def reset(self) -> None:
        pass  # replays the shared materialized window

    def __iter__(self) -> Iterator[DataSet]:
        yield from self.splitter._batches()[self.start:self.end]


class IteratorDataSetIterator(DataSetIterator):
    """Re-batches a stream of small DataSets into ``batch_size`` examples
    (IteratorDataSetIterator.java)."""

    def __init__(self, base: DataSetIterator, batch_size: int):
        self.base = base
        self.batch_size = batch_size

    def reset(self) -> None:
        self.base.reset()

    def __iter__(self) -> Iterator[DataSet]:
        buf: List[DataSet] = []
        count = 0
        for ds in self.base:
            buf.append(ds)
            count += ds.num_examples()
            if count >= self.batch_size:
                yield DataSet.merge(buf)
                buf, count = [], 0
        if buf:
            yield DataSet.merge(buf)


class INDArrayDataSetIterator(DataSetIterator):
    """Iterate (features, labels) array pairs (INDArrayDataSetIterator.java)."""

    def __init__(self, pairs: Sequence, batch_size: int):
        self.pairs = list(pairs)
        self.batch_size = batch_size

    def reset(self) -> None:
        pass

    def __iter__(self) -> Iterator[DataSet]:
        buf_f, buf_l, count = [], [], 0
        for f, l in self.pairs:
            f = np.atleast_2d(np.asarray(f))
            l = np.atleast_2d(np.asarray(l))
            buf_f.append(f)
            buf_l.append(l)
            count += f.shape[0]
            if count >= self.batch_size:
                yield DataSet(np.concatenate(buf_f), np.concatenate(buf_l))
                buf_f, buf_l, count = [], [], 0
        if buf_f:
            yield DataSet(np.concatenate(buf_f), np.concatenate(buf_l))


class ExistingDataSetIterator(DataSetIterator):
    """Wrap an existing iterable of DataSets (ExistingDataSetIterator.java):
    exposes the DataSetIterator surface over a plain list/generator factory."""

    def __init__(self, iterable, total: Optional[int] = None):
        self._factory = iterable if callable(iterable) else None
        self._items = None if callable(iterable) else list(iterable)
        self.total = total

    def reset(self) -> None:
        pass

    def __iter__(self) -> Iterator[DataSet]:
        source = self._factory() if self._factory is not None else self._items
        for i, ds in enumerate(source):
            if self.total is not None and i >= self.total:
                return
            yield ds


class ViewIterator(DataSetIterator):
    """Batched view over one DataSet without copying the whole array up
    front (ViewIterator.java)."""

    def __init__(self, data: DataSet, batch_size: int):
        self.data = data
        self.batch_size = batch_size

    def reset(self) -> None:
        pass

    def __iter__(self) -> Iterator[DataSet]:
        n = self.data.num_examples()
        f = np.asarray(self.data.features)
        l = np.asarray(self.data.labels)
        fm = None if self.data.features_mask is None else np.asarray(self.data.features_mask)
        lm = None if self.data.labels_mask is None else np.asarray(self.data.labels_mask)
        for s in range(0, n, self.batch_size):
            e = s + self.batch_size
            yield DataSet(f[s:e], l[s:e],
                          None if fm is None else fm[s:e],
                          None if lm is None else lm[s:e])


class FileSplitDataSetIterator(DataSetIterator):
    """Stream serialized DataSets from files in a directory
    (FileSplitDataSetIterator.java). Files are ``.npz`` archives with
    features/labels(/masks) — what ParameterAveragingTrainingMaster's export
    staging writes; an optional callback runs per loaded DataSet."""

    def __init__(self, directory: str, pattern: str = "*.npz",
                 callback=None):
        import glob as _glob
        import os as _os
        self.files = sorted(_glob.glob(_os.path.join(directory, pattern)))
        self.callback = callback

    def reset(self) -> None:
        pass

    def __iter__(self) -> Iterator[DataSet]:
        for path in self.files:
            z = np.load(path)
            ds = DataSet(z["features"], z["labels"],
                         z["features_mask"] if "features_mask" in z else None,
                         z["labels_mask"] if "labels_mask" in z else None)
            if self.callback is not None:
                self.callback.call(ds)
            yield ds


class DataSetCallback:
    """Per-DataSet hook (datasets/iterator/callbacks/DataSetCallback.java)."""

    def call(self, ds: DataSet) -> None:  # pragma: no cover - interface
        pass


class DefaultCallback(DataSetCallback):
    """Moves each DataSet's arrays onto the accelerator ahead of the compute
    thread (the reference's DefaultCallback touches arrays so device-side
    prefetch happens off the training thread; here that's a device_put)."""

    def call(self, ds: DataSet) -> None:
        import jax
        ds.features = jax.device_put(np.asarray(ds.features))
        ds.labels = jax.device_put(np.asarray(ds.labels))
        # masks ride along too — a masked RNN batch would otherwise
        # re-transfer its masks on the training thread every step
        if ds.features_mask is not None:
            ds.features_mask = jax.device_put(np.asarray(ds.features_mask))
        if ds.labels_mask is not None:
            ds.labels_mask = jax.device_put(np.asarray(ds.labels_mask))


class AsyncShieldDataSetIterator(DataSetIterator):
    """Pass-through wrapper that blocks async prefetch wrapping
    (AsyncShieldDataSetIterator.java). In the reference this guards ND4J
    workspace-scoped arrays from being detached by the async thread; the
    jax runtime has no workspace scoping, so the semantic content is simply
    "do not wrap me in AsyncDataSetIterator" — honored via
    ``async_supported``."""

    async_supported = False

    def __init__(self, base: DataSetIterator):
        self.base = base

    def reset(self) -> None:
        self.base.reset()

    def __iter__(self) -> Iterator[DataSet]:
        return iter(self.base)


class AsyncShieldMultiDataSetIterator(AsyncShieldDataSetIterator):
    """MultiDataSet variant (AsyncShieldMultiDataSetIterator.java)."""


class EarlyTerminationMultiDataSetIterator(EarlyTerminationDataSetIterator):
    """MultiDataSet variant (EarlyTerminationMultiDataSetIterator.java) —
    identical truncation logic over MultiDataSet-yielding iterators."""


class JointParallelDataSetIterator(DataSetIterator):
    """Interleave several source iterators round-robin
    (datasets/iterator/parallel/JointParallelDataSetIterator.java with
    InequalityHandling.STOP_EVERYONE / PASS_NULL → here stop-on-first-
    exhausted or drain-remaining)."""

    def __init__(self, *iterators: DataSetIterator,
                 stop_on_first_exhausted: bool = True):
        self.iterators = list(iterators)
        self.stop_on_first_exhausted = stop_on_first_exhausted

    def reset(self) -> None:
        for it in self.iterators:
            it.reset()

    def __iter__(self) -> Iterator[DataSet]:
        its = [iter(i) for i in self.iterators]
        active = [True] * len(its)
        while any(active):
            for k, it in enumerate(its):
                if not active[k]:
                    continue
                try:
                    yield next(it)
                except StopIteration:
                    active[k] = False
                    if self.stop_on_first_exhausted:
                        return


class DoublesDataSetIterator(INDArrayDataSetIterator):
    """(features, labels) pairs of plain float sequences
    (DoublesDataSetIterator.java) — f64 arrays."""

    def __init__(self, pairs: Sequence, batch_size: int):
        super().__init__([(np.asarray(f, np.float64), np.asarray(l, np.float64))
                          for f, l in pairs], batch_size)


class FloatsDataSetIterator(INDArrayDataSetIterator):
    """(features, labels) pairs of plain float sequences
    (FloatsDataSetIterator.java) — f32 arrays."""

    def __init__(self, pairs: Sequence, batch_size: int):
        super().__init__([(np.asarray(f, np.float32), np.asarray(l, np.float32))
                          for f, l in pairs], batch_size)


class ReconstructionDataSetIterator(DataSetIterator):
    """Labels := features (ReconstructionDataSetIterator.java) — the
    autoencoder wrapper over any DataSetIterator."""

    def __init__(self, base: DataSetIterator):
        self.base = base

    def reset(self) -> None:
        if hasattr(self.base, "reset"):
            self.base.reset()

    def __iter__(self) -> Iterator[DataSet]:
        for ds in self.base:
            yield DataSet(ds.features, ds.features, ds.features_mask,
                          ds.features_mask)


class IteratorMultiDataSetIterator:
    """Re-batches a stream of MultiDataSets into ``batch_size`` examples
    (IteratorMultiDataSetIterator.java)."""

    def __init__(self, source, batch_size: int):
        self._items = list(source)
        self.batch_size = batch_size

    def reset(self) -> None:
        pass

    def __iter__(self):
        buf, count = [], 0
        for mds in self._items:
            buf.append(mds)
            count += int(np.asarray(mds.features[0]).shape[0])
            if count >= self.batch_size:
                yield _merge_mds(buf)
                buf, count = [], 0
        if buf:
            yield _merge_mds(buf)


def _merge_mds(items):
    from deeplearning4j_tpu.datasets.dataset import MultiDataSet
    n_f = len(items[0].features)
    n_l = len(items[0].labels)
    feats = [np.concatenate([np.asarray(m.features[i]) for m in items])
             for i in range(n_f)]
    labels = [np.concatenate([np.asarray(m.labels[i]) for m in items])
              for i in range(n_l)]
    return MultiDataSet(feats, labels)


class MultiDataSetWrapperIterator(DataSetIterator):
    """Single-input/single-output MultiDataSet iterator exposed as a plain
    DataSetIterator (MultiDataSetWrapperIterator.java)."""

    def __init__(self, base):
        self.base = base

    def reset(self) -> None:
        if hasattr(self.base, "reset"):
            self.base.reset()

    def __iter__(self) -> Iterator[DataSet]:
        for mds in self.base:
            if len(mds.features) != 1 or len(mds.labels) != 1:
                raise ValueError(
                    "MultiDataSetWrapperIterator requires single-input/"
                    f"single-output MultiDataSets (got {len(mds.features)} "
                    f"inputs, {len(mds.labels)} outputs)")
            fm = (None if mds.features_masks is None
                  else mds.features_masks[0])
            lm = None if mds.labels_masks is None else mds.labels_masks[0]
            yield DataSet(mds.features[0], mds.labels[0], fm, lm)


class DummyPreProcessor:
    """No-op DataSet pre-processor (DummyPreProcessor.java)."""

    def preprocess(self, ds) -> None:
        return None


class CombinedPreProcessor:
    """Apply a list of pre-processors / normalizers in order
    (CombinedPreProcessor.java). Handles both mutating ``preprocess`` and
    returning ``transform`` faces; returns the final DataSet."""

    def __init__(self, *preprocessors):
        self.preprocessors = list(preprocessors)

    def preprocess(self, ds):
        from deeplearning4j_tpu.datasets.dataset import apply_preprocessor
        for p in self.preprocessors:
            ds = apply_preprocessor(p, ds)
        return ds
