"""DataSet container and iterator SPI.

Reference: ND4J's ``DataSet`` (features/labels + optional masks) and
``DataSetIterator`` used by every fit loop
(``MultiLayerNetwork.fit(DataSetIterator):1262``). Host-side data stays in
numpy; device transfer happens at the jit boundary (and is overlapped by
``AsyncDataSetIterator`` — see datasets/iterators.py).
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

import numpy as np


class DataSet:
    """features/labels (+ optional masks), the unit a fit step consumes."""

    def __init__(self, features, labels, features_mask=None, labels_mask=None,
                 example_meta_data=None):
        self.features = features
        self.labels = labels
        self.features_mask = features_mask
        self.labels_mask = labels_mask
        # per-example provenance (RecordMetaData list), populated by record
        # iterators with collect_meta_data=True (DataSet.getExampleMetaData)
        self.example_meta_data = example_meta_data

    def get_example_meta_data(self):
        return self.example_meta_data

    def num_examples(self) -> int:
        return int(np.asarray(self.features).shape[0])

    def split_test_and_train(self, n_train: int):
        f = np.asarray(self.features)
        l = np.asarray(self.labels)
        return (DataSet(f[:n_train], l[:n_train]),
                DataSet(f[n_train:], l[n_train:]))

    def shuffle(self, seed: Optional[int] = None) -> None:
        rng = np.random.default_rng(seed)
        idx = rng.permutation(self.num_examples())
        self.features = np.asarray(self.features)[idx]
        self.labels = np.asarray(self.labels)[idx]
        if self.features_mask is not None:
            self.features_mask = np.asarray(self.features_mask)[idx]
        if self.labels_mask is not None:
            self.labels_mask = np.asarray(self.labels_mask)[idx]

    @staticmethod
    def merge(datasets: Sequence["DataSet"]) -> "DataSet":
        f = np.concatenate([np.asarray(d.features) for d in datasets])
        l = np.concatenate([np.asarray(d.labels) for d in datasets])

        def merge_masks(masks, arrays):
            # mixed mask presence: synthesize all-ones masks (all steps valid)
            if all(m is None for m in masks):
                return None
            out = []
            for m, a in zip(masks, arrays):
                if m is None:
                    a = np.asarray(a)
                    m = np.ones(a.shape[:2] if a.ndim >= 3 else a.shape[:1],
                                np.float32)
                out.append(np.asarray(m))
            return np.concatenate(out)

        fm = merge_masks([d.features_mask for d in datasets],
                         [d.features for d in datasets])
        lm = merge_masks([d.labels_mask for d in datasets],
                         [d.labels for d in datasets])
        return DataSet(f, l, fm, lm)


def apply_preprocessor(pre, ds):
    """Apply a DataSet pre-processor or normalizer, whichever face it
    exposes — mutating ``preprocess``/``pre_process`` or returning
    ``transform`` — and carry ``example_meta_data`` across a returned
    copy. The one shared implementation of this duck-typing."""
    fn = (getattr(pre, "preprocess", None)
          or getattr(pre, "pre_process", None)
          or getattr(pre, "transform", None))
    out = fn(ds)
    if out is not None:
        if getattr(out, "example_meta_data", None) is None:
            out.example_meta_data = getattr(ds, "example_meta_data", None)
        ds = out
    return ds


class MultiDataSet:
    """Multiple features/labels arrays (ComputationGraph input/output sets)."""

    def __init__(self, features: Sequence, labels: Sequence,
                 features_masks: Optional[Sequence] = None,
                 labels_masks: Optional[Sequence] = None):
        self.features = list(features)
        self.labels = list(labels)
        self.features_masks = None if features_masks is None else list(features_masks)
        self.labels_masks = None if labels_masks is None else list(labels_masks)

    def num_examples(self) -> int:
        return int(np.asarray(self.features[0]).shape[0])


def batch_nbytes(ds) -> int:
    """Host→device payload of one batch: features/labels/masks bytes, for
    both DataSet and MultiDataSet faces. Shared by ParallelWrapper and the
    single-process fit paths so ``training_transfer_bytes_total`` means the
    same thing everywhere."""
    total = 0
    if isinstance(ds, MultiDataSet):
        groups = [ds.features, ds.labels, ds.features_masks or (),
                  ds.labels_masks or ()]
        arrays = [a for g in groups for a in g]
    else:
        arrays = [ds.features, ds.labels, ds.features_mask, ds.labels_mask]
    for a in arrays:
        if a is not None:
            total += int(getattr(a, "nbytes", 0))
    return total


class DataSetIterator:
    """Iterator SPI (reset + iteration). Subclasses yield DataSet batches."""

    def reset(self) -> None:  # pragma: no cover - interface
        pass

    def __iter__(self) -> Iterator[DataSet]:  # pragma: no cover - interface
        raise NotImplementedError


class ListDataSetIterator(DataSetIterator):
    """Batches a DataSet (or list of examples) — DL4J ListDataSetIterator."""

    def __init__(self, data: DataSet, batch_size: int = 32, shuffle: bool = False,
                 seed: int = 0, drop_last: bool = False):
        self.data = data
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self._epoch = 0

    def reset(self) -> None:
        self._epoch += 1

    def __iter__(self) -> Iterator[DataSet]:
        n = self.data.num_examples()
        idx = np.arange(n)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self._epoch)
            rng.shuffle(idx)
        f = np.asarray(self.data.features)
        l = np.asarray(self.data.labels)
        fm = None if self.data.features_mask is None else np.asarray(self.data.features_mask)
        lm = None if self.data.labels_mask is None else np.asarray(self.data.labels_mask)
        for s in range(0, n, self.batch_size):
            sel = idx[s:s + self.batch_size]
            if self.drop_last and len(sel) < self.batch_size:
                break
            yield DataSet(f[sel], l[sel],
                          None if fm is None else fm[sel],
                          None if lm is None else lm[sel])
