"""Dataset fetchers/iterators — MNIST, EMNIST, CIFAR, Iris, UCI, …

Reference: ``deeplearning4j-core/.../datasets/fetchers/`` +
``iterator/impl/``: ``MnistDataFetcher.java:42``, EMNIST, Cifar, SVHN,
TinyImageNet, LFW, ``IrisDataSetIterator``, UCI synthetic control, with
download-cache-extract base ``CacheableExtractableDataSetFetcher``.

This environment has no egress, so fetchers resolve data in this order:
1. local cache dir (``$DL4J_TPU_DATA_DIR`` or ``~/.deeplearning4j_tpu/data``)
   holding the standard file formats (MNIST idx, CIFAR binary batches);
2. datasets bundled with locally installed libs (sklearn's real Iris);
3. deterministic synthetic data with the same shapes/classes when
   ``allow_synthetic=True`` (the default for tests) — clearly marked.
"""

from __future__ import annotations

import gzip
import os
import struct
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, DataSetIterator, ListDataSetIterator


def data_dir() -> Path:
    return Path(os.environ.get("DL4J_TPU_DATA_DIR",
                               os.path.expanduser("~/.deeplearning4j_tpu/data")))


def _read_idx(path: Path) -> np.ndarray:
    """Parse an IDX file (optionally gzipped) — the MNIST/EMNIST format."""
    opener = gzip.open if path.suffix == ".gz" else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = [struct.unpack(">I", f.read(4))[0] for _ in range(ndim)]
        data = np.frombuffer(f.read(), np.uint8)
    return data.reshape(dims)


def _find(base: Path, names) -> Optional[Path]:
    for n in names:
        p = base / n
        if p.exists():
            return p
        pg = base / (n + ".gz")
        if pg.exists():
            return pg
    return None


def _synthetic_images(n: int, h: int, w: int, c: int, n_classes: int,
                      seed: int) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic class-separable images: class k gets a bright band at a
    class-specific row plus noise. Learnable by convs; NOT real data."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, n_classes, size=n)
    x = rng.uniform(0, 0.2, size=(n, h, w, c)).astype(np.float32)
    rows = (np.linspace(0, h - 3, n_classes)).astype(int)
    for i in range(n):
        r = rows[labels[i]]
        x[i, r:r + 2, :, :] += 0.8
    return (x * 255).astype(np.float32), labels.astype(np.int64)


class MnistDataFetcher:
    """MNIST (MnistDataFetcher.java:42). Loads idx files from the cache dir
    (``mnist/``) or synthesizes deterministic stand-in digits."""

    NUM_EXAMPLES = 60000
    NUM_EXAMPLES_TEST = 10000

    def __init__(self, train: bool = True, allow_synthetic: bool = True,
                 synthetic_size: Optional[int] = None, seed: int = 123):
        base = data_dir() / "mnist"
        img_names = (["train-images-idx3-ubyte", "train-images.idx3-ubyte"]
                     if train else ["t10k-images-idx3-ubyte", "t10k-images.idx3-ubyte"])
        lbl_names = (["train-labels-idx1-ubyte", "train-labels.idx1-ubyte"]
                     if train else ["t10k-labels-idx1-ubyte", "t10k-labels.idx1-ubyte"])
        img_p, lbl_p = _find(base, img_names), _find(base, lbl_names)
        if img_p is not None and lbl_p is not None:
            imgs = _read_idx(img_p).astype(np.float32)
            self.labels = _read_idx(lbl_p).astype(np.int64)
            self.images = imgs[..., None]  # NHWC
            self.synthetic = False
        elif allow_synthetic:
            n = synthetic_size or (4096 if train else 1024)
            self.images, self.labels = _synthetic_images(
                n, 28, 28, 1, 10, seed + (0 if train else 1))
            self.synthetic = True
        else:
            raise FileNotFoundError(
                f"MNIST idx files not found under {base}; place the standard "
                "files there or pass allow_synthetic=True")

    def dataset(self, normalize: bool = True) -> DataSet:
        x = self.images / 255.0 if normalize else self.images
        y = np.eye(10, dtype=np.float32)[self.labels]
        return DataSet(x.astype(np.float32), y)


class MnistDataSetIterator(ListDataSetIterator):
    """DL4J MnistDataSetIterator(batch, train) equivalent."""

    def __init__(self, batch_size: int, train: bool = True, *, shuffle=True,
                 seed: int = 123, normalize: bool = True,
                 allow_synthetic: bool = True, synthetic_size=None):
        fetcher = MnistDataFetcher(train, allow_synthetic, synthetic_size, seed)
        self.synthetic = fetcher.synthetic
        super().__init__(fetcher.dataset(normalize), batch_size, shuffle, seed)


class EmnistDataSetIterator(ListDataSetIterator):
    """EMNIST (EmnistDataFetcher): same idx format, more classes. Sets:
    letters(26), digits(10), balanced(47), byclass(62), bymerge(47)."""

    SETS = {"letters": 26, "digits": 10, "balanced": 47, "byclass": 62,
            "bymerge": 47, "mnist": 10}

    def __init__(self, dataset: str, batch_size: int, train: bool = True, *,
                 shuffle=True, seed: int = 123, allow_synthetic: bool = True):
        if dataset not in self.SETS:
            raise ValueError(f"unknown EMNIST set {dataset!r}")
        n_classes = self.SETS[dataset]
        base = data_dir() / "emnist"
        split = "train" if train else "test"
        img_p = _find(base, [f"emnist-{dataset}-{split}-images-idx3-ubyte"])
        lbl_p = _find(base, [f"emnist-{dataset}-{split}-labels-idx1-ubyte"])
        if img_p is not None and lbl_p is not None:
            x = _read_idx(img_p).astype(np.float32)[..., None] / 255.0
            lab = _read_idx(lbl_p).astype(np.int64)
            if dataset == "letters":  # the letters set alone is 1-indexed
                lab = lab - 1
            self.synthetic = False
        else:
            x, lab = _synthetic_images(2048 if train else 512, 28, 28, 1,
                                       n_classes, seed)
            x = x / 255.0
            self.synthetic = True
        y = np.eye(n_classes, dtype=np.float32)[lab]
        super().__init__(DataSet(x.astype(np.float32), y), batch_size, shuffle, seed)


class CifarDataSetIterator(ListDataSetIterator):
    """CIFAR-10 (CifarDataSetIterator): binary batches from cache dir or
    synthetic 32x32x3 stand-ins."""

    def __init__(self, batch_size: int, train: bool = True, *, shuffle=True,
                 seed: int = 123, allow_synthetic: bool = True):
        base = data_dir() / "cifar-10-batches-bin"
        files = ([base / f"data_batch_{i}.bin" for i in range(1, 6)]
                 if train else [base / "test_batch.bin"])
        if all(f.exists() for f in files):
            xs, ys = [], []
            for f in files:
                raw = np.frombuffer(f.read_bytes(), np.uint8).reshape(-1, 3073)
                ys.append(raw[:, 0].astype(np.int64))
                xs.append(raw[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
            x = np.concatenate(xs).astype(np.float32) / 255.0
            lab = np.concatenate(ys)
            self.synthetic = False
        else:
            x, lab = _synthetic_images(2048 if train else 512, 32, 32, 3, 10, seed)
            x = x / 255.0
            self.synthetic = True
        y = np.eye(10, dtype=np.float32)[lab]
        super().__init__(DataSet(x.astype(np.float32), y), batch_size, shuffle, seed)


class _CachedNpyIterator(ListDataSetIterator):
    """Shared cache-or-synthetic loader: ``<dir>/<split>_{x,y}.npy`` if
    present, else deterministic synthetic stand-ins (the reference's
    ``CacheableExtractableDataSetFetcher`` downloads instead; this image has
    no egress)."""

    def __init__(self, batch_size: int, *, dir_name: str, split: str,
                 n_synth: int, hw: int, n_classes: int,
                 shuffle=True, seed: int = 123):
        base = data_dir() / dir_name
        xp, yp = base / f"{split}_x.npy", base / f"{split}_y.npy"
        if xp.exists() and yp.exists():
            x = np.load(xp).astype(np.float32) / 255.0
            lab = np.load(yp).astype(np.int64)
            self.synthetic = False
        else:
            x, lab = _synthetic_images(n_synth, hw, hw, 3, n_classes, seed)
            x = x / 255.0
            self.synthetic = True
        y = np.eye(n_classes, dtype=np.float32)[lab]
        super().__init__(DataSet(x.astype(np.float32), y), batch_size, shuffle, seed)


class TinyImageNetDataSetIterator(_CachedNpyIterator):
    """TinyImageNet (TinyImageNetFetcher): 64x64x3, 200 classes."""

    def __init__(self, batch_size: int, train: bool = True, *, shuffle=True,
                 seed: int = 123, n_classes: int = 200):
        super().__init__(batch_size, dir_name="tinyimagenet",
                         split="train" if train else "val",
                         n_synth=1024 if train else 256, hw=64,
                         n_classes=n_classes, shuffle=shuffle, seed=seed)


class LFWDataSetIterator(_CachedNpyIterator):
    """LFW faces (LFWDataSetIterator / LFWDataFetcher): cache-or-synthetic.
    The reference serves 250x250x3 faces over 5749 identities with a
    configurable subset; here image side and label count are parameters and
    the cache layout is ``lfw/{train,test}_{x,y}.npy``."""

    def __init__(self, batch_size: int, train: bool = True, *, shuffle=True,
                 seed: int = 123, n_classes: int = 10, image_size: int = 64):
        super().__init__(batch_size, dir_name="lfw",
                         split="train" if train else "test",
                         n_synth=512 if train else 128, hw=image_size,
                         n_classes=n_classes, shuffle=shuffle, seed=seed)


class SvhnDataSetIterator(_CachedNpyIterator):
    """SVHN (SvhnDataFetcher): 32x32x3 digits, same cache-or-synthetic policy."""

    def __init__(self, batch_size: int, train: bool = True, *, shuffle=True,
                 seed: int = 123):
        super().__init__(batch_size, dir_name="svhn",
                         split="train" if train else "test",
                         n_synth=1024 if train else 256, hw=32,
                         n_classes=10, shuffle=shuffle, seed=seed)


class IrisDataSetIterator(ListDataSetIterator):
    """Iris (IrisDataSetIterator): the real 150-example dataset via sklearn's
    bundled copy (offline), else a deterministic 3-cluster stand-in."""

    def __init__(self, batch_size: int = 150, num_examples: int = 150, *,
                 shuffle=False, seed: int = 123):
        try:
            from sklearn.datasets import load_iris
            d = load_iris()
            x = d.data.astype(np.float32)
            lab = d.target.astype(np.int64)
            self.synthetic = False
        except Exception:  # pragma: no cover - sklearn always present in CI
            rng = np.random.default_rng(seed)
            lab = np.repeat(np.arange(3), 50)
            centers = np.asarray([[5.0, 3.4, 1.5, 0.2], [5.9, 2.8, 4.3, 1.3],
                                  [6.6, 3.0, 5.6, 2.0]], np.float32)
            x = centers[lab] + rng.normal(0, 0.3, (150, 4)).astype(np.float32)
            self.synthetic = True
        x, lab = x[:num_examples], lab[:num_examples]
        y = np.eye(3, dtype=np.float32)[lab]
        super().__init__(DataSet(x, y), batch_size, shuffle, seed)


class UciSequenceDataSetIterator(DataSetIterator):
    """UCI synthetic-control sequences (UciSequenceDataFetcher): 600 series of
    length 60 in 6 classes; generated deterministically per the published
    generator equations (the UCI 'synthetic control' data is itself synthetic)."""

    def __init__(self, batch_size: int, train: bool = True, seed: int = 123):
        rng = np.random.default_rng(seed + (0 if train else 7))
        n_per = 100 if train else 20
        t = np.arange(60, dtype=np.float32)
        xs, ys = [], []
        for cls in range(6):
            for _ in range(n_per):
                base = 30 + rng.normal(0, 2, 60).astype(np.float32)
                if cls == 1:    # cyclic
                    base += 15 * np.sin(2 * np.pi * t / rng.uniform(10, 15))
                elif cls == 2:  # increasing trend
                    base += rng.uniform(0.2, 0.5) * t
                elif cls == 3:  # decreasing trend
                    base -= rng.uniform(0.2, 0.5) * t
                elif cls == 4:  # upward shift
                    base += np.where(t > rng.uniform(20, 40), rng.uniform(7.5, 20), 0)
                elif cls == 5:  # downward shift
                    base -= np.where(t > rng.uniform(20, 40), rng.uniform(7.5, 20), 0)
                xs.append(base[:, None])  # [T, 1]
                ys.append(cls)
        x = np.stack(xs).astype(np.float32)  # [N, 60, 1]
        y = np.eye(6, dtype=np.float32)[np.asarray(ys)]
        self._it = ListDataSetIterator(DataSet(x, y), batch_size, shuffle=True,
                                       seed=seed)

    def reset(self):
        self._it.reset()

    def __iter__(self):
        return iter(self._it)
