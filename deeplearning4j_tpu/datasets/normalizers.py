"""Data normalizers — fit/transform/revert preprocessing.

Reference: ND4J's ``DataNormalization`` family used throughout DL4J examples
and serialized into model zips (``ModelSerializer.addNormalizerToModel:654``):
NormalizerStandardize (zero mean / unit variance), NormalizerMinMaxScaler,
ImagePreProcessingScaler (pixel [0,255] → [0,1]), and the zoo's
VGG16ImagePreProcessor (mean-RGB subtraction).
"""

from __future__ import annotations

import json
from typing import Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet

NORMALIZER_REGISTRY = {}


def register_normalizer(cls):
    NORMALIZER_REGISTRY[cls.__name__] = cls
    return cls


class Normalizer:
    """fit(iterator|DataSet) → transform/revert in place (DataNormalization).

    ``fit_label`` mirrors DL4J's ``DataNormalization.fitLabel(boolean)``:
    when True, ``fit`` also collects label statistics and
    ``transform``/``revert`` apply them to ``ds.labels``.
    """

    fit_label: bool = False

    def fit(self, data) -> "Normalizer":
        raise NotImplementedError

    def transform(self, ds: DataSet) -> DataSet:
        raise NotImplementedError

    def revert(self, ds: DataSet) -> DataSet:
        raise NotImplementedError

    def pre_process(self, ds: DataSet) -> DataSet:  # DL4J alias
        return self.transform(ds)

    # -- serde ---------------------------------------------------------------
    def to_dict(self) -> dict:
        raise NotImplementedError

    @staticmethod
    def from_dict(d: dict) -> "Normalizer":
        cls = NORMALIZER_REGISTRY[d["@normalizer"]]
        return cls._from_dict(d)

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    @staticmethod
    def from_json(s: str) -> "Normalizer":
        return Normalizer.from_dict(json.loads(s))


def _iter_datasets(data):
    if isinstance(data, DataSet):
        yield data
    else:
        if hasattr(data, "reset"):
            data.reset()
        yield from data


class _MomentAcc:
    """Streaming per-feature mean/std over [*, n]-shaped batches."""

    def __init__(self):
        self.count, self.s, self.s2 = 0, None, None

    def add(self, a):
        f = np.asarray(a, np.float64)
        f2 = f.reshape(-1, f.shape[-1]) if f.ndim > 2 else f
        if self.s is None:
            self.s = f2.sum(0)
            self.s2 = (f2 ** 2).sum(0)
        else:
            self.s += f2.sum(0)
            self.s2 += (f2 ** 2).sum(0)
        self.count += f2.shape[0]

    def finish(self, what):
        if self.count == 0:
            raise ValueError(f"nothing to fit: no {what}")
        mean = (self.s / self.count).astype(np.float32)
        var = self.s2 / self.count - (self.s / self.count) ** 2
        std = np.sqrt(np.maximum(var, 1e-12)).astype(np.float32)
        return mean, std


class _ExtremaAcc:
    """Streaming per-feature min/max over [*, n]-shaped batches."""

    def __init__(self):
        self.lo, self.hi = None, None

    def add(self, a):
        f = np.asarray(a)
        f2 = f.reshape(-1, f.shape[-1]) if f.ndim > 2 else f
        mn, mx = f2.min(0), f2.max(0)
        self.lo = mn if self.lo is None else np.minimum(self.lo, mn)
        self.hi = mx if self.hi is None else np.maximum(self.hi, mx)

    def finish(self, what):
        if self.lo is None:
            raise ValueError(f"nothing to fit: no {what}")
        return self.lo.astype(np.float32), self.hi.astype(np.float32)


@register_normalizer
class NormalizerStandardize(Normalizer):
    """Per-feature zero-mean/unit-std (NormalizerStandardize)."""

    def __init__(self):
        self.mean: Optional[np.ndarray] = None
        self.std: Optional[np.ndarray] = None
        self.fit_label = False
        self.label_mean: Optional[np.ndarray] = None
        self.label_std: Optional[np.ndarray] = None

    def fit(self, data) -> "NormalizerStandardize":
        # single streaming pass: feature and (optional) label moments
        # accumulate together, O(batch) memory
        f_acc, l_acc = _MomentAcc(), _MomentAcc()
        for ds in _iter_datasets(data):
            f_acc.add(ds.features)
            if self.fit_label and ds.labels is not None:
                l_acc.add(ds.labels)
        self.mean, self.std = f_acc.finish("features")
        if self.fit_label:
            self.label_mean, self.label_std = l_acc.finish(
                "labels (fit_label=True but no batch carried labels)")
        return self

    def transform(self, ds: DataSet) -> DataSet:
        f = (np.asarray(ds.features) - self.mean) / self.std
        labels = ds.labels
        if self.fit_label and self.label_mean is not None and labels is not None:
            labels = ((np.asarray(labels) - self.label_mean)
                      / self.label_std).astype(np.float32)
        return DataSet(f.astype(np.float32), labels, ds.features_mask,
                       ds.labels_mask)

    def revert(self, ds: DataSet) -> DataSet:
        f = np.asarray(ds.features) * self.std + self.mean
        labels = ds.labels
        if self.fit_label and self.label_mean is not None and labels is not None:
            labels = (np.asarray(labels) * self.label_std
                      + self.label_mean).astype(np.float32)
        return DataSet(f.astype(np.float32), labels, ds.features_mask,
                       ds.labels_mask)

    def to_dict(self) -> dict:
        d = {"@normalizer": "NormalizerStandardize",
             "mean": self.mean.tolist(), "std": self.std.tolist()}
        if self.fit_label and self.label_mean is not None:
            d["label_mean"] = self.label_mean.tolist()
            d["label_std"] = self.label_std.tolist()
        return d

    @classmethod
    def _from_dict(cls, d):
        n = cls()
        n.mean = np.asarray(d["mean"], np.float32)
        n.std = np.asarray(d["std"], np.float32)
        if "label_mean" in d:
            n.fit_label = True
            n.label_mean = np.asarray(d["label_mean"], np.float32)
            n.label_std = np.asarray(d["label_std"], np.float32)
        return n


@register_normalizer
class NormalizerMinMaxScaler(Normalizer):
    """Scale features to [min_range, max_range] (NormalizerMinMaxScaler)."""

    def __init__(self, min_range: float = 0.0, max_range: float = 1.0):
        self.min_range = min_range
        self.max_range = max_range
        self.data_min: Optional[np.ndarray] = None
        self.data_max: Optional[np.ndarray] = None
        self.fit_label = False
        self.label_min: Optional[np.ndarray] = None
        self.label_max: Optional[np.ndarray] = None

    def fit(self, data) -> "NormalizerMinMaxScaler":
        f_acc, l_acc = _ExtremaAcc(), _ExtremaAcc()
        for ds in _iter_datasets(data):
            f_acc.add(ds.features)
            if self.fit_label and ds.labels is not None:
                l_acc.add(ds.labels)
        self.data_min, self.data_max = f_acc.finish("features")
        if self.fit_label:
            self.label_min, self.label_max = l_acc.finish(
                "labels (fit_label=True but no batch carried labels)")
        return self

    def _scale(self, a, lo, hi):
        rng = np.maximum(hi - lo, 1e-12)
        out = (np.asarray(a) - lo) / rng
        return (out * (self.max_range - self.min_range)
                + self.min_range).astype(np.float32)

    def _unscale(self, a, lo, hi):
        rng = np.maximum(hi - lo, 1e-12)
        out = ((np.asarray(a) - self.min_range)
               / (self.max_range - self.min_range))
        return (out * rng + lo).astype(np.float32)

    def transform(self, ds: DataSet) -> DataSet:
        f = self._scale(ds.features, self.data_min, self.data_max)
        labels = ds.labels
        if self.fit_label and self.label_min is not None and labels is not None:
            labels = self._scale(labels, self.label_min, self.label_max)
        return DataSet(f, labels, ds.features_mask, ds.labels_mask)

    def revert(self, ds: DataSet) -> DataSet:
        f = self._unscale(ds.features, self.data_min, self.data_max)
        labels = ds.labels
        if self.fit_label and self.label_min is not None and labels is not None:
            labels = self._unscale(labels, self.label_min, self.label_max)
        return DataSet(f, labels, ds.features_mask, ds.labels_mask)

    def to_dict(self) -> dict:
        d = {"@normalizer": "NormalizerMinMaxScaler",
             "min_range": self.min_range, "max_range": self.max_range,
             "data_min": self.data_min.tolist(),
             "data_max": self.data_max.tolist()}
        if self.fit_label and self.label_min is not None:
            d["label_min"] = self.label_min.tolist()
            d["label_max"] = self.label_max.tolist()
        return d

    @classmethod
    def _from_dict(cls, d):
        n = cls(d["min_range"], d["max_range"])
        n.data_min = np.asarray(d["data_min"], np.float32)
        n.data_max = np.asarray(d["data_max"], np.float32)
        if "label_min" in d:
            n.fit_label = True
            n.label_min = np.asarray(d["label_min"], np.float32)
            n.label_max = np.asarray(d["label_max"], np.float32)
        return n


@register_normalizer
class ImagePreProcessingScaler(Normalizer):
    """Pixels [0, max_pixel] → [min, max] (ImagePreProcessingScaler)."""

    def __init__(self, min_range: float = 0.0, max_range: float = 1.0,
                 max_pixel: float = 255.0):
        self.min_range = min_range
        self.max_range = max_range
        self.max_pixel = max_pixel

    def fit(self, data) -> "ImagePreProcessingScaler":
        return self  # stateless

    def transform(self, ds: DataSet) -> DataSet:
        f = np.asarray(ds.features) / self.max_pixel
        f = f * (self.max_range - self.min_range) + self.min_range
        return DataSet(f.astype(np.float32), ds.labels, ds.features_mask,
                       ds.labels_mask)

    def revert(self, ds: DataSet) -> DataSet:
        f = (np.asarray(ds.features) - self.min_range) / (self.max_range - self.min_range)
        return DataSet((f * self.max_pixel).astype(np.float32), ds.labels,
                       ds.features_mask, ds.labels_mask)

    def to_dict(self) -> dict:
        return {"@normalizer": "ImagePreProcessingScaler",
                "min_range": self.min_range, "max_range": self.max_range,
                "max_pixel": self.max_pixel}

    @classmethod
    def _from_dict(cls, d):
        return cls(d["min_range"], d["max_range"], d["max_pixel"])


@register_normalizer
class VGG16ImagePreProcessor(Normalizer):
    """Subtract ImageNet mean RGB (zoo VGG16ImagePreProcessor), NHWC."""

    MEAN_RGB = np.asarray([123.68, 116.779, 103.939], np.float32)

    def fit(self, data) -> "VGG16ImagePreProcessor":
        return self

    def transform(self, ds: DataSet) -> DataSet:
        f = np.asarray(ds.features) - self.MEAN_RGB
        return DataSet(f.astype(np.float32), ds.labels, ds.features_mask,
                       ds.labels_mask)

    def revert(self, ds: DataSet) -> DataSet:
        f = np.asarray(ds.features) + self.MEAN_RGB
        return DataSet(f.astype(np.float32), ds.labels, ds.features_mask,
                       ds.labels_mask)

    def to_dict(self) -> dict:
        return {"@normalizer": "VGG16ImagePreProcessor"}

    @classmethod
    def _from_dict(cls, d):
        return cls()


class NormalizingIterator:
    """Applies a fitted normalizer to every batch of a base iterator."""

    def __init__(self, base, normalizer: Normalizer):
        self.base = base
        self.normalizer = normalizer

    def reset(self):
        if hasattr(self.base, "reset"):
            self.base.reset()

    def __iter__(self):
        for ds in self.base:
            yield self.normalizer.transform(ds)


class MultiNormalizer:
    """Per-input normalization of MultiDataSets (reference:
    ``MultiNormalizerStandardize`` / ``MultiNormalizerMinMaxScaler`` in ND4J):
    one child normalizer per features array. Labels pass through unless
    ``fit_label`` is set (DL4J's ``fitLabel(true)``), in which case one
    label child per labels array is fitted and applied.

    ``kind`` selects the child type: "standardize" | "minmax".
    """

    def __init__(self, kind: str = "standardize", **kwargs):
        if kind not in ("standardize", "minmax"):
            raise ValueError(f"unknown MultiNormalizer kind {kind!r}")
        self.kind = kind
        self.kwargs = kwargs
        self.children = []
        self.fit_label = False
        self.label_children = []

    def _new_child(self):
        return (NormalizerStandardize() if self.kind == "standardize"
                else NormalizerMinMaxScaler(**self.kwargs))

    def fit(self, data) -> "MultiNormalizer":
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet
        mds_list = [data] if isinstance(data, MultiDataSet) else list(data)
        n_inputs = len(mds_list[0].features)
        self.children = [self._new_child() for _ in range(n_inputs)]
        for i, child in enumerate(self.children):
            child.fit([DataSet(m.features[i],
                               m.labels[0] if m.labels else None)
                       for m in mds_list])
        if self.fit_label:
            labeled = [m for m in mds_list if m.labels]
            if not labeled:
                raise ValueError(
                    "nothing to fit: labels (fit_label=True but no "
                    "MultiDataSet carried labels)")
            n_outputs = len(labeled[0].labels)
            self.label_children = [self._new_child()
                                   for _ in range(n_outputs)]
            for o, child in enumerate(self.label_children):
                child.fit([DataSet(m.labels[o], None) for m in labeled])
        return self

    def transform(self, mds):
        if not self.children:
            raise ValueError("fit the MultiNormalizer first")
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet
        labels = mds.labels[0] if mds.labels else None
        feats = [np.asarray(c.transform(DataSet(f, labels)).features)
                 for c, f in zip(self.children, mds.features)]
        out_labels = mds.labels
        if self.label_children and mds.labels:
            out_labels = [
                np.asarray(c.transform(DataSet(y, None)).features)
                for c, y in zip(self.label_children, mds.labels)]
        return MultiDataSet(feats, out_labels, mds.features_masks,
                            mds.labels_masks)

    pre_process = transform

    def revert(self, mds):
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet
        labels = mds.labels[0] if mds.labels else None
        feats = [np.asarray(c.revert(DataSet(f, labels)).features)
                 for c, f in zip(self.children, mds.features)]
        out_labels = mds.labels
        if self.label_children and mds.labels:
            out_labels = [
                np.asarray(c.revert(DataSet(y, None)).features)
                for c, y in zip(self.label_children, mds.labels)]
        return MultiDataSet(feats, out_labels, mds.features_masks,
                            mds.labels_masks)

    def to_dict(self) -> dict:
        return {"@normalizer": "MultiNormalizer", "kind": self.kind,
                "kwargs": self.kwargs,
                "children": [c.to_dict() for c in self.children],
                "label_children": [c.to_dict()
                                   for c in self.label_children]}

    @staticmethod
    def from_dict(d: dict) -> "MultiNormalizer":
        m = MultiNormalizer(d["kind"], **d.get("kwargs", {}))
        m.children = [Normalizer.from_dict(c) for c in d.get("children", [])]
        m.label_children = [Normalizer.from_dict(c)
                            for c in d.get("label_children", [])]
        m.fit_label = bool(m.label_children)
        return m
