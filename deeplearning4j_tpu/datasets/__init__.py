from deeplearning4j_tpu.datasets.dataset import (  # noqa: F401
    DataSet,
    DataSetIterator,
    ListDataSetIterator,
    MultiDataSet,
)
from deeplearning4j_tpu.datasets.iterators import (  # noqa: F401
    AsyncDataSetIterator,
    AsyncMultiDataSetIterator,
    DataSetIteratorSplitter,
    EarlyTerminationDataSetIterator,
    INDArrayDataSetIterator,
    IteratorDataSetIterator,
    MultipleEpochsIterator,
    SamplingDataSetIterator,
)
from deeplearning4j_tpu.datasets.fetchers import (  # noqa: F401
    CifarDataSetIterator,
    EmnistDataSetIterator,
    IrisDataSetIterator,
    MnistDataFetcher,
    MnistDataSetIterator,
    SvhnDataSetIterator,
    TinyImageNetDataSetIterator,
    UciSequenceDataSetIterator,
)
from deeplearning4j_tpu.datasets.normalizers import (  # noqa: F401
    ImagePreProcessingScaler,
    Normalizer,
    MultiNormalizer,
    NormalizerMinMaxScaler,
    NormalizerStandardize,
    NormalizingIterator,
    VGG16ImagePreProcessor,
)
