from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator  # noqa: F401
