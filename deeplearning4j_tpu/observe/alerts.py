"""Alerting: a rule engine over any ``MetricsRegistry`` exposition.

The action half of the observability loop — signals the metrics core
already exports become firing/resolved alerts routed to sinks. The engine
deliberately reads metrics THROUGH the Prometheus text exposition
(``parse_prometheus_text(registry.exposition())``): the rules see exactly
what an external Prometheus would scrape, so the exposition format is the
contract (and the tests lock it).

Rule types:

- :class:`ThresholdRule` — instantaneous comparison of a series sum
  (label-subset matched) against a bound, with an optional ``for_s``
  pending duration;
- :class:`AbsenceRule` — the metric stopped being exported (a dead
  exporter looks exactly like a healthy zero without this);
- :class:`RateOfChangeRule` — per-second derivative over a lookback
  window (counter resets clamp to 0, the ``rate()`` convention);
- :class:`BurnRateRule` — multiwindow SLO burn-rate alerting (Google SRE
  Workbook ch. 5): for an SLO objective like "99% of requests succeed",
  burn rate = (error ratio in window) / (error budget); the rule fires
  when BOTH a long and a short window exceed the factor — the long window
  gives significance, the short one fast detection AND fast resolution.

:class:`AlertManager` evaluates rules against a sample history, runs the
``ok → pending → firing → resolved`` state machine (each transition
notifies every sink exactly once — dedup by construction), and can run as
a background evaluator. The clock is an injectable
``parallel.time_source.TimeSource`` so every transition is unit-testable
deterministically (``ManualTimeSource`` + ``evaluate_once``).

Rules load from JSON (``load_rules``) so the ``--alerts rules.json`` CLI
flag and ``tools/validate_alert_rules.py`` share one schema.
"""

from __future__ import annotations

import json
import logging
import operator
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from deeplearning4j_tpu.observe import log as _slog
from deeplearning4j_tpu.observe.metrics import (MetricsRegistry,
                                                parse_prometheus_text)
from deeplearning4j_tpu.parallel.time_source import (TimeSource,
                                                     get_time_source)

log = logging.getLogger(__name__)

_OPS: Dict[str, Callable[[float, float], bool]] = {
    ">": operator.gt, ">=": operator.ge, "<": operator.lt,
    "<=": operator.le, "==": operator.eq, "!=": operator.ne,
}

Sample = Dict[str, Dict[Tuple[Tuple[str, str], ...], float]]


def series_sum(sample: Sample, metric: str,
               labels: Optional[Dict[str, str]] = None) -> Optional[float]:
    """Sum every series of ``metric`` whose labels INCLUDE ``labels``
    (subset match, the PromQL selector shape); ``None`` when no series
    matches — absence is distinct from zero."""
    series = sample.get(metric)
    if not series:
        return None
    want = set((str(k), str(v)) for k, v in (labels or {}).items())
    vals = [v for key, v in series.items() if want <= set(key)]
    return sum(vals) if vals else None


class SampleHistory:
    """Bounded ring of ``(t_seconds, parsed exposition)`` samples — the
    lookback store windowed rules read. Old samples age out past
    ``max_age_s`` (sized for the longest burn-rate window)."""

    def __init__(self, max_age_s: float = 2 * 3600.0,
                 max_samples: int = 4096):
        self.max_age_s = float(max_age_s)
        self._samples: "deque[Tuple[float, Sample]]" = deque(
            maxlen=int(max_samples))

    def add(self, t: float, sample: Sample) -> None:
        self._samples.append((float(t), sample))
        while self._samples and self._samples[0][0] < t - self.max_age_s:
            self._samples.popleft()

    def latest(self) -> Optional[Tuple[float, Sample]]:
        return self._samples[-1] if self._samples else None

    def oldest(self) -> Optional[Tuple[float, Sample]]:
        return self._samples[0] if self._samples else None

    def at_or_before(self, t: float) -> Optional[Tuple[float, Sample]]:
        """The NEWEST sample not newer than ``t`` (None when every sample
        is newer)."""
        best = None
        for ts, sample in self._samples:
            if ts <= t:
                best = (ts, sample)
            else:
                break
        return best

    def __len__(self) -> int:
        return len(self._samples)


class RuleResult:
    """One evaluation: is the condition met right now, with evidence."""

    __slots__ = ("active", "value", "detail")

    def __init__(self, active: bool, value: Optional[float], detail: str):
        self.active = bool(active)
        self.value = value
        self.detail = detail


class AlertRule:
    """Base: a named condition over the sample history. ``for_s`` is the
    pending duration the condition must hold before firing (0 = fire on
    the first evaluation that matches)."""

    def __init__(self, name: str, *, severity: str = "warning",
                 for_s: float = 0.0):
        if not name:
            raise ValueError("alert rule needs a name")
        self.name = name
        self.severity = severity
        self.for_s = float(for_s)

    def evaluate(self, history: SampleHistory,
                 now: float) -> RuleResult:  # pragma: no cover - interface
        raise NotImplementedError

    def describe(self) -> Dict[str, Any]:
        return {"name": self.name, "type": type(self).__name__,
                "severity": self.severity, "for_s": self.for_s}


class ThresholdRule(AlertRule):
    """``sum(metric{labels}) <op> value``."""

    def __init__(self, name: str, metric: str, op: str, value: float, *,
                 labels: Optional[Dict[str, str]] = None, **kw):
        super().__init__(name, **kw)
        if op not in _OPS:
            raise ValueError(f"unknown op {op!r} (one of {sorted(_OPS)})")
        self.metric = metric
        self.op = op
        self.value = float(value)
        self.labels = dict(labels or {})

    def evaluate(self, history: SampleHistory, now: float) -> RuleResult:
        latest = history.latest()
        v = (series_sum(latest[1], self.metric, self.labels)
             if latest is not None else None)
        if v is None:
            return RuleResult(False, None,
                              f"{self.metric} absent (threshold not judged)")
        active = _OPS[self.op](v, self.value)
        return RuleResult(active, v,
                          f"{self.metric}={v:g} {self.op} {self.value:g}")


class AbsenceRule(AlertRule):
    """Fires when the metric exports NO matching series — a crashed
    exporter/listener is indistinguishable from "all quiet" otherwise."""

    def __init__(self, name: str, metric: str, *,
                 labels: Optional[Dict[str, str]] = None, **kw):
        super().__init__(name, **kw)
        self.metric = metric
        self.labels = dict(labels or {})

    def evaluate(self, history: SampleHistory, now: float) -> RuleResult:
        latest = history.latest()
        v = (series_sum(latest[1], self.metric, self.labels)
             if latest is not None else None)
        if v is None:
            return RuleResult(True, None, f"{self.metric} is absent")
        return RuleResult(False, v, f"{self.metric} present ({v:g})")


class RateOfChangeRule(AlertRule):
    """``rate(metric[window_s]) <op> value`` (per-second, counter resets
    clamped to 0). Inactive until the history spans the window."""

    def __init__(self, name: str, metric: str, op: str, value: float,
                 window_s: float, *,
                 labels: Optional[Dict[str, str]] = None, **kw):
        super().__init__(name, **kw)
        if op not in _OPS:
            raise ValueError(f"unknown op {op!r} (one of {sorted(_OPS)})")
        if window_s <= 0:
            raise ValueError("window_s must be positive")
        self.metric = metric
        self.op = op
        self.value = float(value)
        self.window_s = float(window_s)
        self.labels = dict(labels or {})

    def evaluate(self, history: SampleHistory, now: float) -> RuleResult:
        latest = history.latest()
        past = history.at_or_before(now - self.window_s)
        if latest is None or past is None or past[0] >= latest[0]:
            return RuleResult(False, None,
                              f"history does not span {self.window_s:g}s")
        v1 = series_sum(latest[1], self.metric, self.labels)
        if v1 is None:
            return RuleResult(False, None, f"{self.metric} absent")
        v0 = series_sum(past[1], self.metric, self.labels) or 0.0
        rate = max(0.0, v1 - v0) / (latest[0] - past[0])
        active = _OPS[self.op](rate, self.value)
        return RuleResult(
            active, rate,
            f"rate({self.metric}[{self.window_s:g}s])={rate:g} "
            f"{self.op} {self.value:g}")


class SLOSpec:
    """An availability SLO over a counter: ``objective`` (e.g. 0.99) of
    events matched by ``labels`` must NOT match ``error_labels``.
    Error budget = ``1 - objective``."""

    def __init__(self, metric: str, error_labels: Dict[str, str], *,
                 labels: Optional[Dict[str, str]] = None,
                 objective: float = 0.99):
        if not 0.0 < objective < 1.0:
            raise ValueError("objective must be in (0, 1)")
        if not error_labels:
            raise ValueError("slo needs error_labels selecting the errors")
        self.metric = metric
        self.labels = dict(labels or {})
        self.error_labels = {**self.labels, **dict(error_labels)}
        self.objective = float(objective)

    @property
    def budget(self) -> float:
        return 1.0 - self.objective

    def describe(self) -> Dict[str, Any]:
        return {"metric": self.metric, "labels": self.labels,
                "error_labels": self.error_labels,
                "objective": self.objective}


class BurnRateRule(AlertRule):
    """Multiwindow burn rate over an :class:`SLOSpec`.

    ``windows`` is a list of ``(long_s, short_s, factor)``: the rule is
    active when for ANY entry both the long- and short-window burn rates
    reach ``factor`` (e.g. the SRE Workbook's 1h/5m at 14.4x paging pair).
    When the history is shorter than a window, the available span is used
    (burn rate is an event RATIO, so a short span is just fewer events —
    the conservative start-up behaviour)."""

    def __init__(self, name: str, slo: SLOSpec,
                 windows: List[Tuple[float, float, float]], **base_kw):
        super().__init__(name, **base_kw)
        if not windows:
            raise ValueError("burn_rate rule needs at least one window")
        self.slo = slo
        self.windows = [(float(l), float(s), float(f))
                        for l, s, f in windows]
        for l, s, f in self.windows:
            if s > l:
                raise ValueError(f"short window {s:g}s exceeds long {l:g}s")
            if f <= 0:
                raise ValueError("burn-rate factor must be positive")

    def _burn(self, history: SampleHistory, now: float,
              window_s: float) -> Optional[float]:
        latest = history.latest()
        if latest is None:
            return None
        past = history.at_or_before(now - window_s) or history.oldest()
        d_total = ((series_sum(latest[1], self.slo.metric, self.slo.labels)
                    or 0.0)
                   - (series_sum(past[1], self.slo.metric, self.slo.labels)
                      or 0.0))
        d_err = ((series_sum(latest[1], self.slo.metric,
                             self.slo.error_labels) or 0.0)
                 - (series_sum(past[1], self.slo.metric,
                               self.slo.error_labels) or 0.0))
        if d_total <= 0:
            return 0.0
        ratio = max(0.0, d_err) / d_total
        return ratio / self.slo.budget

    def evaluate(self, history: SampleHistory, now: float) -> RuleResult:
        parts = []
        active = False
        worst: Optional[float] = None
        for long_s, short_s, factor in self.windows:
            b_long = self._burn(history, now, long_s)
            b_short = self._burn(history, now, short_s)
            if b_long is None or b_short is None:
                parts.append(f"{long_s:g}s/{short_s:g}s: no data")
                continue
            hit = b_long >= factor and b_short >= factor
            active = active or hit
            worst = max(worst or 0.0, min(b_long, b_short))
            parts.append(f"{long_s:g}s={b_long:.2f}x/"
                         f"{short_s:g}s={b_short:.2f}x (>= {factor:g}x)")
        return RuleResult(active, worst, "burn " + "; ".join(parts))

    def describe(self) -> Dict[str, Any]:
        d = super().describe()
        d["slo"] = self.slo.describe()
        d["windows"] = [list(w) for w in self.windows]
        return d


# ---------------------------------------------------------------------------
# notification sinks
# ---------------------------------------------------------------------------

class Notification:
    """One deduped state transition: ``state`` is ``firing`` or
    ``resolved``."""

    __slots__ = ("rule", "severity", "state", "value", "detail", "ts")

    def __init__(self, rule: str, severity: str, state: str,
                 value: Optional[float], detail: str, ts: float):
        self.rule = rule
        self.severity = severity
        self.state = state
        self.value = value
        self.detail = detail
        self.ts = ts

    def to_dict(self) -> Dict[str, Any]:
        return {"rule": self.rule, "severity": self.severity,
                "state": self.state, "value": self.value,
                "detail": self.detail, "ts": self.ts}

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"Notification({self.rule}, {self.state})"


class LogSink:
    """Routes notifications into the structured log stream (falling back
    to stdlib logging when no hub is active)."""

    def __init__(self):
        self._slog = _slog.get_logger("observe.alerts")

    def notify(self, n: Notification) -> None:
        if _slog.get_active_hub() is not None:
            self._slog.log(
                logging.ERROR if n.state == "firing" else logging.INFO,
                f"alert {n.rule} {n.state}", rule=n.rule, state=n.state,
                severity=n.severity, value=n.value, detail=n.detail)
        else:
            log.log(logging.ERROR if n.state == "firing" else logging.INFO,
                    "[alert:%s] %s (%s)", n.rule, n.state, n.detail)


class CallbackSink:
    """Hands each notification to a callable."""

    def __init__(self, fn: Callable[[Notification], None]):
        self.fn = fn

    def notify(self, n: Notification) -> None:
        self.fn(n)


class WebhookSink:
    """POSTs each notification as JSON with bounded retry + exponential
    backoff. ``post`` and ``sleep`` are injectable for tests; delivery
    failures are counted (``failed``) and never raise into the evaluator."""

    def __init__(self, url: str, *, retries: int = 3,
                 backoff_s: float = 0.5, timeout_s: float = 5.0,
                 post: Optional[Callable[[str, bytes], int]] = None,
                 sleep: Callable[[float], None] = time.sleep):
        self.url = url
        self.retries = max(0, int(retries))
        self.backoff_s = float(backoff_s)
        self.timeout_s = float(timeout_s)
        self._post = post if post is not None else self._http_post
        self._sleep = sleep
        self.delivered = 0
        self.failed = 0
        self.last_error: Optional[str] = None

    def _http_post(self, url: str, body: bytes) -> int:
        from urllib.request import Request, urlopen
        req = Request(url, data=body,
                      headers={"Content-Type": "application/json"})
        with urlopen(req, timeout=self.timeout_s) as resp:
            return resp.status

    def notify(self, n: Notification) -> None:
        body = json.dumps(n.to_dict()).encode()
        delay = self.backoff_s
        for attempt in range(self.retries + 1):
            try:
                status = self._post(self.url, body)
                if 200 <= status < 300:
                    self.delivered += 1
                    self.last_error = None
                    return
                self.last_error = f"HTTP {status}"
            except Exception as e:  # noqa: BLE001 - delivery must not raise
                self.last_error = f"{type(e).__name__}: {e}"
            if attempt < self.retries:
                self._sleep(delay)
                delay *= 2
        self.failed += 1
        log.warning("webhook %s dropped %s notification after %d attempts "
                    "(%s)", self.url, n.rule, self.retries + 1,
                    self.last_error)


# ---------------------------------------------------------------------------
# the manager
# ---------------------------------------------------------------------------

class _RuleState:
    __slots__ = ("state", "since", "fired_at", "value", "detail")

    def __init__(self):
        self.state = "ok"          # ok | pending | firing
        self.since: Optional[float] = None
        self.fired_at: Optional[float] = None
        self.value: Optional[float] = None
        self.detail = ""


class AlertManager:
    """Evaluates rules against a registry's exposition; routes deduped
    firing/resolved notifications to sinks.

    ``time_source`` (``parallel.time_source.TimeSource``) stamps every
    sample and transition — inject a ``ManualTimeSource`` and drive
    :meth:`evaluate_once` for deterministic tests; :meth:`start` runs a
    background daemon evaluating every ``interval_s`` wall seconds.

    The manager exports its own state through the SAME registry it
    samples: ``alerts_firing{rule}`` and
    ``alert_notifications_total{rule,state}``.
    """

    def __init__(self, metrics: MetricsRegistry, rules: List[AlertRule],
                 sinks: Optional[List[Any]] = None, *,
                 interval_s: float = 15.0,
                 time_source: Optional[TimeSource] = None,
                 history_max_age_s: float = 2 * 3600.0):
        names = [r.name for r in rules]
        dupes = {n for n in names if names.count(n) > 1}
        if dupes:
            raise ValueError(f"duplicate rule names {sorted(dupes)}")
        self.metrics = metrics
        self.rules = list(rules)
        self.sinks = list(sinks) if sinks is not None else [LogSink()]
        self.interval_s = float(interval_s)
        self.time_source = (time_source if time_source is not None
                            else get_time_source())
        self.history = SampleHistory(max_age_s=history_max_age_s)
        self._states: Dict[str, _RuleState] = {r.name: _RuleState()
                                               for r in self.rules}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._m_firing = metrics.gauge(
            "alerts_firing", "1 while the rule is firing", ("rule",))
        self._m_notifications = metrics.counter(
            "alert_notifications_total",
            "Alert state transitions notified to sinks", ("rule", "state"))
        self.evaluations = 0

    # ------------------------------------------------------------ evaluate
    def _now(self) -> float:
        return self.time_source.current_time_millis() / 1e3

    def _notify(self, n: Notification) -> None:
        self._m_notifications.inc(rule=n.rule, state=n.state)
        for sink in self.sinks:
            try:
                sink.notify(n)
            except Exception as e:  # noqa: BLE001 - sinks are contained
                log.warning("alert sink %r failed for %s: %s",
                            type(sink).__name__, n.rule, e)

    def evaluate_once(self, now: Optional[float] = None
                      ) -> List[Notification]:
        """One evaluation round: scrape, append to history, run every
        rule's state machine. Returns the notifications emitted this round
        (each transition exactly once)."""
        with self._lock:
            if now is None:
                now = self._now()
            sample = parse_prometheus_text(self.metrics.exposition())
            self.history.add(now, sample)
            self.evaluations += 1
            out: List[Notification] = []
            for rule in self.rules:
                st = self._states[rule.name]
                try:
                    res = rule.evaluate(self.history, now)
                except Exception as e:  # noqa: BLE001 - bad rule contained
                    log.warning("alert rule %s failed to evaluate: %s",
                                rule.name, e)
                    # state is kept (a broken rule must not flap
                    # firing→resolved) but the error is surfaced in
                    # /alerts instead of pinning the old detail silently
                    st.detail = f"evaluation error: {type(e).__name__}: {e}"
                    continue
                st.value, st.detail = res.value, res.detail
                if res.active:
                    if st.state == "ok":
                        st.since = now
                        st.state = ("pending" if rule.for_s > 0
                                    else "firing")
                    elif (st.state == "pending"
                          and now - st.since >= rule.for_s):
                        st.state = "firing"
                    if st.state == "firing" and st.fired_at is None:
                        st.fired_at = now
                        self._m_firing.set(1, rule=rule.name)
                        out.append(Notification(rule.name, rule.severity,
                                                "firing", res.value,
                                                res.detail, now))
                else:
                    if st.state == "firing":
                        self._m_firing.set(0, rule=rule.name)
                        out.append(Notification(rule.name, rule.severity,
                                                "resolved", res.value,
                                                res.detail, now))
                    st.state, st.since, st.fired_at = "ok", None, None
        # sinks run OUTSIDE the manager lock: a slow webhook (seconds of
        # retry/backoff) must not block /alerts or firing(), and a callback
        # sink that queries the manager must not deadlock. Transitions were
        # already recorded above, so delivery stays exactly-once.
        for n in out:
            self._notify(n)
        return out

    # ------------------------------------------------------------- queries
    def firing(self) -> List[str]:
        with self._lock:
            return sorted(n for n, s in self._states.items()
                          if s.state == "firing")

    def describe(self) -> Dict[str, Any]:
        """The ``/alerts`` endpoint payload."""
        with self._lock:
            rules = []
            for rule in self.rules:
                st = self._states[rule.name]
                d = rule.describe()
                d.update(state=st.state, since=st.since,
                         fired_at=st.fired_at, value=st.value,
                         detail=st.detail)
                rules.append(d)
            return {"firing": sorted(n for n, s in self._states.items()
                                     if s.state == "firing"),
                    "evaluations": self.evaluations,
                    "rules": rules}

    # ----------------------------------------------------------- lifecycle
    def start(self) -> "AlertManager":
        """Run the background evaluator (daemon; ``stop()`` is prompt)."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()

        def _loop():
            while not self._stop.is_set():
                try:
                    self.evaluate_once()
                except Exception:  # noqa: BLE001 - the loop must survive
                    log.exception("alert evaluation round failed")
                self._stop.wait(self.interval_s)

        self._thread = threading.Thread(target=_loop, daemon=True,
                                        name="alert-manager")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


# ---------------------------------------------------------------------------
# JSON rule loading — the --alerts rules.json / validator schema
# ---------------------------------------------------------------------------

def _build_threshold(c: dict) -> AlertRule:
    return ThresholdRule(c["name"], c["metric"], c["op"], c["value"],
                         labels=c.get("labels"),
                         severity=c.get("severity", "warning"),
                         for_s=c.get("for_s", 0.0))


def _build_absence(c: dict) -> AlertRule:
    return AbsenceRule(c["name"], c["metric"], labels=c.get("labels"),
                       severity=c.get("severity", "warning"),
                       for_s=c.get("for_s", 0.0))


def _build_rate(c: dict) -> AlertRule:
    return RateOfChangeRule(c["name"], c["metric"], c["op"], c["value"],
                            c["window_s"], labels=c.get("labels"),
                            severity=c.get("severity", "warning"),
                            for_s=c.get("for_s", 0.0))


def _build_burn(c: dict) -> AlertRule:
    slo_c = c["slo"]
    slo = SLOSpec(slo_c["metric"], slo_c["error_labels"],
                  labels=slo_c.get("labels"),
                  objective=slo_c.get("objective", 0.99))
    windows = [(w["long_s"], w["short_s"], w["factor"])
               for w in c["windows"]]
    return BurnRateRule(c["name"], slo, windows,
                        severity=c.get("severity", "warning"),
                        for_s=c.get("for_s", 0.0))


RULE_BUILDERS: Dict[str, Callable[[dict], AlertRule]] = {
    "threshold": _build_threshold,
    "absence": _build_absence,
    "rate_of_change": _build_rate,
    "burn_rate": _build_burn,
}


def load_rules(spec) -> List[AlertRule]:
    """Build rules from a spec: a path to a JSON file, a JSON string, or
    an already-parsed ``{"rules": [...]}`` dict. Raises ``ValueError``
    with the offending rule index/name on any schema problem."""
    if isinstance(spec, (str, bytes)) and not str(spec).lstrip().startswith(
            ("{", "[")):
        with open(spec, "r", encoding="utf-8") as fh:
            spec = json.load(fh)
    elif isinstance(spec, (str, bytes)):
        spec = json.loads(spec)
    if isinstance(spec, list):
        spec = {"rules": spec}
    if not isinstance(spec, dict) or not isinstance(spec.get("rules"), list):
        raise ValueError("alert rules spec must be {'rules': [...]}")
    rules: List[AlertRule] = []
    for i, c in enumerate(spec["rules"]):
        if not isinstance(c, dict):
            raise ValueError(f"rules[{i}]: not an object")
        rtype = c.get("type")
        builder = RULE_BUILDERS.get(rtype)
        if builder is None:
            raise ValueError(
                f"rules[{i}] ({c.get('name', '?')}): unknown type {rtype!r} "
                f"(one of {sorted(RULE_BUILDERS)})")
        try:
            rules.append(builder(c))
        except KeyError as e:
            raise ValueError(
                f"rules[{i}] ({c.get('name', '?')}): missing field {e}"
            ) from e
        except (TypeError, ValueError) as e:
            raise ValueError(
                f"rules[{i}] ({c.get('name', '?')}): {e}") from e
    names = [r.name for r in rules]
    dupes = {n for n in names if names.count(n) > 1}
    if dupes:
        raise ValueError(f"duplicate rule names {sorted(dupes)}")
    return rules
