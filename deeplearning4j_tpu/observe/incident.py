"""Incident flight recorder: one bounded, auditable bundle per recovery.

When the elastic supervisor decides restart/shrink/fail (including
partition resolutions) the operator's first question is *why* — and
today the evidence is scattered across per-incarnation log files, four
disjoint metric registries and in-memory trace rings that die with the
processes.  :class:`IncidentRecorder` assembles everything into one
``incident_<generation>_<seq>/`` directory at decision time:

- ``incident.json`` — the schema'd manifest (``SCHEMA_VERSION``):
  victim, decision ladder with per-rung reasons, world before/after,
  per-worker last committed step, checkpoint restore point, fault-plan
  echo, bounds;
- ``metrics.prom`` — the final fleet metrics snapshot (the
  ``FleetRegistry`` union at the moment of the decision);
- ``spans/<source>.jsonl`` — the last-N spans of every worker span
  stream plus the supervisor's own ring, in ``SpanFileWriter`` format —
  the bundle stays ``merge_chrome_traces``-loadable;
- ``logs.jsonl`` — the last-N structured log lines from the
  supervisor's active :class:`~deeplearning4j_tpu.observe.log.LogRing`;
- ``logs/slot<N>.log`` — the byte-capped tail of each victim's captured
  output.

Every list is bounded (``max_spans`` per source, ``max_log_lines``,
``max_log_bytes``) — a flight recorder that can fill the checkpoint
volume is itself an incident.  ``tools/validate_incident.py`` lints a
bundle against this schema + these bounds, and the CI chaos tests run
it over the bundles their injected kills produce.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

SCHEMA_VERSION = 1
KIND = "elastic_incident"
DECISIONS = ("restart", "shrink", "fail")

DEFAULT_MAX_SPANS = 200        # per span source
DEFAULT_MAX_LOG_LINES = 256    # supervisor structured-log tail
DEFAULT_MAX_LOG_BYTES = 16384  # per victim stdout/stderr tail
_PLAN_CAP = 16384              # fault-plan file echo


def bundle_name(generation: int, seq: int) -> str:
    return f"incident_{int(generation):03d}_{int(seq):03d}"


class IncidentRecorder:
    """Writes incident bundles under ``directory``.  Hold ``None``
    instead of an instance to disable — every call site is a single
    ``is None`` check, the ``enable_tracing()`` pattern."""

    def __init__(self, directory: str, *,
                 max_spans: int = DEFAULT_MAX_SPANS,
                 max_log_lines: int = DEFAULT_MAX_LOG_LINES,
                 max_log_bytes: int = DEFAULT_MAX_LOG_BYTES):
        self.directory = str(directory)
        self.max_spans = int(max_spans)
        self.max_log_lines = int(max_log_lines)
        self.max_log_bytes = int(max_log_bytes)
        # seed the sequence past every bundle already on disk: a re-run
        # supervisor restarts generation numbering at 1, and a collision
        # would silently mix a previous run's evidence (its spans/ and
        # logs/ files) into the new incident's bundle
        self._seq = self._existing_max_seq()
        self.bundles: List[str] = []

    def _existing_max_seq(self) -> int:
        try:
            names = os.listdir(self.directory)
        except OSError:
            return 0
        seqs = [0]
        for name in names:
            parts = name.split("_")
            if len(parts) == 3 and parts[0] == "incident":
                try:
                    seqs.append(int(parts[2]))
                except ValueError:
                    continue
        return max(seqs)

    # ------------------------------------------------------------- helpers
    def _tail_span_file(self, src_path: str, dst_path: str) -> int:
        """Copy one ``SpanFileWriter`` stream keeping its meta line and
        the LAST ``max_spans`` complete span lines; returns the span
        count (0 = nothing readable)."""
        meta_line = None
        spans: List[str] = []
        try:
            with open(src_path, encoding="utf-8") as fh:
                for line in fh:
                    if not line.endswith("\n"):
                        continue  # torn tail (writer SIGKILLed mid-write)
                    if meta_line is None and '"meta"' in line:
                        meta_line = line
                        continue
                    spans.append(line)
                    if len(spans) > self.max_spans:
                        spans.pop(0)
        except OSError:
            return 0
        if meta_line is None and not spans:
            return 0
        with open(dst_path, "w", encoding="utf-8") as fh:
            if meta_line is not None:
                fh.write(meta_line)
            fh.writelines(spans)
        return len(spans)

    def _write_live_spans(self, dst_path: str, label: str, spans,
                          extra_meta: Optional[Dict[str, Any]]) -> int:
        """Serialize a live recorder's last-N spans in SpanFileWriter
        format (meta line + one line per span)."""
        from deeplearning4j_tpu.observe.fleet import SpanFileWriter
        done = [s for s in spans if s.end_ns is not None][-self.max_spans:]
        writer = SpanFileWriter(dst_path, label=label,
                                extra_meta=extra_meta)
        try:
            for s in done:
                writer.add(s)
        finally:
            writer.close()
        return len(done)

    # -------------------------------------------------------------- record
    def record(self, *, job_id: str, generation: int, ts_ms: int,
               decision: str, reason: str, backoff_s: float,
               ladder: Sequence[Dict[str, Any]],
               victim: Dict[str, Any], dead_slots: Sequence[int],
               world_before: Sequence[int], world_after: Sequence[int],
               workers: Sequence[Dict[str, Any]],
               checkpoint: Dict[str, Any],
               fault_plan_env: Optional[str] = None,
               metrics_text: Optional[str] = None,
               span_files: Sequence[str] = (),
               live_spans: Optional[Tuple[str, list]] = None,
               log_tails: Optional[Dict[int, str]] = None) -> str:
        """Assemble one bundle; returns its directory path.  Must never
        fail recovery: callers wrap it (a broken flight recorder is an
        error log line, not a second incident)."""
        self._seq += 1
        bundle = os.path.join(self.directory,
                              bundle_name(generation, self._seq))
        os.makedirs(bundle, exist_ok=True)
        files: Dict[str, Optional[str]] = {
            "metrics": None, "spans_dir": None, "logs": None,
            "log_tail_dir": None}

        if metrics_text is not None:
            with open(os.path.join(bundle, "metrics.prom"), "w",
                      encoding="utf-8") as fh:
                fh.write(metrics_text)
            files["metrics"] = "metrics.prom"

        span_dir = os.path.join(bundle, "spans")
        wrote_spans = False
        for src in span_files:
            os.makedirs(span_dir, exist_ok=True)
            dst = os.path.join(span_dir, os.path.basename(src))
            if self._tail_span_file(src, dst) or os.path.exists(dst):
                wrote_spans = True
        if live_spans is not None:
            label, spans = live_spans
            if spans:
                os.makedirs(span_dir, exist_ok=True)
                self._write_live_spans(
                    os.path.join(span_dir, "supervisor.jsonl"),
                    label, spans, {"role": "supervisor"})
                wrote_spans = True
        if wrote_spans:
            files["spans_dir"] = "spans"

        from deeplearning4j_tpu.observe.log import get_active_hub
        hub = get_active_hub()
        if hub is not None:
            records = hub.ring.records()[-self.max_log_lines:]
            if records:
                with open(os.path.join(bundle, "logs.jsonl"), "w",
                          encoding="utf-8") as fh:
                    for rec in records:
                        fh.write(rec.to_json() + "\n")
                files["logs"] = "logs.jsonl"

        if log_tails:
            tail_dir = os.path.join(bundle, "logs")
            os.makedirs(tail_dir, exist_ok=True)
            for slot, text in sorted(log_tails.items()):
                data = (text or "").encode(errors="replace")
                data = data[-self.max_log_bytes:]
                with open(os.path.join(tail_dir, f"slot{int(slot)}.log"),
                          "wb") as fh:
                    fh.write(data)
            files["log_tail_dir"] = "logs"

        plan: Optional[Dict[str, Any]] = None
        if fault_plan_env:
            plan = {"env": fault_plan_env, "content": None}
            if os.path.exists(fault_plan_env):
                try:
                    with open(fault_plan_env, encoding="utf-8") as fh:
                        plan["content"] = fh.read(_PLAN_CAP)
                except OSError:
                    pass

        manifest = {
            "schema": SCHEMA_VERSION, "kind": KIND,
            "job_id": str(job_id), "generation": int(generation),
            "seq": self._seq, "ts_ms": int(ts_ms),
            "decision": {"action": str(decision), "reason": str(reason),
                         "backoff_s": float(backoff_s),
                         "ladder": [dict(r) for r in ladder]},
            "victim": dict(victim),
            "dead_slots": [int(s) for s in dead_slots],
            "world": {"before": [int(s) for s in world_before],
                      "after": [int(s) for s in world_after]},
            "workers": [dict(w) for w in workers],
            "checkpoint": dict(checkpoint),
            "fault_plan": plan,
            "bounds": {"max_spans": self.max_spans,
                       "max_log_lines": self.max_log_lines,
                       "max_log_bytes": self.max_log_bytes},
            "files": files,
        }
        # the manifest lands LAST: its presence certifies a complete bundle
        with open(os.path.join(bundle, "incident.json"), "w",
                  encoding="utf-8") as fh:
            json.dump(manifest, fh, indent=1, sort_keys=True)
        self.bundles.append(bundle)
        return bundle


# ---------------------------------------------------------------------------
# on-demand capture — the /debug/capture?seconds=N mini bundle
# ---------------------------------------------------------------------------

CAPTURE_KIND = "debug_capture"
DEFAULT_MAX_COST_ENTRIES = 50  # cost-ledger slice bound


def capture_bundle(*, seconds: float, tracer=None, metrics=None,
                   cost=None, sampler=None,
                   max_spans: int = DEFAULT_MAX_SPANS,
                   max_cost_entries: int = DEFAULT_MAX_COST_ENTRIES
                   ) -> Dict[str, Any]:
    """Assemble the on-demand mini incident bundle: the last
    ``seconds`` of completed spans as a Chrome trace (straight from the
    active recorder's ring — tail-sampling never thins it), the metrics
    exposition snapshot, a bounded cost-ledger slice, and the tail
    sampler's accounting.  The full flight recorder answers "why did
    recovery act"; this answers "what is this process doing RIGHT NOW"
    without restarting anything.  Same bounds discipline
    (``max_spans``, ``max_cost_entries``), echoed in the payload so a
    truncated capture can never masquerade as a complete one."""
    from deeplearning4j_tpu.observe.export import to_chrome_trace
    from deeplearning4j_tpu.observe.trace import get_active_tracer
    if tracer is None:
        tracer = get_active_tracer()
    seconds = max(float(seconds), 0.0)
    max_spans = int(max_spans)

    spans: List[Any] = []
    total_done = 0
    if tracer is not None:
        import time as _time
        cutoff_ns = _time.perf_counter_ns() - int(seconds * 1e9)
        done = [s for s in tracer.recorder.spans()
                if s.end_ns is not None]
        windowed = [s for s in done if s.end_ns >= cutoff_ns]
        total_done = len(windowed)
        spans = windowed[-max_spans:]

    bundle: Dict[str, Any] = {
        "schema": SCHEMA_VERSION, "kind": CAPTURE_KIND,
        "seconds": seconds,
        "bounds": {"max_spans": max_spans,
                   "max_cost_entries": int(max_cost_entries),
                   "span_count": len(spans),
                   "spans_truncated": total_done > len(spans)},
        "trace": to_chrome_trace(
            spans, service=getattr(tracer, "service",
                                   "deeplearning4j_tpu")),
        "metrics": metrics.exposition() if metrics is not None else None,
        "cost": None,
        "sampler": None,
    }
    if cost is not None:
        bundle["cost"] = {"recent": cost.recent(int(max_cost_entries)),
                          "totals": cost.describe()}
    if sampler is not None and hasattr(sampler, "describe"):
        bundle["sampler"] = sampler.describe()
    return bundle
