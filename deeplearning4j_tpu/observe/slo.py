"""Declarative SLOs over the Prometheus exposition contract.

``alerts.py`` gives the mechanism (burn-rate rules, the state machine);
this module gives the POLICY object: an :class:`SLO` names an objective
("99% of bench predictions under 250 ms", "99.9% of requests succeed"),
reads ANY registry through ``parse_prometheus_text`` — the same contract
the alert engine and fleet federation use — and derives everything else:

- **compliance**: the good/total event ratio right now (for the ``/slo``
  endpoint);
- **burn rates**: multiwindow error-budget burn (SRE Workbook ch. 5),
  long window for significance, short for fast detection AND resolution;
- **alert rules**: each SLO auto-generates exactly one burn-rate rule
  for the existing :class:`~.alerts.AlertManager` — availability SLOs
  reuse :class:`~.alerts.BurnRateRule` verbatim via an
  :class:`~.alerts.SLOSpec`; latency SLOs use
  :class:`LatencyBurnRateRule`, which counts "good" events from the
  histogram's cumulative buckets (the count at the largest ``le`` not
  above the threshold) so no separate error counter is needed.

The latency SLI deliberately judges against BUCKET BOUNDS, not exact
latencies: a threshold below the lowest bucket makes every request a
violation (good = 0), which is exactly the deterministic knob the chaos
example and bench use to drive a burn without wall-clock sleeps.

SLOs load from JSON (``load_slos``) so ``serve --slo slo.json`` and
``tools/validate_slo_config.py`` share one schema::

    {"slos": [{"name": "bench-latency", "sli": "latency",
               "metric": "serving_request_latency_seconds",
               "threshold_ms": 250, "objective": 0.99,
               "labels": {"model": "bench"},
               "windows": [{"long_s": 3600, "short_s": 300,
                            "factor": 14.4}]},
              {"name": "bench-availability", "sli": "availability",
               "metric": "serving_requests_total",
               "error_labels": {"status": "error"},
               "objective": 0.999}]}
"""

from __future__ import annotations

import json
import math
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.observe.alerts import (AlertRule, BurnRateRule,
                                               SampleHistory, SLOSpec,
                                               series_sum)
from deeplearning4j_tpu.observe.metrics import parse_prometheus_text

# SRE Workbook ch. 5 defaults: the paging pair (1h/5m at 14.4x) plus the
# ticket pair (6h/30m at 6x)
DEFAULT_WINDOWS: Tuple[Tuple[float, float, float], ...] = (
    (3600.0, 300.0, 14.4), (21600.0, 1800.0, 6.0))

# threshold_ms is converted into the histogram's native unit
_UNIT_DIVISOR = {"s": 1e3, "ms": 1.0}


def latency_counts(sample, metric: str, threshold_s: float,
                   labels: Optional[Dict[str, str]] = None
                   ) -> Optional[Tuple[float, float]]:
    """``(good, total)`` from a histogram's cumulative buckets.

    ``good`` is the count at the largest ``le`` not above the threshold
    (0 when no bucket qualifies — a sub-bucket threshold makes every
    event a violation, deliberately); ``total`` the ``+Inf`` count.
    Series are label-subset matched and summed; ``None`` when the metric
    has no bucket series at all (absence is distinct from zero)."""
    want = set((str(k), str(v)) for k, v in (labels or {}).items())
    groups: Dict[Tuple[Tuple[str, str], ...], Dict[float, float]] = {}
    for key, v in sample.get(metric + "_bucket", {}).items():
        kd = [(k, val) for k, val in key if k != "le"]
        le = dict(key).get("le")
        if le is None or not want <= set(kd):
            continue
        try:
            le_v = float(le)          # float("+Inf") == math.inf
        except ValueError:
            continue
        groups.setdefault(tuple(kd), {})[le_v] = v
    if not groups:
        return None
    good = total = 0.0
    for series in groups.values():
        bounds = sorted(series)
        total += series.get(math.inf, series[bounds[-1]])
        eligible = [b for b in bounds
                    if b <= threshold_s * (1 + 1e-9) + 1e-12]
        if eligible:
            good += series[eligible[-1]]
    return good, total


class SLO:
    """One declarative objective; ``sli`` is ``latency`` (histogram
    threshold) or ``availability`` (error-labelled counter)."""

    def __init__(self, name: str, *, sli: str, metric: str,
                 objective: float = 0.99,
                 threshold_ms: Optional[float] = None,
                 unit: str = "s",
                 labels: Optional[Dict[str, str]] = None,
                 error_labels: Optional[Dict[str, str]] = None,
                 windows: Optional[Sequence[Sequence[float]]] = None,
                 severity: str = "warning", for_s: float = 0.0):
        if not name:
            raise ValueError("slo needs a name")
        if sli not in ("latency", "availability"):
            raise ValueError(
                f"unknown sli {sli!r} (one of ['availability', 'latency'])")
        if not 0.0 < float(objective) < 1.0:
            raise ValueError("objective must be in (0, 1)")
        if unit not in _UNIT_DIVISOR:
            raise ValueError(f"unknown unit {unit!r} (one of ['ms', 's'])")
        self.name = name
        self.sli = sli
        self.metric = metric
        self.objective = float(objective)
        self.unit = unit
        self.labels = dict(labels or {})
        self.error_labels = dict(error_labels or {})
        self.severity = severity
        self.for_s = float(for_s)
        self.windows = [tuple(float(x) for x in w)
                        for w in (windows if windows else DEFAULT_WINDOWS)]
        if sli == "latency":
            if threshold_ms is None:
                raise ValueError("latency slo needs threshold_ms")
            self.threshold_ms = float(threshold_ms)
            if self.threshold_ms <= 0:
                raise ValueError("threshold_ms must be positive")
        else:
            if not self.error_labels:
                raise ValueError("availability slo needs error_labels")
            self.threshold_ms = None
        # construction validates windows/objective eagerly (load-time
        # schema errors, not evaluation-time surprises)
        self._rule = self._build_rule()

    @property
    def budget(self) -> float:
        return 1.0 - self.objective

    @property
    def rule_name(self) -> str:
        return f"slo_burn:{self.name}"

    # ---------------------------------------------------------------- SLI
    def good_total(self, sample) -> Optional[Tuple[float, float]]:
        """``(good, total)`` event counts from one parsed sample, or
        ``None`` when the underlying metric is absent."""
        if self.sli == "latency":
            thr = self.threshold_ms / _UNIT_DIVISOR[self.unit]
            return latency_counts(sample, self.metric, thr, self.labels)
        total = series_sum(sample, self.metric, self.labels)
        if total is None:
            return None
        err = series_sum(sample, self.metric,
                         {**self.labels, **self.error_labels}) or 0.0
        return max(total - err, 0.0), total

    def compliance(self, sample) -> Dict[str, Any]:
        """The instantaneous good/total ratio (lifetime-to-date, the
        ``/slo`` headline number)."""
        gt = self.good_total(sample)
        if gt is None:
            return {"good": None, "total": None, "ratio": None,
                    "met": None, "detail": f"{self.metric} absent"}
        good, total = gt
        ratio = (good / total) if total > 0 else None
        met = None if ratio is None else ratio >= self.objective
        return {"good": good, "total": total, "ratio": ratio, "met": met}

    # -------------------------------------------------------------- rules
    def _build_rule(self) -> AlertRule:
        if self.sli == "availability":
            spec = SLOSpec(self.metric, self.error_labels,
                           labels=self.labels, objective=self.objective)
            return BurnRateRule(self.rule_name, spec, list(self.windows),
                                severity=self.severity, for_s=self.for_s)
        return LatencyBurnRateRule(self.rule_name, self, list(self.windows),
                                   severity=self.severity, for_s=self.for_s)

    def rule(self) -> AlertRule:
        """The auto-generated burn-rate rule for ``AlertManager``."""
        return self._rule

    def describe(self) -> Dict[str, Any]:
        d = {"name": self.name, "sli": self.sli, "metric": self.metric,
             "objective": self.objective, "labels": self.labels,
             "windows": [list(w) for w in self.windows],
             "rule": self.rule_name}
        if self.sli == "latency":
            d["threshold_ms"] = self.threshold_ms
            d["unit"] = self.unit
        else:
            d["error_labels"] = self.error_labels
        return d


class LatencyBurnRateRule(BurnRateRule):
    """Burn rate where "error" means "served above the threshold":
    good/total deltas come from the histogram's cumulative buckets, so a
    latency SLO needs no separate error counter.  Reuses the base class's
    multiwindow ``evaluate`` and the manager's state machine verbatim —
    only the per-window burn computation differs."""

    def _burn(self, history: SampleHistory, now: float,
              window_s: float) -> Optional[float]:
        latest = history.latest()
        if latest is None:
            return None
        past = history.at_or_before(now - window_s) or history.oldest()
        gt1 = self.slo.good_total(latest[1]) or (0.0, 0.0)
        gt0 = self.slo.good_total(past[1]) or (0.0, 0.0)
        d_total = gt1[1] - gt0[1]
        d_good = gt1[0] - gt0[0]
        if d_total <= 0:
            return 0.0
        ratio = min(max(1.0 - max(d_good, 0.0) / d_total, 0.0), 1.0)
        return ratio / self.slo.budget


class SLOSet:
    """The loaded config: iterable SLOs + their generated rules + the
    ``/slo`` endpoint payload."""

    def __init__(self, slos: Sequence[SLO]):
        names = [s.name for s in slos]
        dupes = {n for n in names if names.count(n) > 1}
        if dupes:
            raise ValueError(f"duplicate slo names {sorted(dupes)}")
        self.slos = list(slos)

    def __len__(self) -> int:
        return len(self.slos)

    def __iter__(self):
        return iter(self.slos)

    def rules(self) -> List[AlertRule]:
        """One burn-rate rule per SLO, for ``AlertManager(rules=...)``."""
        return [s.rule() for s in self.slos]

    def status(self, *, metrics=None, alerts=None, sample=None,
               now: Optional[float] = None) -> Dict[str, Any]:
        """The ``/slo`` payload: per-SLO compliance, per-window burn
        rates, and (when an ``alerts`` manager is attached) the generated
        rule's live state.  Burn rates read the manager's sample history
        when available; otherwise a single fresh scrape (burn 0 — one
        sample has no deltas)."""
        history: Optional[SampleHistory] = None
        if alerts is not None:
            if now is None:
                now = alerts.time_source.current_time_millis() / 1e3
            history = alerts.history
            if sample is None and len(history):
                sample = history.latest()[1]
        if sample is None and metrics is not None:
            sample = parse_prometheus_text(metrics.exposition())
        if now is None:
            now = time.time()
        if history is None or not len(history):
            history = SampleHistory()
            if sample is not None:
                history.add(now, sample)
        alert_states: Dict[str, dict] = {}
        if alerts is not None:
            alert_states = {d["name"]: d
                            for d in alerts.describe()["rules"]}
        out: Dict[str, Any] = {"now": now, "slos": []}
        for slo in self.slos:
            rule = slo.rule()
            entry = slo.describe()
            entry["compliance"] = (slo.compliance(sample)
                                   if sample is not None else None)
            burns = []
            for long_s, short_s, factor in rule.windows:
                b_long = rule._burn(history, now, long_s)
                b_short = rule._burn(history, now, short_s)
                burns.append({
                    "long_s": long_s, "short_s": short_s, "factor": factor,
                    "long": b_long, "short": b_short,
                    "active": (b_long is not None and b_short is not None
                               and b_long >= factor and b_short >= factor)})
            entry["burn"] = burns
            st = alert_states.get(rule.name)
            entry["alert"] = (
                {"rule": rule.name, "state": st["state"],
                 "detail": st["detail"]} if st is not None
                else {"rule": rule.name, "state": "unmanaged"})
            out["slos"].append(entry)
        return out

    def describe(self) -> List[Dict[str, Any]]:
        return [s.describe() for s in self.slos]


def load_slos(spec) -> SLOSet:
    """Build an :class:`SLOSet` from a spec: a path to a JSON file, a
    JSON string, or an already-parsed ``{"slos": [...]}`` dict.  Raises
    ``ValueError`` naming the offending entry on any schema problem (the
    ``load_rules`` convention, shared with the validator)."""
    if isinstance(spec, (str, bytes)) and not str(spec).lstrip().startswith(
            ("{", "[")):
        with open(spec, "r", encoding="utf-8") as fh:
            spec = json.load(fh)
    elif isinstance(spec, (str, bytes)):
        spec = json.loads(spec)
    if isinstance(spec, list):
        spec = {"slos": spec}
    if not isinstance(spec, dict) or not isinstance(spec.get("slos"), list):
        raise ValueError("slo spec must be {'slos': [...]}")
    slos: List[SLO] = []
    for i, c in enumerate(spec["slos"]):
        if not isinstance(c, dict):
            raise ValueError(f"slos[{i}]: not an object")
        windows = None
        if "windows" in c:
            if not isinstance(c["windows"], list) or not c["windows"]:
                raise ValueError(
                    f"slos[{i}] ({c.get('name', '?')}): windows must be a "
                    f"non-empty list")
            try:
                windows = [(w["long_s"], w["short_s"], w["factor"])
                           for w in c["windows"]]
            except (KeyError, TypeError) as e:
                raise ValueError(
                    f"slos[{i}] ({c.get('name', '?')}): window entries "
                    f"need long_s/short_s/factor ({e})") from e
        try:
            slos.append(SLO(
                c["name"], sli=c["sli"], metric=c["metric"],
                objective=c.get("objective", 0.99),
                threshold_ms=c.get("threshold_ms"),
                unit=c.get("unit", "s"),
                labels=c.get("labels"), error_labels=c.get("error_labels"),
                windows=windows, severity=c.get("severity", "warning"),
                for_s=c.get("for_s", 0.0)))
        except KeyError as e:
            raise ValueError(
                f"slos[{i}] ({c.get('name', '?')}): missing field {e}"
            ) from e
        except (TypeError, ValueError) as e:
            raise ValueError(
                f"slos[{i}] ({c.get('name', '?')}): {e}") from e
    return SLOSet(slos)
