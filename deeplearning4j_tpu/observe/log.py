"""Structured logging with trace correlation — the third observability pillar.

Spans (``observe/trace.py``) and metrics (``observe/metrics.py``) answer
"where did the time go" and "how much"; this module answers "what
happened", in a form machines can join back to the other two: every
record is one JSON line carrying ``trace_id``/``span_id`` pulled from the
ACTIVE span automatically (the Dapper correlation contract — a log line
emitted inside a traced request is findable from that request's trace id,
across threads and the HTTP boundary, with no caller plumbing).

Pieces, mirroring the tracing layer's shape:

- :class:`LogRecord` — one structured event (timestamp, level, logger,
  message, trace/span ids, free-form fields) with a strict-JSON line form;
- :class:`LogRing` — bounded in-memory ring with drop accounting (the
  ``TraceRecorder`` pattern: a long-running server logs forever, exports
  the recent window on demand, and the drop count is honest);
- :class:`LogHub` — the process-wide sink: ring + optional JSON-lines
  stream. ``enable_structured_logging()`` installs one, exactly like
  ``enable_tracing()``; every emit site is a single ``is None`` check
  no-op until then;
- :class:`StdlibBridgeHandler` — a ``logging.Handler`` routed into the
  hub, so every existing ``logging.*`` call in the codebase gains trace
  correlation for free (installed on the root logger by
  ``enable_structured_logging(bridge_stdlib=True)``);
- :class:`every_n` / :class:`at_most_every` — rate-limit gates for
  hot-path logs (per-iteration watchdog findings, dispatcher retries),
  the latter with an injectable clock so tests never sleep.
"""

from __future__ import annotations

import json
import logging
import math
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, TextIO

from deeplearning4j_tpu.observe import trace as _trace

#: level names ↔ stdlib numeric levels (shared so the bridge is lossless)
LEVELS = {"debug": logging.DEBUG, "info": logging.INFO,
          "warning": logging.WARNING, "error": logging.ERROR,
          "critical": logging.CRITICAL}
_LEVEL_NAMES = {v: k for k, v in LEVELS.items()}


def _level_no(level) -> int:
    if isinstance(level, str):
        return LEVELS[level.lower()]
    return int(level)


def _level_name(levelno: int) -> str:
    name = _LEVEL_NAMES.get(levelno)
    if name is not None:
        return name
    # nearest named level at or below (stdlib allows arbitrary ints)
    below = [v for v in _LEVEL_NAMES if v <= levelno]
    return _LEVEL_NAMES[max(below)] if below else "debug"


def _jsonable(v: Any) -> Any:
    """Map any value to a strict-JSON-safe equivalent. Non-finite floats
    become their repr strings (``chrome://tracing``-style strictness: a
    NaN loss must survive ``json.loads`` downstream); unknown objects
    degrade to ``repr`` instead of failing the log site."""
    if isinstance(v, bool) or v is None or isinstance(v, (str, int)):
        return v
    if isinstance(v, float):
        return v if math.isfinite(v) else repr(v)
    if hasattr(v, "item") and not isinstance(v, (dict, list, tuple)):
        try:  # numpy/jax scalars
            return _jsonable(v.item())
        except Exception:  # noqa: BLE001 - non-scalar .item()
            return repr(v)
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    return repr(v)


class LogRecord:
    """One structured log event. Immutable once emitted."""

    __slots__ = ("ts", "levelno", "logger", "message", "trace_id", "span_id",
                 "thread_name", "fields")

    def __init__(self, ts: float, levelno: int, logger: str, message: str,
                 trace_id: Optional[str], span_id: Optional[str],
                 thread_name: str, fields: Dict[str, Any]):
        self.ts = ts
        self.levelno = levelno
        self.logger = logger
        self.message = message
        self.trace_id = trace_id
        self.span_id = span_id
        self.thread_name = thread_name
        self.fields = fields

    @property
    def level(self) -> str:
        return _level_name(self.levelno)

    def to_dict(self) -> Dict[str, Any]:
        # free-form fields first, reserved keys authoritative on collision
        d: Dict[str, Any] = {str(k): _jsonable(v)
                             for k, v in self.fields.items()}
        d.update(ts=self.ts, level=self.level, logger=self.logger,
                 message=self.message, thread=self.thread_name)
        if self.trace_id is not None:
            d["trace_id"] = self.trace_id
            d["span_id"] = self.span_id
        return d

    def to_json(self) -> str:
        """The JSON-lines form (one line, strict JSON)."""
        return json.dumps(self.to_dict(), sort_keys=True, default=repr)

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"LogRecord({self.level}, {self.logger}, {self.message!r})"


class LogRing:
    """Bounded ring buffer of records; overflow drops the OLDEST and
    ``dropped`` counts them — the ``TraceRecorder`` contract."""

    def __init__(self, capacity: int = 8192):
        self.capacity = int(capacity)
        self._records: "deque[LogRecord]" = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._total = 0

    def add(self, record: LogRecord) -> None:
        with self._lock:
            self._records.append(record)
            self._total += 1

    def records(self) -> List[LogRecord]:
        with self._lock:
            return list(self._records)

    def clear(self) -> None:
        with self._lock:
            self._records.clear()
            self._total = 0

    @property
    def total_recorded(self) -> int:
        with self._lock:
            return self._total

    @property
    def dropped(self) -> int:
        with self._lock:
            return max(0, self._total - len(self._records))

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)


class LogHub:
    """The process-wide structured-log sink: every record lands in the
    ring; when a ``stream`` is attached each record is also written as one
    JSON line (the shippable form). Level filtering happens here, once."""

    def __init__(self, *, stream: Optional[TextIO] = None,
                 capacity: int = 8192, level="debug"):
        self.ring = LogRing(capacity)
        self.stream = stream
        self.levelno = _level_no(level)
        self._stream_lock = threading.Lock()
        self._owns_stream = False

    def emit(self, record: LogRecord) -> None:
        if record.levelno < self.levelno:
            return
        self.ring.add(record)
        # the stream is read AND written under the lock: close() (hub swap
        # or disable mid-run) must never yank it between the None check
        # and the write on an emitting thread
        with self._stream_lock:
            stream = self.stream
            if stream is not None:
                try:
                    stream.write(record.to_json() + "\n")
                    stream.flush()
                except Exception:  # noqa: BLE001 - a dead stream (disk
                    # full, closed fd) must never raise into arbitrary log
                    # call sites (the stdlib Handler contract); the ring
                    # keeps recording
                    self.stream = None
                    if self._owns_stream:
                        try:
                            stream.close()
                        except Exception:  # noqa: BLE001
                            pass

    def close(self) -> None:
        with self._stream_lock:
            stream, self.stream = self.stream, None
            if self._owns_stream and stream is not None:
                stream.close()


def _current_span_ids():
    tr = _trace.get_active_tracer()
    if tr is None:
        return None, None
    ctx = tr.current_context()
    if ctx is None:
        return None, None
    return ctx.trace_id, ctx.span_id


class StructuredLogger:
    """Named front-end over the ACTIVE hub. Binding is late (per call), so
    enabling structured logging mid-run picks up every existing logger,
    and every call is a no-op ``is None`` check until a hub exists."""

    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def log(self, level, message: str, /, **fields) -> Optional[LogRecord]:
        hub = _active_hub
        if hub is None:
            return None
        levelno = _level_no(level)
        if levelno < hub.levelno:
            return None
        trace_id, span_id = _current_span_ids()
        rec = LogRecord(time.time(), levelno, self.name, str(message),
                        trace_id, span_id,
                        threading.current_thread().name, fields)
        hub.emit(rec)
        return rec

    def debug(self, message: str, /, **fields):
        return self.log(logging.DEBUG, message, **fields)

    def info(self, message: str, /, **fields):
        return self.log(logging.INFO, message, **fields)

    def warning(self, message: str, /, **fields):
        return self.log(logging.WARNING, message, **fields)

    def error(self, message: str, /, **fields):
        return self.log(logging.ERROR, message, **fields)


def get_logger(name: str) -> StructuredLogger:
    """A named structured logger (cheap; holds no state but the name)."""
    return StructuredLogger(name)


class StdlibBridgeHandler(logging.Handler):
    """Routes stdlib ``logging`` records into the active hub, stamping the
    current span's ids at emit time — every pre-existing ``log.info(...)``
    in the codebase joins the correlated stream for free."""

    def emit(self, record: logging.LogRecord) -> None:
        hub = _active_hub
        if hub is None:
            return
        try:
            message = record.getMessage()
        except Exception:  # noqa: BLE001 - bad %-format args must not raise
            message = str(record.msg)
        fields: Dict[str, Any] = {}
        if record.exc_info and record.exc_info[0] is not None:
            fields["exc_type"] = record.exc_info[0].__name__
            fields["exc"] = str(record.exc_info[1])
        trace_id, span_id = _current_span_ids()
        hub.emit(LogRecord(record.created, record.levelno, record.name,
                           message, trace_id, span_id,
                           threading.current_thread().name, fields))


# ---------------------------------------------------------------------------
# process-wide activation (the enable_tracing() pattern)
# ---------------------------------------------------------------------------

_active_hub: Optional[LogHub] = None
_active_lock = threading.Lock()
_bridge: Optional[StdlibBridgeHandler] = None


def get_active_hub() -> Optional[LogHub]:
    return _active_hub


def enable_structured_logging(*, stream: Optional[TextIO] = None,
                              path: Optional[str] = None,
                              capacity: int = 8192, level="debug",
                              bridge_stdlib: bool = True) -> LogHub:
    """Install the process-wide :class:`LogHub` and return it.

    ``stream`` (a text file object) or ``path`` (opened append-mode, owned
    and closed by ``disable_structured_logging``) receives JSON lines;
    with neither, records only land in the in-memory ring.
    ``bridge_stdlib`` attaches :class:`StdlibBridgeHandler` to the root
    logger (idempotent). A second call swaps the hub; the bridge follows
    the active hub automatically.
    """
    global _active_hub, _bridge
    if stream is not None and path is not None:
        raise ValueError("pass stream= or path=, not both")
    hub = LogHub(stream=stream, capacity=capacity, level=level)
    if path is not None:
        hub.stream = open(path, "a", encoding="utf-8")
        hub._owns_stream = True
    with _active_lock:
        old, _active_hub = _active_hub, hub
        if old is not None:
            old.close()
        if bridge_stdlib and _bridge is None:
            _bridge = StdlibBridgeHandler()
            logging.getLogger().addHandler(_bridge)
    return hub


def disable_structured_logging() -> None:
    """Deactivate: emit sites revert to no-ops, the stdlib bridge handler
    is removed, and a hub-owned file stream is closed."""
    global _active_hub, _bridge
    with _active_lock:
        hub, _active_hub = _active_hub, None
        if _bridge is not None:
            logging.getLogger().removeHandler(_bridge)
            _bridge = None
    if hub is not None:
        hub.close()


# ---------------------------------------------------------------------------
# rate-limit gates for hot-path logs
# ---------------------------------------------------------------------------

class every_n:
    """Callable gate: True on the 1st, (n+1)th, (2n+1)th ... call.

        _gate = every_n(100)
        ...
        if _gate():
            log.info("step", iteration=i)
    """

    def __init__(self, n: int):
        self.n = max(1, int(n))
        self._count = -1
        self._lock = threading.Lock()

    def __call__(self) -> bool:
        with self._lock:
            self._count += 1
            return self._count % self.n == 0


class at_most_every:
    """Callable gate: True at most once per ``seconds``, measured on
    ``clock`` (injectable — tests pass a manual clock, no sleeps)."""

    def __init__(self, seconds: float,
                 clock: Callable[[], float] = time.monotonic):
        self.seconds = float(seconds)
        self.clock = clock
        self._last: Optional[float] = None
        self._lock = threading.Lock()

    def __call__(self) -> bool:
        now = self.clock()
        with self._lock:
            if self._last is not None and now - self._last < self.seconds:
                return False
            self._last = now
            return True
