"""TraceListener — the TrainingListener → observe bridge.

Attach it like any other listener and every ``fit()`` in the framework
(MultiLayerNetwork, ComputationGraph, ParallelWrapper — anything that
fires ``iteration_done``) records per-iteration spans and exports
training metrics through the same Prometheus registry the serving tier
scrapes at ``/metrics`` — the role DL4J's PerformanceListener +
StatsListener play for the training UI, landed in the unified pipeline.

Spans are recorded AFTER the fact (the iteration window is closed inside
``iteration_done``), so the listener owns no open span state: a peer
listener throwing mid-iteration, or training aborting, can never leave a
dangling span behind.

Exported series (all labeled ``model``):

- ``training_steps_total``            counter
- ``training_step_seconds``           histogram (iteration wall time)
- ``training_examples_total``         counter   (rows seen)
- ``training_epochs_total``           counter
- ``training_score``                  gauge     (last loss; device sync!)
- ``training_compile_total``          counter   (XLA recompiles attributed
  to training steps, sampled from the active tracer's compile counter)
- ``training_last_batch_size``        gauge
- ``training_transfer_bytes_total``   counter   (host→device batch payload,
  sampled from the model's ``transfer_bytes`` accumulator)
"""

from __future__ import annotations

import time
from typing import Optional

from deeplearning4j_tpu.observe import trace as _trace
from deeplearning4j_tpu.observe.metrics import (MetricsRegistry,
                                                default_registry)
from deeplearning4j_tpu.optimize.listeners import TrainingListener

# step-time oriented buckets: 1ms … 60s (training steps dwarf the serving
# latency defaults)
STEP_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


class TraceListener(TrainingListener):
    """Record per-iteration spans + training metrics from any fit loop.

    ``tracer=None`` binds to the ACTIVE tracer at each call (so enabling
    tracing mid-run starts recording without re-wiring listeners);
    ``metrics=None`` uses the process-wide default registry — the one the
    serving/KNN/UI servers already expose.
    ``collect_score=False`` skips the ``training_score`` gauge and its
    device sync for throughput-critical runs.
    """

    def __init__(self, tracer: Optional[_trace.Tracer] = None,
                 metrics: Optional[MetricsRegistry] = None,
                 model_name: str = "default", *,
                 collect_score: bool = True):
        self._tracer = tracer
        self.model_name = model_name
        self.collect_score = collect_score
        self.metrics = metrics if metrics is not None else default_registry()
        m = self.metrics
        self._m_steps = m.counter(
            "training_steps_total", "Completed training iterations",
            ("model",))
        self._m_step_time = m.histogram(
            "training_step_seconds", "Training iteration wall time",
            ("model",), buckets=STEP_BUCKETS)
        self._m_examples = m.counter(
            "training_examples_total", "Training examples consumed",
            ("model",))
        self._m_epochs = m.counter(
            "training_epochs_total", "Completed training epochs", ("model",))
        self._m_score = m.gauge(
            "training_score", "Last training loss/score", ("model",))
        self._m_compiles = m.counter(
            "training_compile_total",
            "XLA compiles observed during training iterations", ("model",))
        self._m_batch = m.gauge(
            "training_last_batch_size", "Rows in the last training batch",
            ("model",))
        self._m_transfer = m.counter(
            "training_transfer_bytes_total",
            "Host to device bytes shipped with training batches", ("model",))
        self._t_last: Optional[int] = None
        self._compiles_seen: Optional[int] = None
        self._transfer_seen: Optional[int] = None

    # ------------------------------------------------------------- helpers
    def _active(self) -> Optional[_trace.Tracer]:
        return self._tracer if self._tracer is not None \
            else _trace.get_active_tracer()

    # ------------------------------------------------------ listener hooks
    def on_epoch_start(self, model) -> None:
        # (re)anchor the window so the first iteration of each epoch does
        # not absorb between-epoch work (evaluation, checkpointing)
        self._t_last = time.perf_counter_ns()
        # baseline the compile counter BEFORE the first step so step-0's
        # compile counts as "observed during training"
        if self._compiles_seen is None:
            tracer = self._active()
            if tracer is not None:
                self._compiles_seen = tracer.thread_compile_count()
        # likewise baseline the model's transfer accumulator so bytes shipped
        # before this listener attached are not replayed into the counter
        if self._transfer_seen is None:
            total = getattr(model, "transfer_bytes", None)
            if total is not None:
                self._transfer_seen = int(total)

    def iteration_done(self, model, iteration: int, epoch: int) -> None:
        now = time.perf_counter_ns()
        tracer = self._active()
        batch = int(getattr(model, "last_batch_size", 0) or 0)
        self._m_steps.inc(model=self.model_name)
        if batch:
            self._m_examples.inc(batch, model=self.model_name)
            self._m_batch.set(batch, model=self.model_name)
        if self.collect_score:
            try:
                self._m_score.set(float(model.score_), model=self.model_name)
            except Exception:  # noqa: BLE001 - score may be unset/deferred
                pass
        if tracer is not None:
            # recompiles since the last window, counted PER THREAD: only
            # compiles triggered on this training thread attribute to
            # training (a serving dispatcher compiling a new batch bucket
            # elsewhere in the process must not trip the alarm)
            count = tracer.thread_compile_count()
            if self._compiles_seen is None:
                self._compiles_seen = count
            elif count > self._compiles_seen:
                self._m_compiles.inc(count - self._compiles_seen,
                                     model=self.model_name)
                self._compiles_seen = count
        # transfer bytes: the fit loops accumulate model.transfer_bytes per
        # batch; export the delta since the last window (baselined at epoch
        # start so history before this listener attached is not replayed)
        total = getattr(model, "transfer_bytes", None)
        if total is not None:
            total = int(total)
            if self._transfer_seen is None:
                self._transfer_seen = 0
            if total > self._transfer_seen:
                self._m_transfer.inc(total - self._transfer_seen,
                                     model=self.model_name)
                self._transfer_seen = total
        if self._t_last is not None:
            dt_s = (now - self._t_last) / 1e9
            self._m_step_time.observe(dt_s, model=self.model_name)
            if tracer is not None:
                tracer.record(
                    "train_iteration", self._t_last, now, category="train",
                    attrs={"iteration": iteration, "epoch": epoch,
                           "batch": batch, "model": self.model_name})
        self._t_last = now

    def on_epoch_end(self, model) -> None:
        self._m_epochs.inc(model=self.model_name)
        self._t_last = None  # next window opens at on_epoch_start
