"""Unified observability layer: tracing + metrics across train and serve.

One subsystem answers "where did this millisecond go" end to end
(the role of DL4J's listener/StatsListener/training-UI stack plus the
Dapper-style request tracing the reference never had):

- ``trace``    — ``Span``/``Tracer``/``TraceRecorder``: contextvar-nested
  spans, explicit cross-thread handoff, W3C ``traceparent`` in/out,
  bounded ring buffer; ``enable_tracing()`` flips every instrumented hot
  path (ParallelWrapper steps, the ParallelInference dispatcher, the
  ModelServer request path, streaming routes) from no-op to recording;
- ``jaxhook``  — JAX compile/lowering attribution: ``jax.monitoring``
  events become ``xla_compile``/``jax_lowering`` spans nested under
  whatever span triggered them, so recompiles show up loudly;
- ``export``   — Chrome trace-event JSON (``chrome://tracing``/Perfetto)
  with flow arrows across threads, plus a terminal text timeline;
- ``metrics``  — the Prometheus registry core (promoted from
  ``serving.metrics``; that path remains as a deprecation re-export);
- ``listener`` — ``TraceListener``: the TrainingListener bridge that makes
  any ``fit()`` record spans and export ``training_*`` series through the
  same ``/metrics`` the serving tier already exposes;
- ``log``      — structured JSON-lines logging with automatic
  ``trace_id``/``span_id`` correlation from the active span, a bounded
  ring with drop accounting, a stdlib-``logging`` bridge and rate-limit
  gates (``enable_structured_logging()`` flips it on process-wide);
- ``health``   — ``TrainingWatchdog`` (NaN/Inf loss+params, gradient-norm
  EWMA, loss divergence, step stalls — with log/raise/callback action
  policies) and the serving ``HealthReport`` probes behind ``/livez``;
- ``alerts``   — threshold/absence/rate-of-change/multiwindow burn-rate
  rules evaluated over any registry's Prometheus exposition, with a
  deduping firing/resolved state machine, pluggable sinks and the
  ``AlertManager`` background evaluator (injectable clock);
- ``fleet``    — the multi-process operator plane for elastic/pod jobs:
  worker-side metrics snapshot files + crash-durable span streams,
  supervisor-side ``FleetRegistry`` federation (relabeled
  ``{slot,host,generation}`` union served at ``/metrics`` and fed to the
  alert engine) and ``merge_chrome_traces`` clock-aligned trace
  stitching;
- ``incident`` — the flight recorder: one bounded, schema'd
  ``incident_*`` bundle per elastic recovery decision
  (``tools/validate_incident.py`` lints it), plus ``capture_bundle``:
  the ``/debug/capture?seconds=N`` on-demand mini bundle;
- ``cost``     — the request-cost ledger: per-request device-time
  apportionment from ``batch_execute`` spans (compile excluded),
  conservation-checked, billed once into ``request_device_ms`` with
  exemplars;
- ``slo``      — declarative SLOs (latency-threshold and availability)
  compiled into multiwindow burn-rate rules for the alert engine, with
  a ``/slo`` compliance surface.
"""

from deeplearning4j_tpu.observe.metrics import (  # noqa: F401
    Counter,
    Exemplar,
    Gauge,
    Histogram,
    HTTPObserverMixin,
    MetricsRegistry,
    default_registry,
    exemplar_trace_ids,
    format_exemplar,
    instrument_http,
    parse_prometheus_text,
)
from deeplearning4j_tpu.observe.trace import (  # noqa: F401
    Span,
    SpanContext,
    TraceRecorder,
    Tracer,
    current_traceparent,
    disable_tracing,
    enable_tracing,
    get_active_tracer,
    parse_traceparent,
    span,
)
from deeplearning4j_tpu.observe.export import (  # noqa: F401
    merge_chrome_traces,
    text_timeline,
    to_chrome_trace,
    write_chrome_trace,
)
from deeplearning4j_tpu.observe.fleet import (  # noqa: F401
    FleetMetricsServer,
    FleetRegistry,
    MetricsFileExporter,
    SpanFileWriter,
    TailSampler,
    read_span_file,
)
from deeplearning4j_tpu.observe.incident import (  # noqa: F401
    IncidentRecorder,
    capture_bundle,
)
from deeplearning4j_tpu.observe.cost import (  # noqa: F401
    CostLedger,
    RequestCost,
)
from deeplearning4j_tpu.observe.slo import (  # noqa: F401
    SLO,
    LatencyBurnRateRule,
    SLOSet,
    load_slos,
)
from deeplearning4j_tpu.observe.listener import TraceListener  # noqa: F401
from deeplearning4j_tpu.observe.jaxhook import install_jax_hook  # noqa: F401
from deeplearning4j_tpu.observe.log import (  # noqa: F401
    LogHub,
    LogRecord,
    LogRing,
    StructuredLogger,
    at_most_every,
    disable_structured_logging,
    enable_structured_logging,
    every_n,
    get_active_hub,
    get_logger,
)
from deeplearning4j_tpu.observe.health import (  # noqa: F401
    HealthCheck,
    HealthEvent,
    HealthReport,
    ServingHealth,
    TrainingWatchdog,
    WatchdogAlarm,
    attach_observability,
)
from deeplearning4j_tpu.observe.alerts import (  # noqa: F401
    AbsenceRule,
    AlertManager,
    BurnRateRule,
    CallbackSink,
    LogSink,
    Notification,
    RateOfChangeRule,
    SLOSpec,
    ThresholdRule,
    WebhookSink,
    load_rules,
)
