"""JAX compile/execute attribution via ``jax.monitoring`` events.

``jax`` emits named duration events around tracing, lowering and backend
compilation (``/jax/core/compile/*``). Registering one process-wide
listener turns those into spans on the ACTIVE tracer, parented by whatever
span is current on the emitting thread — so a recompile triggered inside a
``train_step`` or ``batch_execute`` span nests under it and is impossible
to miss in the exported timeline.

The listener is installed once per process and is a cheap no-op while no
tracer is active (``jax.monitoring`` offers no single-listener removal, so
install is one-way by design). Import of ``jax`` is deferred to install
time: merely importing ``observe`` never pulls in the backend.
"""

from __future__ import annotations

import threading

from deeplearning4j_tpu.observe import trace as _trace

# monitoring event name → span name recorded on the active tracer
_EVENT_SPANS = {
    # the big one: XLA backend compilation (the recompile alarm)
    "/jax/core/compile/backend_compile_duration": "xla_compile",
    # jaxpr → StableHLO lowering (cheap, but visible when it isn't)
    "/jax/core/compile/jaxpr_to_mlir_module_duration": "jax_lowering",
}

_installed = False
_install_lock = threading.Lock()


def _on_event_duration(name: str, duration_s: float, **kwargs) -> None:
    span_name = _EVENT_SPANS.get(name)
    if span_name is None:
        return
    tracer = _trace.get_active_tracer()
    if tracer is None:
        return
    try:
        tracer.note_compile_event(span_name, duration_s)
    except Exception:  # noqa: BLE001 — observability must never break compute
        pass


def install_jax_hook() -> bool:
    """Register the monitoring listener (idempotent). Returns True when the
    hook is installed, False when ``jax.monitoring`` is unavailable."""
    global _installed
    with _install_lock:
        if _installed:
            return True
        try:
            import jax.monitoring as monitoring
        except Exception:  # pragma: no cover - jax always present in-repo
            return False
        monitoring.register_event_duration_secs_listener(_on_event_duration)
        _installed = True
        return True


def hook_installed() -> bool:
    return _installed
