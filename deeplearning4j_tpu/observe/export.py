"""Trace exporters: Chrome trace-event JSON and a terminal text timeline.

The JSON form follows the Trace Event Format (the ``chrome://tracing`` /
Perfetto input): one complete (``"ph": "X"``) event per span with
microsecond timestamps normalized to the earliest span, metadata events
naming the process and threads, and flow arrows (``"s"``/``"f"``) drawn
for span links — e.g. from an HTTP request span to the device batch that
served it on the dispatcher thread.

The text form is the same data for people without a browser: a
time-ordered, nesting-indented listing with durations, suitable for
dumping at the end of a CLI run.
"""

from __future__ import annotations

import json
import math
import os
from typing import Dict, List, Optional, Sequence

from deeplearning4j_tpu.observe.trace import Span


def _zlib_flow_id(src: str, dst: str) -> int:
    """Stable positive flow id from the two span ids (ids are hex strings;
    fold them — collisions across a single trace are practically nil)."""
    return (int(src, 16) ^ (int(dst, 16) << 1)) & 0x7FFFFFFF


def to_chrome_trace(spans: Sequence[Span], *,
                    service: str = "deeplearning4j_tpu") -> dict:
    """Render completed spans as a Trace Event Format object."""
    pid = os.getpid()
    events: List[dict] = [{
        "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
        "args": {"name": service},
    }]
    done = [s for s in spans if s.end_ns is not None]
    if not done:
        return {"traceEvents": events, "displayTimeUnit": "ms"}
    base = min(s.start_ns for s in done)
    by_id = {s.span_id: s for s in done}

    named_threads = set()
    for s in done:
        if s.thread_id not in named_threads:
            named_threads.add(s.thread_id)
            events.append({
                "name": "thread_name", "ph": "M", "pid": pid,
                "tid": s.thread_id, "args": {"name": s.thread_name},
            })

    for s in sorted(done, key=lambda sp: sp.start_ns):
        ts = (s.start_ns - base) / 1e3
        args = {"trace_id": s.trace_id, "span_id": s.span_id}
        if s.parent_id:
            args["parent_id"] = s.parent_id
        if s.error:
            args["error"] = s.error
        for k, v in s.attrs.items():
            args[str(k)] = sanitize_attr(v)
        events.append({
            "name": s.name, "cat": s.category, "ph": "X",
            "ts": ts, "dur": max((s.end_ns - s.start_ns) / 1e3, 0.0),
            "pid": pid, "tid": s.thread_id, "args": args,
        })
        # flow arrows: linked span → this span (only when the source is
        # still in the ring buffer; a dropped source just loses its arrow)
        for link in s.links:
            src = by_id.get(link.span_id)
            if src is None:
                continue
            fid = _zlib_flow_id(src.span_id, s.span_id)
            events.append({
                "name": "link", "cat": "flow", "ph": "s", "id": fid,
                "ts": (src.start_ns - base) / 1e3, "pid": pid,
                "tid": src.thread_id,
            })
            events.append({
                "name": "link", "cat": "flow", "ph": "f", "bp": "e",
                "id": fid, "ts": ts, "pid": pid, "tid": s.thread_id,
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(path, spans: Sequence[Span], *,
                       service: str = "deeplearning4j_tpu") -> dict:
    """Write the Chrome trace JSON; returns the object written."""
    obj = to_chrome_trace(spans, service=service)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(obj, fh)
    return obj


# ---------------------------------------------------------------------------
# fleet trace stitching: one Perfetto timeline from many processes
# ---------------------------------------------------------------------------

def sanitize_attr(v):
    """THE attr-value rule for every exporter (inline trace, worker span
    files, merged fleet trace): non-finite floats become their repr —
    bare NaN/Infinity tokens are not strict JSON and chrome://tracing
    rejects the whole file — and non-primitives degrade to ``str``."""
    if isinstance(v, float) and not math.isfinite(v):
        return str(v)
    if isinstance(v, (int, float, bool, str, type(None))):
        return v
    return str(v)


def _sanitize_args(rec_args: dict) -> dict:
    return {str(k): sanitize_attr(v) for k, v in rec_args.items()}


def merge_chrome_traces(sources: Sequence, *, out=None) -> dict:
    """Stitch per-process span streams into ONE Chrome/Perfetto timeline.

    ``sources`` mixes two forms:

    - a path to a ``SpanFileWriter`` JSONL file (a worker's crash-durable
      span stream), or
    - ``{"label": str, "spans": [Span], "anchor": (perf_ns, epoch_us)}``
      for a live recorder (the supervisor's own ring; anchor defaults to
      this process's ``EPOCH_ANCHOR``).

    Clock alignment: ``perf_counter_ns`` is per-process, so every source
    carries its own anchor pair ``(perf_ns_at_import, epoch_us_at_import)``
    and each span maps to wall-clock micros as
    ``epoch_us = anchor_epoch_us + (start_ns - anchor_perf_ns)/1e3``; the
    merged timeline is normalized to the earliest aligned span.  Sources
    without an anchor (torn meta line) are skipped — a mis-aligned row
    is worse than a missing one.

    Rendering: one Chrome ``pid`` row per source (process_name = the
    source label, e.g. ``slot 2 gen 1``), ``X`` events per span,
    ``category == "decision"`` spans as instant events (``ph: "i"`` —
    the supervisor's restart/shrink/fail calls), and flow arrows for
    span links resolved ACROSS sources — a ``dcn_recv`` linking the
    sender's ``dcn_send`` renders as an arrow between worker rows.
    """
    from deeplearning4j_tpu.observe.fleet import read_span_file
    from deeplearning4j_tpu.observe.trace import EPOCH_ANCHOR

    norm = []  # (label, anchor, [span dicts])
    for src in sources:
        if isinstance(src, (str, os.PathLike)):
            try:
                parsed = read_span_file(str(src))
            except OSError:
                continue
            if parsed["anchor"] is None or not parsed["spans"]:
                continue
            norm.append((parsed["label"], parsed["anchor"], parsed["spans"]))
        else:
            spans = [{
                "name": s.name, "cat": s.category, "trace": s.trace_id,
                "span": s.span_id, "parent": s.parent_id,
                "start_ns": s.start_ns, "end_ns": s.end_ns,
                "tid": s.thread_id, "tname": s.thread_name,
                "attrs": s.attrs, "error": s.error,
                "links": [{"trace": l.trace_id, "span": l.span_id}
                          for l in s.links],
            } for s in src["spans"] if s.end_ns is not None]
            if not spans:
                continue
            norm.append((src.get("label", "process"),
                         tuple(src.get("anchor", EPOCH_ANCHOR)), spans))

    events: List[dict] = []
    if not norm:
        obj = {"traceEvents": events, "displayTimeUnit": "ms"}
        if out is not None:
            with open(out, "w", encoding="utf-8") as fh:
                json.dump(obj, fh)
        return obj

    def aligned_us(anchor, ns: int) -> float:
        return anchor[1] + (ns - anchor[0]) / 1e3

    base = min(aligned_us(anchor, rec["start_ns"])
               for _, anchor, spans in norm for rec in spans)

    # global span index for cross-process flow resolution
    by_id: Dict[str, tuple] = {}
    for pid, (_, anchor, spans) in enumerate(norm, start=1):
        for rec in spans:
            by_id[rec["span"]] = (pid, rec["tid"],
                                  max(0.0, aligned_us(anchor,
                                                      rec["start_ns"]) - base),
                                  rec["name"])

    for pid, (label, anchor, spans) in enumerate(norm, start=1):
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": str(label)}})
        named = set()
        for rec in sorted(spans, key=lambda r: r["start_ns"]):
            tid = int(rec["tid"])
            if tid not in named:
                named.add(tid)
                events.append({
                    "name": "thread_name", "ph": "M", "pid": pid,
                    "tid": tid,
                    "args": {"name": str(rec.get("tname", tid))}})
            ts = max(0.0, aligned_us(anchor, rec["start_ns"]) - base)
            args = {"trace_id": rec["trace"], "span_id": rec["span"]}
            if rec.get("parent"):
                args["parent_id"] = rec["parent"]
            if rec.get("error"):
                args["error"] = rec["error"]
            args.update(_sanitize_args(rec.get("attrs") or {}))
            if rec.get("cat") == "decision":
                # supervisor decisions: a point in time, not an interval
                events.append({"name": rec["name"], "cat": "decision",
                               "ph": "i", "s": "p", "ts": ts, "pid": pid,
                               "tid": tid, "args": args})
            else:
                dur = max((rec["end_ns"] - rec["start_ns"]) / 1e3, 0.0)
                events.append({"name": rec["name"],
                               "cat": str(rec.get("cat", "app")),
                               "ph": "X", "ts": ts, "dur": dur, "pid": pid,
                               "tid": tid, "args": args})
            for link in rec.get("links") or ():
                src_loc = by_id.get(link.get("span"))
                if src_loc is None:
                    continue  # source dropped/killed: the arrow is lost
                src_pid, src_tid, src_ts, _ = src_loc
                fid = _zlib_flow_id(link["span"], rec["span"])
                events.append({"name": "link", "cat": "flow", "ph": "s",
                               "id": fid, "ts": src_ts, "pid": src_pid,
                               "tid": src_tid})
                events.append({"name": "link", "cat": "flow", "ph": "f",
                               "bp": "e", "id": fid, "ts": ts, "pid": pid,
                               "tid": tid})
    obj = {"traceEvents": events, "displayTimeUnit": "ms"}
    if out is not None:
        with open(out, "w", encoding="utf-8") as fh:
            json.dump(obj, fh)
    return obj


def text_timeline(spans: Sequence[Span], *, limit: Optional[int] = None,
                  attrs: bool = True) -> str:
    """Compact terminal rendering: start offset, duration, nesting depth.

    ::

        [+     0.000ms    12.40ms] train_step  iteration=1 batch=32
        [+     0.312ms     9.80ms]   xla_compile
    """
    done = sorted((s for s in spans if s.end_ns is not None),
                  key=lambda sp: sp.start_ns)
    if limit is not None:
        done = done[-limit:]
    if not done:
        return "(no spans recorded)"
    base = done[0].start_ns
    by_id: Dict[str, Span] = {s.span_id: s for s in done}

    def depth(s: Span) -> int:
        d, seen = 0, set()
        while s.parent_id and s.parent_id in by_id and s.span_id not in seen:
            seen.add(s.span_id)
            s = by_id[s.parent_id]
            d += 1
        return d

    lines = []
    for s in done:
        off = (s.start_ns - base) / 1e6
        dur = (s.end_ns - s.start_ns) / 1e6
        line = (f"[+{off:10.3f}ms {dur:9.3f}ms] "
                f"{'  ' * depth(s)}{s.name}")
        if s.error:
            line += f"  !{s.error}"
        if attrs and s.attrs:
            line += "  " + " ".join(f"{k}={v}" for k, v in s.attrs.items())
        if s.links:
            # the Chrome exporter's flow arrows, in text: name the linked
            # source span when it is still in the window, else its id —
            # dispatcher coalescing / DCN exchanges stay visible on a
            # terminal
            tags = []
            for link in s.links:
                src = by_id.get(link.span_id)
                tags.append(f"<-{src.name}" if src is not None
                            else f"<-{link.span_id[:8]}")
            line += "  [" + " ".join(tags) + "]"
        lines.append(line)
    return "\n".join(lines)
