"""Trace exporters: Chrome trace-event JSON and a terminal text timeline.

The JSON form follows the Trace Event Format (the ``chrome://tracing`` /
Perfetto input): one complete (``"ph": "X"``) event per span with
microsecond timestamps normalized to the earliest span, metadata events
naming the process and threads, and flow arrows (``"s"``/``"f"``) drawn
for span links — e.g. from an HTTP request span to the device batch that
served it on the dispatcher thread.

The text form is the same data for people without a browser: a
time-ordered, nesting-indented listing with durations, suitable for
dumping at the end of a CLI run.
"""

from __future__ import annotations

import json
import math
import os
from typing import Dict, List, Optional, Sequence

from deeplearning4j_tpu.observe.trace import Span


def _zlib_flow_id(src: str, dst: str) -> int:
    """Stable positive flow id from the two span ids (ids are hex strings;
    fold them — collisions across a single trace are practically nil)."""
    return (int(src, 16) ^ (int(dst, 16) << 1)) & 0x7FFFFFFF


def to_chrome_trace(spans: Sequence[Span], *,
                    service: str = "deeplearning4j_tpu") -> dict:
    """Render completed spans as a Trace Event Format object."""
    pid = os.getpid()
    events: List[dict] = [{
        "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
        "args": {"name": service},
    }]
    done = [s for s in spans if s.end_ns is not None]
    if not done:
        return {"traceEvents": events, "displayTimeUnit": "ms"}
    base = min(s.start_ns for s in done)
    by_id = {s.span_id: s for s in done}

    named_threads = set()
    for s in done:
        if s.thread_id not in named_threads:
            named_threads.add(s.thread_id)
            events.append({
                "name": "thread_name", "ph": "M", "pid": pid,
                "tid": s.thread_id, "args": {"name": s.thread_name},
            })

    for s in sorted(done, key=lambda sp: sp.start_ns):
        ts = (s.start_ns - base) / 1e3
        args = {"trace_id": s.trace_id, "span_id": s.span_id}
        if s.parent_id:
            args["parent_id"] = s.parent_id
        if s.error:
            args["error"] = s.error
        for k, v in s.attrs.items():
            if isinstance(v, float) and not math.isfinite(v):
                v = str(v)  # bare NaN/Infinity tokens are not JSON —
                # chrome://tracing would reject the whole file
            elif not isinstance(v, (int, float, bool, str, type(None))):
                v = str(v)
            args[str(k)] = v
        events.append({
            "name": s.name, "cat": s.category, "ph": "X",
            "ts": ts, "dur": max((s.end_ns - s.start_ns) / 1e3, 0.0),
            "pid": pid, "tid": s.thread_id, "args": args,
        })
        # flow arrows: linked span → this span (only when the source is
        # still in the ring buffer; a dropped source just loses its arrow)
        for link in s.links:
            src = by_id.get(link.span_id)
            if src is None:
                continue
            fid = _zlib_flow_id(src.span_id, s.span_id)
            events.append({
                "name": "link", "cat": "flow", "ph": "s", "id": fid,
                "ts": (src.start_ns - base) / 1e3, "pid": pid,
                "tid": src.thread_id,
            })
            events.append({
                "name": "link", "cat": "flow", "ph": "f", "bp": "e",
                "id": fid, "ts": ts, "pid": pid, "tid": s.thread_id,
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(path, spans: Sequence[Span], *,
                       service: str = "deeplearning4j_tpu") -> dict:
    """Write the Chrome trace JSON; returns the object written."""
    obj = to_chrome_trace(spans, service=service)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(obj, fh)
    return obj


def text_timeline(spans: Sequence[Span], *, limit: Optional[int] = None,
                  attrs: bool = True) -> str:
    """Compact terminal rendering: start offset, duration, nesting depth.

    ::

        [+     0.000ms    12.40ms] train_step  iteration=1 batch=32
        [+     0.312ms     9.80ms]   xla_compile
    """
    done = sorted((s for s in spans if s.end_ns is not None),
                  key=lambda sp: sp.start_ns)
    if limit is not None:
        done = done[-limit:]
    if not done:
        return "(no spans recorded)"
    base = done[0].start_ns
    by_id: Dict[str, Span] = {s.span_id: s for s in done}

    def depth(s: Span) -> int:
        d, seen = 0, set()
        while s.parent_id and s.parent_id in by_id and s.span_id not in seen:
            seen.add(s.span_id)
            s = by_id[s.parent_id]
            d += 1
        return d

    lines = []
    for s in done:
        off = (s.start_ns - base) / 1e6
        dur = (s.end_ns - s.start_ns) / 1e6
        line = (f"[+{off:10.3f}ms {dur:9.3f}ms] "
                f"{'  ' * depth(s)}{s.name}")
        if s.error:
            line += f"  !{s.error}"
        if attrs and s.attrs:
            line += "  " + " ".join(f"{k}={v}" for k, v in s.attrs.items())
        lines.append(line)
    return "\n".join(lines)
