"""Health watchdogs: training divergence detection + serving probes.

The detection half of the observability loop. Training side:
:class:`TrainingWatchdog` is a TrainingListener (attach it like any other
listener — or through :func:`attach_observability`, the one attachment
path it shares with ``TraceListener``) that notices a run going bad while
it is still cheap to stop:

- NaN/Inf loss the step it appears;
- NaN/Inf parameters (periodic scan — a device sync, so off by default);
- gradient-norm explosion/vanishing against an EWMA baseline (norms come
  from a ``gradient_batch`` probe, the ``ParamAndGradientIterationListener``
  technique, or are pushed by an outer loop via
  :meth:`TrainingWatchdog.observe_gradient_norm`);
- loss divergence: score strictly rising for K consecutive windows;
- step-time stall: an iteration taking ``stall_factor``× the rolling
  median (injectable clock — tests drive it without sleeps).

Each check carries a configurable action policy — ``"log"`` (structured
log with trace correlation), ``"raise"`` (:class:`WatchdogAlarm`, which
``EarlyStoppingTrainer`` converts into an ``Error`` termination and the
``util/preemption.py`` rollback flow catches to restore the last good
checkpoint), or a callback.

Serving side: :class:`ServingHealth` folds ``ParallelInference``
dispatcher liveness, ``AdmissionController`` saturation/drain and
``ModelRegistry`` state into one :class:`HealthReport`, served by
``ModelServer`` on ``GET /livez`` (``?verbose=1`` for the full check
list) — the condensed answer "is this process worth keeping alive".
"""

from __future__ import annotations

import logging
import time
from collections import deque
from statistics import median
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.observe import log as _slog
from deeplearning4j_tpu.optimize.listeners import TrainingListener

log = logging.getLogger(__name__)

_CHECKS = ("nan_loss", "nan_params", "nan_gradient", "gradient_explosion",
           "gradient_vanishing", "loss_divergence", "step_stall")


class HealthEvent:
    """One watchdog finding."""

    __slots__ = ("check", "message", "iteration", "epoch", "value",
                 "model_name", "ts")

    def __init__(self, check: str, message: str, iteration: int, epoch: int,
                 value: float, model_name: str):
        self.check = check
        self.message = message
        self.iteration = iteration
        self.epoch = epoch
        self.value = value
        self.model_name = model_name
        self.ts = time.time()

    def to_dict(self) -> Dict[str, Any]:
        return {"check": self.check, "message": self.message,
                "iteration": self.iteration, "epoch": self.epoch,
                "value": self.value, "model": self.model_name,
                "ts": self.ts}

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"HealthEvent({self.check}, iter={self.iteration})"


class WatchdogAlarm(RuntimeError):
    """Raised by a ``"raise"``-policy check. Carries the event; propagates
    out of ``fit()`` so an outer loop (EarlyStopping, the preemption
    rollback flow) can stop the run and recover."""

    def __init__(self, event: HealthEvent):
        super().__init__(f"{event.check} at iteration {event.iteration}: "
                         f"{event.message}")
        self.event = event


class TrainingWatchdog(TrainingListener):
    """Divergence watchdog for any fit loop.

    ``action`` is the default policy (``"log"`` | ``"raise"`` | a callable
    taking the :class:`HealthEvent`); ``actions`` overrides it per check
    name (see module docstring for the check names). Every event is also
    appended to ``self.events`` and counted in
    ``watchdog_events_total{model,check}`` when ``metrics`` is given.

    ``clock`` returns seconds (monotonic); inject a manual one to test
    stall detection deterministically. ``gradient_batch`` — a DataSet or
    ``(x, y)`` tuple — enables the gradient-norm checks via a probe
    ``compute_gradient_and_score`` every ``check_gradients_every``
    iterations (device work: size the probe batch accordingly).
    """

    def __init__(self, *, model_name: str = "default",
                 action="log", actions: Optional[Dict[str, Any]] = None,
                 metrics=None,
                 check_params_every: int = 0,
                 gradient_batch=None, check_gradients_every: int = 1,
                 grad_ewma_alpha: float = 0.1,
                 grad_explode_factor: float = 50.0,
                 grad_vanish_factor: float = 1e-4,
                 grad_warmup: int = 5,
                 divergence_windows: int = 5,
                 stall_factor: float = 10.0, stall_window: int = 16,
                 stall_min_history: int = 5,
                 clock: Callable[[], float] = time.perf_counter):
        unknown = set(actions or ()) - set(_CHECKS)
        if unknown:
            raise ValueError(f"unknown watchdog checks {sorted(unknown)}; "
                             f"known: {_CHECKS}")
        self.model_name = model_name
        self.action = action
        self.actions = dict(actions or {})
        self.check_params_every = int(check_params_every)
        self.gradient_batch = gradient_batch
        self.check_gradients_every = max(1, int(check_gradients_every))
        self.grad_ewma_alpha = float(grad_ewma_alpha)
        self.grad_explode_factor = float(grad_explode_factor)
        self.grad_vanish_factor = float(grad_vanish_factor)
        self.grad_warmup = max(1, int(grad_warmup))
        self.divergence_windows = max(1, int(divergence_windows))
        self.stall_factor = float(stall_factor)
        self.stall_min_history = max(2, int(stall_min_history))
        self.clock = clock
        self.events: List[HealthEvent] = []
        self._m_events = None
        if metrics is not None:
            self._m_events = metrics.counter(
                "watchdog_events_total",
                "Training watchdog findings by check", ("model", "check"))
        self._slog = _slog.get_logger("observe.health")
        self._grad_ewma: Optional[float] = None
        self._grad_seen = 0
        self._prev_score: Optional[float] = None
        self._rising = 0
        self._step_times: "deque[float]" = deque(maxlen=int(stall_window))
        self._t_last: Optional[float] = None
        self._iteration = 0
        self._epoch = 0

    # ------------------------------------------------------------- events
    def _fire(self, check: str, message: str, value: float) -> None:
        event = HealthEvent(check, message, self._iteration, self._epoch,
                            float(value), self.model_name)
        self.events.append(event)
        if self._m_events is not None:
            self._m_events.inc(model=self.model_name, check=check)
        # structured stream when one is active (fields + trace correlation
        # ride along); plain stdlib warning otherwise so the finding is
        # never silent
        if _slog.get_active_hub() is not None:
            self._slog.warning(message, check=check, value=value,
                               iteration=self._iteration, epoch=self._epoch,
                               model=self.model_name)
        else:
            log.warning("[watchdog:%s] %s", check, message)
        act = self.actions.get(check, self.action)
        if callable(act):
            act(event)
        elif act == "raise":
            raise WatchdogAlarm(event)
        elif act != "log":
            raise ValueError(f"unknown watchdog action {act!r} for {check}")

    # ------------------------------------------------------------- checks
    def observe_gradient_norm(self, norm: float) -> None:
        """Feed one global gradient norm (probe-computed here, or pushed by
        an outer training loop that materializes norms anyway, e.g. for
        clipping). Explosion/vanishing are judged against an EWMA baseline
        after ``grad_warmup`` observations."""
        norm = float(norm)
        if not np.isfinite(norm):
            self._fire("nan_gradient",
                       f"gradient norm is non-finite ({norm})", norm)
            return
        if self._grad_seen >= self.grad_warmup and self._grad_ewma is not None:
            # zero baseline (all-zero norms through warmup: frozen params,
            # fully masked batches): ANY nonzero norm is an explosion —
            # the factor semantics in the limit, not a disabled check
            if (norm > self.grad_explode_factor * self._grad_ewma
                    if self._grad_ewma > 0 else norm > 0.0):
                self._fire(
                    "gradient_explosion",
                    f"gradient norm {norm:.4g} exceeds "
                    f"{self.grad_explode_factor}x the EWMA baseline "
                    f"{self._grad_ewma:.4g}", norm)
                return  # a spike must not poison the baseline
            if (self._grad_ewma > 0
                    and norm < self.grad_vanish_factor * self._grad_ewma):
                self._fire(
                    "gradient_vanishing",
                    f"gradient norm {norm:.4g} fell below "
                    f"{self.grad_vanish_factor}x the EWMA baseline "
                    f"{self._grad_ewma:.4g}", norm)
                return
        self._grad_seen += 1
        a = self.grad_ewma_alpha
        self._grad_ewma = (norm if self._grad_ewma is None
                           else a * norm + (1 - a) * self._grad_ewma)

    def _check_score(self, model) -> None:
        try:
            score = float(model.score_)
        except Exception:  # noqa: BLE001 - score may be unset/deferred
            return
        if not np.isfinite(score):
            self._fire("nan_loss", f"training loss is non-finite ({score})",
                       score)
            self._prev_score = None
            return
        if self._prev_score is not None and score > self._prev_score:
            self._rising += 1
            if self._rising >= self.divergence_windows:
                self._fire(
                    "loss_divergence",
                    f"loss rose for {self._rising} consecutive windows "
                    f"(now {score:.6g})", score)
                self._rising = 0
        else:
            self._rising = 0
        self._prev_score = score

    def _check_params(self, model) -> None:
        params = getattr(model, "params", None)
        if params is None:
            return
        groups = params.values() if isinstance(params, dict) else params
        for group in groups:
            if not isinstance(group, dict):
                continue
            for name, arr in group.items():
                if not np.all(np.isfinite(np.asarray(arr))):
                    self._fire(
                        "nan_params",
                        f"parameter {name!r} contains non-finite values",
                        float("nan"))
                    return  # one event per scan is enough

    def _check_gradients(self, model) -> None:
        ds = self.gradient_batch
        if isinstance(ds, tuple):
            grads, _ = model.compute_gradient_and_score(*ds)
        else:
            # masks only when present: ComputationGraph's
            # compute_gradient_and_score has no mask kwargs
            kw = {}
            if getattr(ds, "features_mask", None) is not None:
                kw["features_mask"] = ds.features_mask
            if getattr(ds, "labels_mask", None) is not None:
                kw["labels_mask"] = ds.labels_mask
            grads, _ = model.compute_gradient_and_score(
                ds.features, ds.labels, **kw)
        groups = grads.values() if isinstance(grads, dict) else grads
        sq = 0.0
        for g in groups:
            for arr in g.values():
                a = np.asarray(arr, np.float64)
                sq += float(np.sum(a * a))
        self.observe_gradient_norm(np.sqrt(sq))

    def _check_stall(self, now: float) -> None:
        if self._t_last is None:
            return
        dt = now - self._t_last
        if (len(self._step_times) >= self.stall_min_history
                and dt > self.stall_factor * median(self._step_times)):
            self._fire(
                "step_stall",
                f"iteration took {dt:.4g}s vs rolling median "
                f"{median(self._step_times):.4g}s "
                f"(x{dt / median(self._step_times):.1f})", dt)
        self._step_times.append(dt)

    # ------------------------------------------------------ listener hooks
    def on_epoch_start(self, model) -> None:
        # re-anchor so the first step of an epoch does not absorb
        # between-epoch work (evaluation, checkpointing) as a false stall
        self._t_last = self.clock()

    def iteration_done(self, model, iteration: int, epoch: int) -> None:
        now = self.clock()
        self._iteration, self._epoch = iteration, epoch
        self._check_stall(now)
        self._t_last = now
        self._check_score(model)
        if (self.check_params_every
                and iteration % self.check_params_every == 0):
            self._check_params(model)
        if (self.gradient_batch is not None
                and iteration % self.check_gradients_every == 0):
            self._check_gradients(model)

    def on_epoch_end(self, model) -> None:
        self._t_last = None


def attach_observability(model, *, tracer=None, metrics=None,
                         model_name: str = "default",
                         trace: bool = True,
                         watchdog=None) -> list:
    """The one listener attachment path TraceListener and the watchdog
    share: appends a ``TraceListener`` (unless ``trace=False``) and a
    :class:`TrainingWatchdog` (pass ``watchdog=True`` for defaults, a dict
    of :class:`TrainingWatchdog` kwargs, or a ready instance) to
    ``model.listeners``; returns the listeners it attached."""
    from deeplearning4j_tpu.observe.listener import TraceListener

    attached = []
    if trace:
        attached.append(TraceListener(tracer, metrics, model_name))
    if watchdog is not None and watchdog is not False:
        if isinstance(watchdog, TrainingWatchdog):
            wd = watchdog
        else:
            kw = dict(watchdog) if isinstance(watchdog, dict) else {}
            kw.setdefault("model_name", model_name)
            kw.setdefault("metrics", metrics)
            wd = TrainingWatchdog(**kw)
        attached.append(wd)
    model.listeners.extend(attached)
    return attached


# ---------------------------------------------------------------------------
# serving-side probes
# ---------------------------------------------------------------------------

class HealthCheck:
    """One probe result. ``critical`` failing drives the report to
    ``down`` (restart-worthy); non-critical failures mark ``degraded``."""

    __slots__ = ("name", "healthy", "detail", "critical")

    def __init__(self, name: str, healthy: bool, detail: str = "",
                 critical: bool = False):
        self.name = name
        self.healthy = bool(healthy)
        self.detail = detail
        self.critical = critical

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "healthy": self.healthy,
                "detail": self.detail, "critical": self.critical}


class HealthReport:
    """A set of checks condensed to one status: ``ok`` (all healthy),
    ``degraded`` (non-critical failures) or ``down`` (a critical probe
    failed — the process is not worth keeping alive)."""

    def __init__(self, checks: List[HealthCheck]):
        self.checks = list(checks)

    @property
    def status(self) -> str:
        if any(c.critical and not c.healthy for c in self.checks):
            return "down"
        if any(not c.healthy for c in self.checks):
            return "degraded"
        return "ok"

    @property
    def healthy(self) -> bool:
        return self.status != "down"

    def to_dict(self) -> Dict[str, Any]:
        return {"status": self.status,
                "checks": [c.to_dict() for c in self.checks]}


class ServingHealth:
    """Folds the serving tier's state into one :class:`HealthReport`:
    per-model dispatcher liveness (critical only when the crash is
    TERMINAL — a supervised dispatcher with a restart pending is degraded,
    not restart-worthy: the process will heal itself), circuit-breaker
    quarantines and brownout mode (degraded), admission saturation above
    ``saturation_threshold`` and drain mode (degraded), and registry
    emptiness/hot-swap state. ``extra_probes`` are callables returning a
    :class:`HealthCheck`, the plug point for custom checks."""

    def __init__(self, registry=None, admission=None, *,
                 saturation_threshold: float = 0.9,
                 brownout=None,
                 extra_probes: Optional[List[Callable[[], HealthCheck]]]
                 = None):
        self.registry = registry
        self.admission = admission
        self.brownout = brownout
        self.saturation_threshold = float(saturation_threshold)
        self.extra_probes = list(extra_probes or [])

    def report(self) -> HealthReport:
        checks: List[HealthCheck] = []
        if self.registry is not None:
            names = self.registry.names()
            checks.append(HealthCheck(
                "registry_models", bool(names),
                f"{len(names)} model(s) registered: {', '.join(names)}"
                if names else "no models registered"))
            breaker_states = getattr(self.registry, "breaker_states", None)
            for name in names:
                try:
                    inf = self.registry.get(name).inference
                except Exception:  # noqa: BLE001 - unregistered between
                    continue       # names() and get(); not a failure
                err = getattr(inf, "dispatcher_error", None)
                rst_fn = getattr(inf, "restart_state", None)
                rst = rst_fn() if callable(rst_fn) else None
                if inf.healthy:
                    detail = "up"
                    if rst is not None and rst["restarts_used"]:
                        detail = (f"up (supervised: restarted "
                                  f"{rst['restarts_used']}x of "
                                  f"{rst['max_restarts']} budget)")
                    checks.append(HealthCheck(
                        f"dispatcher:{name}", True, detail, critical=True))
                elif rst is not None and rst["restart_pending"]:
                    # a crash the supervisor will heal is NOT a reason to
                    # kill the process — /livez stays 200 (degraded)
                    checks.append(HealthCheck(
                        f"dispatcher:{name}", False,
                        f"crashed; in-place restart in "
                        f"{rst['retry_after_s']:.2f}s (used "
                        f"{rst['restarts_used']}/{rst['max_restarts']})",
                        critical=False))
                else:
                    checks.append(HealthCheck(
                        f"dispatcher:{name}", False,
                        f"dispatcher dead: {err!r}" if err is not None
                        else "shut down",
                        critical=True))
                if breaker_states is not None:
                    try:
                        tripped = {v: s
                                   for v, s in breaker_states(name).items()
                                   if s != "closed"}
                    except Exception:  # noqa: BLE001 - unregistered race
                        tripped = {}
                    if tripped:
                        checks.append(HealthCheck(
                            f"breaker:{name}", False,
                            "quarantined version(s): " + ", ".join(
                                f"v{v}={s}"
                                for v, s in sorted(tripped.items()))))
            if self.registry.swapping:
                checks.append(HealthCheck(
                    "registry_swap", False, "hot-swap in progress"))
        if self.brownout is not None and self.brownout.active:
            checks.append(HealthCheck(
                "brownout", False,
                "brownout active: "
                + (self.brownout.describe().get("last_reason") or "")))
        if self.admission is not None:
            inflight = self.admission.inflight
            limit = self.admission.max_inflight
            saturated = inflight >= self.saturation_threshold * limit
            checks.append(HealthCheck(
                "admission_saturation", not saturated,
                f"{inflight}/{limit} in flight"))
            if self.admission.draining:
                checks.append(HealthCheck(
                    "admission_drain", False, "draining"))
        for probe in self.extra_probes:
            checks.append(probe())
        return HealthReport(checks)
