"""Request economics: device-time attribution for coalesced inference.

The dispatcher serves many requests in one ``batch_execute`` span; the
per-tenant question "what did THIS request cost in device time" needs
that span's duration split back across its requests.  THE apportionment
rule:

- each batch's **device milliseconds** are the ``batch_execute`` span's
  wall duration on the dispatcher thread MINUS any ``xla_compile`` /
  ``jax_lowering`` seconds observed on that thread during the span
  (``Tracer.thread_compile_seconds`` delta) — a cold bucket's first
  request must never be billed the compile spike it happened to trigger;
- the remainder is divided **row-weighted** across the coalesced
  requests (a 6-row request in an 8-row batch pays 6/8ths);
- compile time is attributed separately per model
  (``request_compile_device_ms_total{model}``), never to a request;
- padding rows belong to nobody, so their time is spread across the real
  rows — the batch's full device time is always conserved:
  ``sum(per-request shares) + unattributed == sum(batch device time)``
  within float tolerance, which :meth:`CostLedger.conservation` checks
  and the bench re-proves on every CI run.

The :class:`CostLedger` keys per-request shares by **trace id** — the one
identifier that already flows client → HTTP span → ``inference_request``
→ the dispatcher's ``_Request.ctx`` — so the serving front-end can
:meth:`~CostLedger.bill` the finished request (observing
``request_device_ms{model,priority}`` with the priority only IT knows)
and echo the cost as the ``X-Device-Ms`` response header.  Requests that
arrive without a trace context (tracing disabled) still conserve: their
shares land in the per-model ``unattributed_device_ms`` bucket.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

# request-level device-ms buckets: sub-ms CPU forwards through multi-second
# cold paths (the latency DEFAULT_BUCKETS are seconds-scaled; these are ms)
DEVICE_MS_BUCKETS = (0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 50.0,
                     100.0, 500.0, 1000.0, 5000.0)


class RequestCost:
    """One request's accumulated device time (a retried/failed-over
    request can appear in more than one batch; shares accumulate)."""

    __slots__ = ("trace_id", "model", "rows", "device_ms", "batches",
                 "billed")

    def __init__(self, trace_id: str, model: str):
        self.trace_id = trace_id
        self.model = model
        self.rows = 0
        self.device_ms = 0.0
        self.batches = 0
        self.billed = False

    def as_dict(self) -> dict:
        return {"trace_id": self.trace_id, "model": self.model,
                "rows": self.rows,
                "device_ms": round(self.device_ms, 6),
                "batches": self.batches, "billed": self.billed}


class CostLedger:
    """Queryable, bounded, conserving ledger of request device time.

    ``metrics`` (optional duck-typed registry) receives
    ``request_device_ms{model,priority}`` (observed at :meth:`bill` time,
    where the priority is known) and
    ``request_compile_device_ms_total{model}`` (at :meth:`record_batch`
    time — compile seconds go to the model, never a request).
    """

    def __init__(self, metrics=None, *, capacity: int = 4096):
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._requests: "OrderedDict[str, RequestCost]" = OrderedDict()
        self.evicted = 0
        # per-model conservation accumulators
        self._models: Dict[str, dict] = {}
        self._m_device = self._m_compile = None
        if metrics is not None:
            self._m_device = metrics.histogram(
                "request_device_ms",
                "Per-request device milliseconds, row-weighted across the "
                "coalesced batch, compile time excluded",
                ("model", "priority"), buckets=DEVICE_MS_BUCKETS)
            self._m_compile = metrics.counter(
                "request_compile_device_ms_total",
                "Compile/lowering milliseconds attributed to the model "
                "(cold buckets, recompiles) — never billed to a request",
                ("model",))

    def _model(self, model: str) -> dict:
        rec = self._models.get(model)
        if rec is None:
            rec = self._models[model] = {
                "device_ms": 0.0, "compile_ms": 0.0,
                "attributed_device_ms": 0.0, "unattributed_device_ms": 0.0,
                "requests": 0, "batches": 0}
        return rec

    # -------------------------------------------------------------- record
    def record_batch(self, model: str, *, span_ms: float,
                     compile_ms: float = 0.0,
                     requests: Sequence[Tuple[Optional[str], int]] = ()
                     ) -> float:
        """Apportion one finished ``batch_execute`` span.

        ``span_ms`` is the span's full wall duration on the dispatcher
        thread; ``compile_ms`` the compile/lowering time observed inside
        it (excluded from request attribution); ``requests`` the
        coalesced ``(trace_id_or_None, rows)`` pairs.  Returns the
        steady-state device ms apportioned."""
        span_ms = float(span_ms)
        compile_ms = min(float(compile_ms), span_ms)
        device_ms = max(span_ms - compile_ms, 0.0)
        total_rows = sum(max(int(r), 0) for _, r in requests)
        with self._lock:
            rec = self._model(model)
            rec["device_ms"] += device_ms
            rec["compile_ms"] += compile_ms
            rec["batches"] += 1
            for trace_id, rows in requests:
                rows = max(int(rows), 0)
                share = (device_ms * rows / total_rows) if total_rows \
                    else 0.0
                if trace_id is None:
                    rec["unattributed_device_ms"] += share
                    continue
                rc = self._requests.get(trace_id)
                if rc is None:
                    rc = RequestCost(trace_id, model)
                    self._requests[trace_id] = rc
                    rec["requests"] += 1
                    while len(self._requests) > self.capacity:
                        self._requests.popitem(last=False)
                        self.evicted += 1
                rc.rows += rows
                rc.device_ms += share
                rc.batches += 1
                rec["attributed_device_ms"] += share
            if not total_rows:
                # a batch with zero real rows (shouldn't happen) still
                # conserves: its time is unattributed
                rec["unattributed_device_ms"] += device_ms
        if self._m_compile is not None and compile_ms > 0:
            self._m_compile.inc(compile_ms, model=model)
        return device_ms

    # ------------------------------------------------------------- queries
    def device_ms(self, trace_id: Optional[str]) -> Optional[float]:
        """The device ms attributed to one trace so far, or None."""
        if trace_id is None:
            return None
        with self._lock:
            rc = self._requests.get(trace_id)
            return None if rc is None else rc.device_ms

    def bill(self, trace_id: Optional[str], *, model: str,
             priority: str = "1") -> Optional[float]:
        """Close out one request at the serving boundary: observe its
        share into ``request_device_ms{model,priority}`` (once — a
        request retried through ``bill`` twice is only observed on new
        accumulation) and return the ms for the ``X-Device-Ms`` header."""
        if trace_id is None:
            return None
        with self._lock:
            rc = self._requests.get(trace_id)
            if rc is None:
                return None
            first = not rc.billed
            rc.billed = True
            ms = rc.device_ms
        if first and self._m_device is not None:
            self._m_device.observe(ms, model=model, priority=str(priority))
        return ms

    def totals(self, model: Optional[str] = None) -> dict:
        """Conservation-grade totals, per model or summed over all."""
        with self._lock:
            if model is not None:
                return dict(self._model(model))
            out = {"device_ms": 0.0, "compile_ms": 0.0,
                   "attributed_device_ms": 0.0,
                   "unattributed_device_ms": 0.0,
                   "requests": 0, "batches": 0}
            for rec in self._models.values():
                for k in out:
                    out[k] += rec[k]
            return out

    def conservation(self, model: Optional[str] = None,
                     tol: float = 1e-6) -> dict:
        """THE invariant: attributed + unattributed == total device ms.
        Returns ``{"ok": bool, "error_ms": float, ...totals}``."""
        t = self.totals(model)
        err = abs(t["attributed_device_ms"] + t["unattributed_device_ms"]
                  - t["device_ms"])
        t["error_ms"] = err
        t["ok"] = err <= tol + 1e-9 * max(t["device_ms"], 1.0)
        return t

    def recent(self, n: int = 50) -> List[dict]:
        """The newest ``n`` per-request entries (the ``/debug/capture``
        cost slice)."""
        with self._lock:
            items = list(self._requests.values())[-int(n):]
        return [rc.as_dict() for rc in items]

    def describe(self) -> dict:
        """Operator payload: per-model totals + conservation + bounds
        (the ``/v1/models`` cost block)."""
        with self._lock:
            models = {m: dict(rec) for m, rec in self._models.items()}
            tracked = len(self._requests)
        out = {"models": {}, "capacity": self.capacity,
               "tracked_requests": tracked, "evicted_requests": self.evicted}
        for m, rec in models.items():
            rec = {k: (round(v, 6) if isinstance(v, float) else v)
                   for k, v in rec.items()}
            out["models"][m] = rec
        cons = self.conservation()
        out["conservation"] = {"ok": cons["ok"],
                               "error_ms": round(cons["error_ms"], 9)}
        return out
