"""Dependency-free tracing core: spans, context propagation, ring recorder.

The Dapper-style answer to "where did this millisecond go" for the whole
stack: a :class:`Span` is one named, timed interval with attributes; a
:class:`Tracer` creates spans, maintains the current-span context through
``contextvars`` (so nesting works across any same-thread call chain,
including ``http.server`` handler threads), and records completed spans
into a bounded ring-buffer :class:`TraceRecorder`.

Cross-thread handoff is EXPLICIT, matching how the hot paths actually hop
threads: the enqueueing side captures ``tracer.current_context()`` (or the
span's ``.context``), ships it with the work item, and the worker either
passes it as ``parent=`` or records an after-the-fact interval with
:meth:`Tracer.record`. ``contextvars`` intentionally do NOT leak into
``threading.Thread`` targets, so an un-handed-off worker simply starts a
new root — never a wrong parent.

Trace identity follows the W3C Trace Context format so the serving tier can
join a client's timeline across the HTTP boundary:
``traceparent: 00-<32 hex trace-id>-<16 hex span-id>-01``.

Timestamps are ``time.perf_counter_ns()`` (monotonic); the exporter
normalizes to the earliest span, and :data:`EPOCH_ANCHOR` lets consumers
map to wall-clock when they must.
"""

from __future__ import annotations

import contextvars
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

# wall-clock anchor: (perf_counter_ns at import, epoch micros at import)
EPOCH_ANCHOR: Tuple[int, int] = (time.perf_counter_ns(),
                                 int(time.time() * 1e6))


def _new_trace_id() -> str:
    return os.urandom(16).hex()


def _new_span_id() -> str:
    return os.urandom(8).hex()


class SpanContext:
    """The portable identity of a span: what crosses threads and the wire."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def traceparent(self) -> str:
        """W3C ``traceparent`` header value (sampled flag always set)."""
        return f"00-{self.trace_id}-{self.span_id}-01"

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"SpanContext({self.trace_id[:8]}…/{self.span_id})"


def parse_traceparent(header: Optional[str]) -> Optional[SpanContext]:
    """Parse a W3C ``traceparent`` header; ``None`` on anything malformed
    (a bad header must never fail a request — tracing is best-effort)."""
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, _flags = parts
    if len(version) != 2 or version == "ff":
        return None
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    try:
        int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    return SpanContext(trace_id, span_id)


class Span:
    """One named, timed interval. Completed spans are immutable records in
    the recorder; open spans accept attributes and links."""

    __slots__ = ("name", "category", "trace_id", "span_id", "parent_id",
                 "start_ns", "end_ns", "attrs", "links", "thread_id",
                 "thread_name", "error")

    def __init__(self, name: str, *, trace_id: str, span_id: str,
                 parent_id: Optional[str], start_ns: int,
                 category: str = "app",
                 attrs: Optional[Dict[str, Any]] = None):
        self.name = name
        self.category = category
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_ns = start_ns
        self.end_ns: Optional[int] = None
        self.attrs: Dict[str, Any] = dict(attrs) if attrs else {}
        self.links: List[SpanContext] = []
        self.thread_id = threading.get_ident()
        self.thread_name = threading.current_thread().name
        self.error: Optional[str] = None

    # ------------------------------------------------------------- mutation
    def set_attribute(self, key: str, value: Any) -> "Span":
        self.attrs[key] = value
        return self

    def add_link(self, ctx: Optional[SpanContext]) -> "Span":
        """Associate another span (e.g. the HTTP request a batch served)
        without making it a parent — exported as a Chrome flow arrow."""
        if ctx is not None:
            self.links.append(ctx)
        return self

    @property
    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    @property
    def duration_ms(self) -> float:
        end = self.end_ns if self.end_ns is not None else time.perf_counter_ns()
        return (end - self.start_ns) / 1e6

    def __repr__(self):  # pragma: no cover - debugging aid
        return (f"Span({self.name!r}, {self.duration_ms:.3f}ms, "
                f"parent={self.parent_id})")


class TraceRecorder:
    """Bounded ring buffer of completed spans. Appends are O(1) and
    thread-safe; overflow silently drops the OLDEST spans (``dropped``
    counts them) so a long-running server can trace forever and export
    the recent window on demand."""

    def __init__(self, capacity: int = 65536):
        self.capacity = int(capacity)
        self._spans: "deque[Span]" = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._total = 0

    def add(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)
            self._total += 1

    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._total = 0

    @property
    def total_recorded(self) -> int:
        with self._lock:
            return self._total

    @property
    def dropped(self) -> int:
        with self._lock:
            return max(0, self._total - len(self._spans))

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)


# the current span context, per execution context (thread/task)
_current_ctx: "contextvars.ContextVar[Optional[Tuple[str, str]]]" = \
    contextvars.ContextVar("dl4j_tpu_trace_ctx", default=None)


class Tracer:
    """Span factory + context manager + recorder front-end.

    ``metrics`` (optional, an ``observe.metrics.MetricsRegistry``) receives
    the compile-attribution counters the JAX hook emits
    (``jax_compiles_total``, ``jax_compile_seconds_total``).
    """

    def __init__(self, recorder: Optional[TraceRecorder] = None,
                 metrics=None, service: str = "deeplearning4j_tpu"):
        self.recorder = recorder if recorder is not None else TraceRecorder()
        self.metrics = metrics
        self.service = service
        self.compile_count = 0  # xla_compile spans seen (the recompile alarm)
        self._compiles_by_thread: Dict[int, int] = {}
        # per-thread compile+lowering SECONDS (xla_compile AND
        # jax_lowering): the cost plane's exclusion source — a dispatcher
        # thread's delta around a batch is exactly the compile time that
        # batch must not bill to its requests
        self._compile_s_by_thread: Dict[int, float] = {}
        self._compile_lock = threading.Lock()

    # ------------------------------------------------------------- context
    def current_context(self) -> Optional[SpanContext]:
        cur = _current_ctx.get()
        return None if cur is None else SpanContext(*cur)

    def current_traceparent(self) -> Optional[str]:
        ctx = self.current_context()
        return None if ctx is None else ctx.traceparent()

    # --------------------------------------------------------------- spans
    @contextmanager
    def span(self, name: str, *, parent: Optional[SpanContext] = None,
             category: str = "app", attrs: Optional[Dict[str, Any]] = None
             ) -> Iterator[Span]:
        """Open a span as the current context; on exit it is timed, closed
        and recorded — even when the body raises (the error is noted on the
        span, then propagates)."""
        sp = self.start_span(name, parent=parent, category=category,
                             attrs=attrs)
        token = _current_ctx.set((sp.trace_id, sp.span_id))
        try:
            yield sp
        except BaseException as e:
            sp.error = f"{type(e).__name__}: {e}"
            raise
        finally:
            _current_ctx.reset(token)
            self.end_span(sp)

    def start_span(self, name: str, *, parent: Optional[SpanContext] = None,
                   category: str = "app",
                   attrs: Optional[Dict[str, Any]] = None) -> Span:
        """Manual span start (pair with :meth:`end_span`). Does NOT set the
        current context — use :meth:`span` for that."""
        if parent is None:
            parent = self.current_context()
        if parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id
        else:
            trace_id, parent_id = _new_trace_id(), None
        return Span(name, trace_id=trace_id, span_id=_new_span_id(),
                    parent_id=parent_id, start_ns=time.perf_counter_ns(),
                    category=category, attrs=attrs)

    def end_span(self, span: Span) -> None:
        if span.end_ns is None:
            span.end_ns = time.perf_counter_ns()
            self.recorder.add(span)

    def record(self, name: str, start_ns: int, end_ns: int, *,
               parent: Optional[SpanContext] = None, category: str = "app",
               attrs: Optional[Dict[str, Any]] = None,
               links: Sequence[SpanContext] = ()) -> Span:
        """Record an interval measured elsewhere as a completed span — the
        after-the-fact form every cross-thread site uses (queue waits,
        compile durations, per-iteration listener windows)."""
        if parent is None:
            parent = self.current_context()
        if parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id
        else:
            trace_id, parent_id = _new_trace_id(), None
        sp = Span(name, trace_id=trace_id, span_id=_new_span_id(),
                  parent_id=parent_id, start_ns=int(start_ns),
                  category=category, attrs=attrs)
        for l in links:
            sp.add_link(l)
        sp.end_ns = int(end_ns)
        self.recorder.add(sp)
        return sp

    # -------------------------------------------- compile attribution sink
    def note_compile_event(self, span_name: str, duration_s: float) -> None:
        """Sink for the JAX monitoring hook (``observe.jaxhook``): records
        the just-finished lowering/compile as a span under whatever context
        is current on THIS thread — a recompile inside ``train_step`` or a
        new batch bucket inside ``batch_execute`` nests exactly where it
        happened and shows up loudly."""
        now = time.perf_counter_ns()
        self.record(span_name, now - int(duration_s * 1e9), now,
                    category="compile")
        tid = threading.get_ident()
        with self._compile_lock:
            self._compile_s_by_thread[tid] = \
                self._compile_s_by_thread.get(tid, 0.0) + float(duration_s)
        if span_name == "xla_compile":
            with self._compile_lock:
                self.compile_count += 1
                self._compiles_by_thread[tid] = \
                    self._compiles_by_thread.get(tid, 0) + 1
            if self.metrics is not None:
                self.metrics.counter(
                    "jax_compiles_total",
                    "XLA backend compilations observed by the tracer").inc()
                self.metrics.counter(
                    "jax_compile_seconds_total",
                    "Cumulative XLA backend compile time").inc(duration_s)

    def thread_compile_count(self, thread_id: Optional[int] = None) -> int:
        """Compiles triggered on one thread (default: the calling thread) —
        the attribution a training listener wants: a serving dispatcher
        compiling a new batch bucket on ITS thread must not count against
        training running elsewhere in the process."""
        tid = thread_id if thread_id is not None else threading.get_ident()
        with self._compile_lock:
            return self._compiles_by_thread.get(tid, 0)

    def thread_compile_seconds(self,
                               thread_id: Optional[int] = None) -> float:
        """Cumulative ``xla_compile`` + ``jax_lowering`` seconds observed
        on one thread (default: the calling thread). The request-cost
        plane brackets each coalesced batch with this counter so a cold
        bucket's compile never bills to the requests that triggered it."""
        tid = thread_id if thread_id is not None else threading.get_ident()
        with self._compile_lock:
            return self._compile_s_by_thread.get(tid, 0.0)

    # -------------------------------------------------------------- export
    def chrome_trace(self) -> dict:
        from deeplearning4j_tpu.observe.export import to_chrome_trace
        return to_chrome_trace(self.recorder.spans(), service=self.service)

    def write_chrome_trace(self, path) -> None:
        from deeplearning4j_tpu.observe.export import write_chrome_trace
        write_chrome_trace(path, self.recorder.spans(), service=self.service)

    def flush(self, path) -> int:
        """Write the Chrome trace to ``path`` and return the span count —
        the one-call form every CLI/bench exit path uses."""
        self.write_chrome_trace(path)
        return len(self.recorder)

    def timeline(self, **kw) -> str:
        from deeplearning4j_tpu.observe.export import text_timeline
        return text_timeline(self.recorder.spans(), **kw)


# ---------------------------------------------------------------------------
# process-wide activation: instrumented hot paths are zero-overhead no-ops
# until a tracer is enabled (one `is None` check per site)
# ---------------------------------------------------------------------------

_active_tracer: Optional[Tracer] = None
_active_lock = threading.Lock()


def get_active_tracer() -> Optional[Tracer]:
    return _active_tracer


def enable_tracing(tracer: Optional[Tracer] = None, *, metrics=None,
                   capacity: int = 65536, jax_hook: bool = True) -> Tracer:
    """Install ``tracer`` (or a fresh one) as the process-wide active tracer
    and (by default) hook JAX compile/lowering events into it. Returns the
    active tracer. Idempotent per tracer; a second call swaps the tracer."""
    global _active_tracer
    with _active_lock:
        if tracer is None:
            tracer = Tracer(TraceRecorder(capacity), metrics=metrics)
        elif tracer.metrics is None and metrics is not None:
            tracer.metrics = metrics  # honor metrics= for explicit tracers
        _active_tracer = tracer
    if jax_hook:
        from deeplearning4j_tpu.observe.jaxhook import install_jax_hook
        install_jax_hook()
    return tracer


def disable_tracing() -> None:
    """Deactivate tracing; every instrumented site reverts to a no-op.
    (The JAX monitoring listener stays registered — it is itself a no-op
    without an active tracer; ``jax.monitoring`` has no single-listener
    removal.)"""
    global _active_tracer
    with _active_lock:
        _active_tracer = None


@contextmanager
def span(name: str, *, parent: Optional[SpanContext] = None,
         category: str = "app",
         attrs: Optional[Dict[str, Any]] = None) -> Iterator[Optional[Span]]:
    """Module-level convenience: a span on the ACTIVE tracer, or a no-op
    (yielding ``None``) when tracing is off — the form the instrumented
    hot paths use."""
    tr = _active_tracer
    if tr is None:
        yield None
        return
    with tr.span(name, parent=parent, category=category, attrs=attrs) as sp:
        yield sp


def current_traceparent() -> Optional[str]:
    """The active context's W3C header value, or None (off / no open span)."""
    tr = _active_tracer
    return None if tr is None else tr.current_traceparent()


def current_span_ids() -> Tuple[Optional[str], Optional[str]]:
    """``(trace_id, span_id)`` of the span open on THIS execution
    context, or ``(None, None)``. Reads the shared contextvar directly —
    the ids are tracer-independent, so correlation stampers (log
    records, pipeline journal lines) work for explicitly-passed tracers
    too, not just the process-wide active one."""
    cur = _current_ctx.get()
    return (None, None) if cur is None else cur
