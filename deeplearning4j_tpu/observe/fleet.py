"""Fleet observability: cross-process metrics federation + span streaming.

Rounds 7-8 built a deep observability stack — and left it strictly
single-process, while the failures that matter (elastic shrink, host
kills, DCN partitions) are multi-process. This module is the operator
plane that spans the JOB instead of the process:

- :class:`MetricsFileExporter` — the worker side of federation: writes
  the registry's Prometheus exposition atomically to a snapshot file
  next to the worker's heartbeat file. Deliberately file-based (not a
  scrape socket): deterministic in CI, crash-durable up to the last
  completed iteration, and the supervisor already owns the directory.
- :class:`FleetRegistry` — the supervisor side: merges every worker
  snapshot through ``parse_prometheus_text`` (the established exposition
  contract), re-labels each series with ``{slot,host,generation}`` under
  a cardinality bound, and serves the union from :meth:`exposition` —
  duck-typing the ``MetricsRegistry`` surface the existing
  :class:`~deeplearning4j_tpu.observe.alerts.AlertManager` and
  ``/metrics`` handlers consume, so burn-rate rules can watch the whole
  job unchanged.
- :class:`FleetMetricsServer` — a minimal HTTP front-end (``/metrics``,
  ``/healthz``, ``/alerts``) for supervisor processes, reusing the
  ModelServer's response plumbing (``observe.metrics.respond``).
- :class:`SpanFileWriter` / :func:`read_span_file` — crash-durable trace
  streaming: a ``TraceRecorder`` drop-in that ALSO appends every
  completed span as one JSON line, so a SIGKILLed worker keeps every
  span up to its last finished iteration.  The file opens with a meta
  line carrying the process's ``EPOCH_ANCHOR`` — the clock-alignment
  rule ``observe.export.merge_chrome_traces`` uses to put every
  process's monotonic timestamps on one wall-clock timeline.
- :class:`TailSampler` — Dapper-style tail-based sampling between the
  recorder and any span sink: complete traces persist only when slow,
  errored, exemplar-referenced, or alert-flagged (plus a deterministic
  probabilistic floor), under a bounded disk budget with drop
  accounting — always-on tracing at always-affordable cost.

Federation preserves exemplars: a worker's ``# {trace_id="..."}``
histogram annotations survive the parse → re-label → re-render cycle, so
a p99 bucket on the SUPERVISOR's ``/metrics`` still names the worker
trace that caused it.

Everything here follows the ``enable_tracing()`` discipline: a worker
without the supervisor's env vars, or a supervisor without a fleet
registry, pays a single ``is None`` check.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

from deeplearning4j_tpu.observe.metrics import (MetricsRegistry,
                                                _format_value, _label_str,
                                                exemplar_trace_ids,
                                                format_exemplar,
                                                parse_prometheus_text)
from deeplearning4j_tpu.observe.trace import EPOCH_ANCHOR, Span, TraceRecorder
from deeplearning4j_tpu.util.fsio import atomic_write_text

#: labels the federation owns; a worker-side label with the same name is
#: overwritten (the supervisor's placement assignment is authoritative)
FEDERATION_LABELS = ("slot", "host", "generation")


class MetricsFileExporter:
    """Worker-side federation endpoint: write the registry's exposition
    text atomically to ``path`` (tmp + ``os.replace``, the heartbeat
    discipline — the supervisor never reads a torn snapshot). Export
    errors are swallowed: a full disk must not fail a training step."""

    def __init__(self, registry: MetricsRegistry, path: str):
        self.registry = registry
        self.path = str(path)
        self.exports = 0
        self.errors = 0
        self._lock = threading.Lock()

    def export(self) -> bool:
        with self._lock:
            try:
                atomic_write_text(self.path, self.registry.exposition())
                self.exports += 1
                return True
            except OSError:
                self.errors += 1
                return False


class FleetRegistry:
    """Supervisor-side union of a local registry and N worker snapshots.

    Duck-types the ``MetricsRegistry`` surface its consumers use
    (``counter``/``gauge``/``histogram``/``get``/``exposition``):
    instruments delegate to the LOCAL registry (where the supervisor's
    own ``elastic_*`` series and the AlertManager's state live);
    :meth:`exposition` appends the re-labeled union of every registered
    source, so ``AlertManager(metrics=fleet)`` and a ``/metrics``
    handler see one job-wide exposition.

    Federated series are re-labeled with the source's
    ``{slot,host,generation}`` assignment and capped at ``max_series``
    total (cardinality bound); drops and scrape failures are themselves
    exported (``fleet_federation_dropped_series_total`` /
    ``fleet_federation_scrape_errors_total``) — silent truncation would
    read as "all quiet".
    """

    def __init__(self, local: Optional[MetricsRegistry] = None, *,
                 max_series: int = 2000):
        self.local = local if local is not None else MetricsRegistry()
        self.max_series = int(max_series)
        self._sources: Dict[Any, Tuple[str, Dict[str, str]]] = {}
        self._lock = threading.Lock()
        self._m_sources = self.local.gauge(
            "fleet_sources", "Worker metric snapshots federated")
        self._m_dropped = self.local.counter(
            "fleet_federation_dropped_series_total",
            "Federated series dropped by the cardinality bound")
        self._m_errors = self.local.counter(
            "fleet_federation_scrape_errors_total",
            "Worker snapshot files that could not be read/parsed")

    # ------------------------------------------------------------- sources
    def set_source(self, key: Any, path: str,
                   labels: Dict[str, Any]) -> None:
        """Register (or update) one worker snapshot file under ``key``
        (the slot id); ``labels`` is the federation's label assignment
        (slot/host/generation)."""
        with self._lock:
            self._sources[key] = (str(path),
                                  {str(k): str(v) for k, v in labels.items()})
            self._m_sources.set(len(self._sources))

    def remove_source(self, key: Any) -> None:
        with self._lock:
            self._sources.pop(key, None)
            self._m_sources.set(len(self._sources))

    def clear_sources(self) -> None:
        with self._lock:
            self._sources.clear()
            self._m_sources.set(0)

    def sources(self) -> Dict[Any, Tuple[str, Dict[str, str]]]:
        with self._lock:
            return dict(self._sources)

    # --------------------------------------------------- instrument surface
    def counter(self, *a, **kw):
        return self.local.counter(*a, **kw)

    def gauge(self, *a, **kw):
        return self.local.gauge(*a, **kw)

    def histogram(self, *a, **kw):
        return self.local.histogram(*a, **kw)

    def get(self, name: str):
        return self.local.get(name)

    # ----------------------------------------------------------- exposition
    def federated_lines(self) -> List[str]:
        """The re-labeled union of every source, one sample line per
        series, capped at ``max_series``. Untyped on purpose: the HELP/
        TYPE headers belong to the writer; ``parse_prometheus_text``
        (the consumer contract) ignores them either way."""
        lines: List[str] = []
        dropped = 0
        errors = 0
        snapshot = self.sources()
        for key in sorted(snapshot, key=str):
            path, fed_labels = snapshot[key]
            try:
                with open(path, encoding="utf-8") as fh:
                    sample = parse_prometheus_text(fh.read())
            except FileNotFoundError:
                # a registered-but-not-yet-written snapshot (the
                # supervisor pre-unlinks it at launch; the worker's
                # first export lands only after jax init) is a normal
                # boot window, not a scrape failure
                continue
            except (OSError, ValueError, AssertionError, IndexError):
                errors += 1
                continue
            exemplars = getattr(sample, "exemplars", {})
            for name in sorted(sample):
                for label_key in sorted(sample[name]):
                    if len(lines) >= self.max_series:
                        dropped += 1
                        continue
                    merged = dict(label_key)
                    merged.update(fed_labels)  # federation labels win
                    pairs = sorted(merged.items())
                    line = (f"{name}{_label_str((), (), extra=pairs)} "
                            f"{_format_value(sample[name][label_key])}")
                    # exemplars ride along under the SAME cardinality
                    # bound (an annotation on a kept series, never an
                    # extra series): the supervisor's p99 bucket keeps
                    # naming the worker trace that caused it
                    ex = exemplars.get((name, label_key))
                    if ex is not None:
                        line += " " + format_exemplar(ex)
                    lines.append(line)
        if dropped:
            self._m_dropped.inc(dropped)
        if errors:
            self._m_errors.inc(errors)
        return lines

    def exposition(self) -> str:
        text = self.local.exposition()
        fed = self.federated_lines()
        if fed:
            text += "\n".join(fed) + "\n"
        return text


class FleetMetricsServer:
    """Minimal observability front-end for supervisor processes: GET
    ``/metrics`` (the :class:`FleetRegistry` union, Prometheus text),
    ``/healthz``, ``/alerts`` when an ``AlertManager`` is attached, and
    ``/slo`` when an :class:`~.slo.SLOSet` is attached — the
    ModelServer's HTTP plumbing without the model surface."""

    def __init__(self, registry, *, host: str = "127.0.0.1", port: int = 0,
                 alerts=None, slo=None):
        self.registry = registry
        self.host = host
        self.port = int(port)
        self.alerts = alerts
        self.slo = slo
        self._httpd = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        """Bind (port 0 → ephemeral) and serve on a daemon thread;
        returns the bound port."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from deeplearning4j_tpu.observe.metrics import respond, respond_json
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # silence
                pass

            def do_GET(self):
                from urllib.parse import urlparse
                path = urlparse(self.path).path
                if path == "/metrics":
                    respond(self, 200,
                            server.registry.exposition().encode(),
                            "text/plain; version=0.0.4")
                elif path == "/healthz":
                    respond_json(self, {"status": "ok"})
                elif path == "/alerts":
                    if server.alerts is None:
                        respond_json(self,
                                     {"error": "no alert manager attached"},
                                     404)
                    else:
                        respond_json(self, server.alerts.describe())
                elif path == "/slo":
                    if server.slo is None:
                        respond_json(self,
                                     {"error": "no slo config attached"},
                                     404)
                    else:
                        respond_json(self, server.slo.status(
                            metrics=server.registry,
                            alerts=server.alerts))
                else:
                    respond_json(self, {"error": "not found"}, 404)

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="fleet-metrics-server")
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def url(self) -> str:
        return f"http://{self.host}:{self.port}"


# ---------------------------------------------------------------------------
# crash-durable span streaming
# ---------------------------------------------------------------------------

# the ONE attr sanitization rule, shared with every exporter
from deeplearning4j_tpu.observe.export import sanitize_attr as _safe_attr


class SpanFileWriter(TraceRecorder):
    """A :class:`TraceRecorder` drop-in that ALSO appends every completed
    span as one JSON line to ``path`` — crash-durable: a SIGKILLed worker
    keeps every span up to its last finished iteration, which is exactly
    what the incident bundle and the merged fleet trace need from a
    victim.  The first line is a ``meta`` record carrying the process's
    monotonic↔epoch anchor (``observe.trace.EPOCH_ANCHOR``), the
    clock-alignment datum :func:`read_span_file` hands to
    ``merge_chrome_traces``.  A dead stream (disk full) detaches; the
    in-memory ring keeps recording (the ``LogHub`` contract).

    The file is TRUNCATED on open: one stream = one process = one
    anchor. A re-run supervisor re-using the same checkpoint dir (and
    therefore the same per-generation filenames) must not leave a stale
    process's spans under a fresh anchor — the merge rule is that a
    mis-aligned row is worse than a missing one."""

    def __init__(self, path: str, *, label: str, capacity: int = 65536,
                 extra_meta: Optional[Dict[str, Any]] = None):
        super().__init__(capacity)
        self.path = str(path)
        self.label = label
        self._file_lock = threading.Lock()
        self._fh = open(self.path, "w", encoding="utf-8")
        meta: Dict[str, Any] = {
            "kind": "meta", "label": label, "pid": os.getpid(),
            "anchor_perf_ns": EPOCH_ANCHOR[0],
            "anchor_epoch_us": EPOCH_ANCHOR[1],
        }
        if extra_meta:
            meta.update({str(k): _safe_attr(v)
                         for k, v in extra_meta.items()})
        self._write_line(meta)

    def add(self, span: Span) -> None:
        super().add(span)
        rec: Dict[str, Any] = {
            "kind": "span", "name": span.name, "cat": span.category,
            "trace": span.trace_id, "span": span.span_id,
            "parent": span.parent_id, "start_ns": span.start_ns,
            "end_ns": span.end_ns, "tid": span.thread_id,
            "tname": span.thread_name,
        }
        if span.attrs:
            rec["attrs"] = {str(k): _safe_attr(v)
                            for k, v in span.attrs.items()}
        if span.error:
            rec["error"] = span.error
        if span.links:
            rec["links"] = [{"trace": l.trace_id, "span": l.span_id}
                            for l in span.links]
        self._write_line(rec)

    def _write_line(self, obj: Dict[str, Any]) -> None:
        with self._file_lock:
            fh = self._fh
            if fh is None:
                return
            try:
                fh.write(json.dumps(obj) + "\n")
                fh.flush()
            except Exception:  # noqa: BLE001 - a dead stream must never
                # raise into an instrumented hot path; the ring records on
                self._fh = None
                try:
                    fh.close()
                except Exception:  # noqa: BLE001
                    pass

    def close(self) -> None:
        with self._file_lock:
            fh, self._fh = self._fh, None
            if fh is not None:
                fh.close()


class TailSampler(TraceRecorder):
    """Tail-based trace sampling at the recorder/sink seam.

    Sits where a :class:`SpanFileWriter` (or any ``add(span)`` sink)
    would: install it as the tracer's recorder and every completed span
    still lands in the in-memory ring (``super().add``) — the on-demand
    capture window keeps working — but the SINK only receives COMPLETE
    traces that earn their disk.  The decision runs when a trace's local
    root ends (a span with no parent, or one whose name is a configured
    root kind — a server whose root carries a remote ``traceparent``
    parent names ``http_request`` in ``slow_ms``), first match wins:

    ==========  ======================================================
    keep        predicate
    ==========  ======================================================
    error       any span in the trace carries ``error``
    slow        root duration >= ``slow_ms[root.name]``
                (else ``default_slow_ms``) milliseconds
    exemplar    the trace_id is referenced by a histogram exemplar in
                ``exemplar_source`` (a registry or a callable → set)
    alert       the attached ``AlertManager`` has any rule firing
    floor       deterministic probabilistic floor:
                ``int(trace_id[:8], 16) / 0xFFFFFFFF < probability``
    ==========  ======================================================

    Everything else drops.  Kept traces spend a bounded disk budget
    (``max_bytes``, estimated per span) — once exhausted, even keepers
    drop (counted separately: a full disk silently masquerading as "no
    slow traces" would be the worst lie).  Unfinished traces buffer up
    to ``max_pending`` before the oldest is evicted (a crashed client
    that never closes its root must not pin memory forever).  Every
    outcome is counted; :meth:`describe` is the accounting surface the
    bench commits."""

    def __init__(self, sink=None, *, slow_ms: Optional[Dict[str, float]]
                 = None, default_slow_ms: float = 250.0,
                 probability: float = 0.0,
                 max_bytes: int = 8 * 1024 * 1024,
                 max_pending: int = 512, capacity: int = 65536,
                 exemplar_source=None, alerts=None, metrics=None):
        super().__init__(capacity)
        if not 0.0 <= float(probability) <= 1.0:
            raise ValueError("probability must be in [0, 1]")
        self.sink = sink
        self.slow_ms = dict(slow_ms or {})
        self.default_slow_ms = float(default_slow_ms)
        self.probability = float(probability)
        self.max_bytes = int(max_bytes)
        self.max_pending = int(max_pending)
        self.exemplar_source = exemplar_source
        self.alerts = alerts
        self._ts_lock = threading.Lock()
        self._pending: "Dict[str, List[Span]]" = {}
        self._decided: "Dict[str, bool]" = {}
        self._decided_cap = 4096
        self.kept_traces = 0
        self.kept_spans = 0
        self.dropped_traces = 0
        self.dropped_spans = 0
        self.dropped_budget_traces = 0
        self.dropped_pending_traces = 0
        self.bytes_written = 0
        self.keep_reasons: Dict[str, int] = {}
        self._m_traces = None
        if metrics is not None:
            self._m_traces = metrics.counter(
                "trace_tail_traces_total",
                "Tail-sampling decisions by outcome",
                ("decision",))

    # ----------------------------------------------------------- recording
    def add(self, span: Span) -> None:
        super().add(span)          # the ring always records
        if self.sink is None:
            return
        trace_id = span.trace_id
        with self._ts_lock:
            verdict = self._decided.get(trace_id)
            if verdict is not None:
                # late arrival on an already-decided trace follows it
                if verdict:
                    self._emit_locked([span])
                else:
                    self.dropped_spans += 1
                return
            buf = self._pending.setdefault(trace_id, [])
            buf.append(span)
            if not (span.parent_id is None or span.name in self.slow_ms):
                self._evict_pending_locked()
                return
            spans = self._pending.pop(trace_id)
        # the keep predicates read OTHER subsystems (registry locks,
        # the alert manager's lock) — never under our own lock
        keep, reason = self._decide(span, spans)
        with self._ts_lock:
            self._remember_locked(trace_id, keep)
            if not keep:
                self.dropped_traces += 1
                self.dropped_spans += len(spans)
            else:
                est = sum(self._span_bytes(s) for s in spans)
                if self.bytes_written + est > self.max_bytes:
                    self._remember_locked(trace_id, False)
                    self.dropped_budget_traces += 1
                    self.dropped_traces += 1
                    self.dropped_spans += len(spans)
                    reason = "drop_budget"
                    keep = False
                else:
                    self.kept_traces += 1
                    self.keep_reasons[reason] = \
                        self.keep_reasons.get(reason, 0) + 1
                    self._emit_locked(spans)
        if self._m_traces is not None:
            # reason is the keep reason, "drop", or "drop_budget"
            self._m_traces.inc(decision=reason)

    # ----------------------------------------------------------- decisions
    def _decide(self, root: Span, spans: List[Span]) -> Tuple[bool, str]:
        if any(s.error for s in spans):
            return True, "error"
        end_ns = root.end_ns if root.end_ns is not None else root.start_ns
        dur_ms = max(end_ns - root.start_ns, 0) / 1e6
        if dur_ms >= self.slow_ms.get(root.name, self.default_slow_ms):
            return True, "slow"
        if root.trace_id in self._exemplar_ids():
            return True, "exemplar"
        if self.alerts is not None and self.alerts.firing():
            return True, "alert"
        if self.probability > 0.0 and self._floor_hit(root.trace_id):
            return True, "floor"
        return False, "drop"

    def _exemplar_ids(self) -> set:
        src = self.exemplar_source
        if src is None:
            return set()
        try:
            if callable(src):
                return set(src())
            return exemplar_trace_ids(src)
        except Exception:  # noqa: BLE001 - sampling must never raise
            return set()

    def _floor_hit(self, trace_id: str) -> bool:
        try:
            return int(trace_id[:8], 16) / 0xFFFFFFFF < self.probability
        except (ValueError, IndexError):
            return False

    # ------------------------------------------------------------ plumbing
    def _remember_locked(self, trace_id: str, keep: bool) -> None:
        self._decided[trace_id] = keep
        while len(self._decided) > self._decided_cap:
            self._decided.pop(next(iter(self._decided)))

    def _evict_pending_locked(self) -> None:
        while len(self._pending) > self.max_pending:
            tid = next(iter(self._pending))
            spans = self._pending.pop(tid)
            self._remember_locked(tid, False)
            self.dropped_pending_traces += 1
            self.dropped_traces += 1
            self.dropped_spans += len(spans)

    @staticmethod
    def _span_bytes(span: Span) -> int:
        # the JSON-line estimate (ids + fixed fields + attrs); cheap on
        # purpose — the budget bounds disk, it does not meter it
        n = 160 + len(span.name) + len(span.trace_id) + len(span.span_id)
        for k, v in (span.attrs or {}).items():
            n += len(str(k)) + len(str(v)) + 8
        return n

    def _emit_locked(self, spans: List[Span]) -> None:
        for s in spans:
            self.bytes_written += self._span_bytes(s)
            self.kept_spans += 1
            try:
                self.sink.add(s)
            except Exception:  # noqa: BLE001 - a dead sink must not
                pass           # raise into the instrumented hot path

    def flush_trace(self, trace_id: str) -> bool:
        """Force-keep one buffered trace (the on-demand capture's
        escape hatch for a trace the policy would drop)."""
        with self._ts_lock:
            spans = self._pending.pop(trace_id, None)
            if spans is None:
                return False
            self._remember_locked(trace_id, True)
            self.kept_traces += 1
            self.keep_reasons["forced"] = \
                self.keep_reasons.get("forced", 0) + 1
            self._emit_locked(spans)
            return True

    def describe(self) -> Dict[str, Any]:
        with self._ts_lock:
            return {
                "kept_traces": self.kept_traces,
                "kept_spans": self.kept_spans,
                "dropped_traces": self.dropped_traces,
                "dropped_spans": self.dropped_spans,
                "dropped_budget_traces": self.dropped_budget_traces,
                "dropped_pending_traces": self.dropped_pending_traces,
                "pending_traces": len(self._pending),
                "bytes_written": self.bytes_written,
                "max_bytes": self.max_bytes,
                "probability": self.probability,
                "default_slow_ms": self.default_slow_ms,
                "slow_ms": dict(self.slow_ms),
                "keep_reasons": dict(self.keep_reasons),
            }

    def close(self) -> None:
        """Drop undecided traces (they are incomplete by definition) and
        close the sink when it can be closed."""
        with self._ts_lock:
            for tid, spans in list(self._pending.items()):
                self.dropped_pending_traces += 1
                self.dropped_traces += 1
                self.dropped_spans += len(spans)
            self._pending.clear()
        if hasattr(self.sink, "close"):
            self.sink.close()


def read_span_file(path: str) -> Dict[str, Any]:
    """Parse one :class:`SpanFileWriter` output file:
    ``{"label", "pid", "anchor": (perf_ns, epoch_us), "spans": [dict]}``.
    Torn final lines (the writer was SIGKILLed mid-write) and unparseable
    lines are skipped — the surviving spans are the point."""
    out: Dict[str, Any] = {"label": os.path.basename(path), "pid": None,
                           "anchor": None, "meta": {}, "spans": []}
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            if not line.endswith("\n"):
                continue  # torn tail: that span never fully landed
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if not isinstance(rec, dict):
                continue
            kind = rec.get("kind")
            if kind == "meta":
                if out["anchor"] is not None:
                    # defense in depth: the writer truncates on open, so
                    # a second meta line means two processes wrote one
                    # file — only the FIRST anchor can align the spans
                    # that follow it; keep it
                    continue
                out["label"] = rec.get("label", out["label"])
                out["pid"] = rec.get("pid")
                out["meta"] = {k: v for k, v in rec.items()
                               if k not in ("kind",)}
                try:
                    out["anchor"] = (int(rec["anchor_perf_ns"]),
                                     int(rec["anchor_epoch_us"]))
                except (KeyError, TypeError, ValueError):
                    pass
            elif kind == "span":
                if not isinstance(rec.get("start_ns"), int) \
                        or not isinstance(rec.get("end_ns"), int):
                    continue
                out["spans"].append(rec)
    return out
