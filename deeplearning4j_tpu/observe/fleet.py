"""Fleet observability: cross-process metrics federation + span streaming.

Rounds 7-8 built a deep observability stack — and left it strictly
single-process, while the failures that matter (elastic shrink, host
kills, DCN partitions) are multi-process. This module is the operator
plane that spans the JOB instead of the process:

- :class:`MetricsFileExporter` — the worker side of federation: writes
  the registry's Prometheus exposition atomically to a snapshot file
  next to the worker's heartbeat file. Deliberately file-based (not a
  scrape socket): deterministic in CI, crash-durable up to the last
  completed iteration, and the supervisor already owns the directory.
- :class:`FleetRegistry` — the supervisor side: merges every worker
  snapshot through ``parse_prometheus_text`` (the established exposition
  contract), re-labels each series with ``{slot,host,generation}`` under
  a cardinality bound, and serves the union from :meth:`exposition` —
  duck-typing the ``MetricsRegistry`` surface the existing
  :class:`~deeplearning4j_tpu.observe.alerts.AlertManager` and
  ``/metrics`` handlers consume, so burn-rate rules can watch the whole
  job unchanged.
- :class:`FleetMetricsServer` — a minimal HTTP front-end (``/metrics``,
  ``/healthz``, ``/alerts``) for supervisor processes, reusing the
  ModelServer's response plumbing (``observe.metrics.respond``).
- :class:`SpanFileWriter` / :func:`read_span_file` — crash-durable trace
  streaming: a ``TraceRecorder`` drop-in that ALSO appends every
  completed span as one JSON line, so a SIGKILLed worker keeps every
  span up to its last finished iteration.  The file opens with a meta
  line carrying the process's ``EPOCH_ANCHOR`` — the clock-alignment
  rule ``observe.export.merge_chrome_traces`` uses to put every
  process's monotonic timestamps on one wall-clock timeline.

Everything here follows the ``enable_tracing()`` discipline: a worker
without the supervisor's env vars, or a supervisor without a fleet
registry, pays a single ``is None`` check.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

from deeplearning4j_tpu.observe.metrics import (MetricsRegistry,
                                                _format_value, _label_str,
                                                parse_prometheus_text)
from deeplearning4j_tpu.observe.trace import EPOCH_ANCHOR, Span, TraceRecorder
from deeplearning4j_tpu.util.fsio import atomic_write_text

#: labels the federation owns; a worker-side label with the same name is
#: overwritten (the supervisor's placement assignment is authoritative)
FEDERATION_LABELS = ("slot", "host", "generation")


class MetricsFileExporter:
    """Worker-side federation endpoint: write the registry's exposition
    text atomically to ``path`` (tmp + ``os.replace``, the heartbeat
    discipline — the supervisor never reads a torn snapshot). Export
    errors are swallowed: a full disk must not fail a training step."""

    def __init__(self, registry: MetricsRegistry, path: str):
        self.registry = registry
        self.path = str(path)
        self.exports = 0
        self.errors = 0
        self._lock = threading.Lock()

    def export(self) -> bool:
        with self._lock:
            try:
                atomic_write_text(self.path, self.registry.exposition())
                self.exports += 1
                return True
            except OSError:
                self.errors += 1
                return False


class FleetRegistry:
    """Supervisor-side union of a local registry and N worker snapshots.

    Duck-types the ``MetricsRegistry`` surface its consumers use
    (``counter``/``gauge``/``histogram``/``get``/``exposition``):
    instruments delegate to the LOCAL registry (where the supervisor's
    own ``elastic_*`` series and the AlertManager's state live);
    :meth:`exposition` appends the re-labeled union of every registered
    source, so ``AlertManager(metrics=fleet)`` and a ``/metrics``
    handler see one job-wide exposition.

    Federated series are re-labeled with the source's
    ``{slot,host,generation}`` assignment and capped at ``max_series``
    total (cardinality bound); drops and scrape failures are themselves
    exported (``fleet_federation_dropped_series_total`` /
    ``fleet_federation_scrape_errors_total``) — silent truncation would
    read as "all quiet".
    """

    def __init__(self, local: Optional[MetricsRegistry] = None, *,
                 max_series: int = 2000):
        self.local = local if local is not None else MetricsRegistry()
        self.max_series = int(max_series)
        self._sources: Dict[Any, Tuple[str, Dict[str, str]]] = {}
        self._lock = threading.Lock()
        self._m_sources = self.local.gauge(
            "fleet_sources", "Worker metric snapshots federated")
        self._m_dropped = self.local.counter(
            "fleet_federation_dropped_series_total",
            "Federated series dropped by the cardinality bound")
        self._m_errors = self.local.counter(
            "fleet_federation_scrape_errors_total",
            "Worker snapshot files that could not be read/parsed")

    # ------------------------------------------------------------- sources
    def set_source(self, key: Any, path: str,
                   labels: Dict[str, Any]) -> None:
        """Register (or update) one worker snapshot file under ``key``
        (the slot id); ``labels`` is the federation's label assignment
        (slot/host/generation)."""
        with self._lock:
            self._sources[key] = (str(path),
                                  {str(k): str(v) for k, v in labels.items()})
            self._m_sources.set(len(self._sources))

    def remove_source(self, key: Any) -> None:
        with self._lock:
            self._sources.pop(key, None)
            self._m_sources.set(len(self._sources))

    def clear_sources(self) -> None:
        with self._lock:
            self._sources.clear()
            self._m_sources.set(0)

    def sources(self) -> Dict[Any, Tuple[str, Dict[str, str]]]:
        with self._lock:
            return dict(self._sources)

    # --------------------------------------------------- instrument surface
    def counter(self, *a, **kw):
        return self.local.counter(*a, **kw)

    def gauge(self, *a, **kw):
        return self.local.gauge(*a, **kw)

    def histogram(self, *a, **kw):
        return self.local.histogram(*a, **kw)

    def get(self, name: str):
        return self.local.get(name)

    # ----------------------------------------------------------- exposition
    def federated_lines(self) -> List[str]:
        """The re-labeled union of every source, one sample line per
        series, capped at ``max_series``. Untyped on purpose: the HELP/
        TYPE headers belong to the writer; ``parse_prometheus_text``
        (the consumer contract) ignores them either way."""
        lines: List[str] = []
        dropped = 0
        errors = 0
        snapshot = self.sources()
        for key in sorted(snapshot, key=str):
            path, fed_labels = snapshot[key]
            try:
                with open(path, encoding="utf-8") as fh:
                    sample = parse_prometheus_text(fh.read())
            except FileNotFoundError:
                # a registered-but-not-yet-written snapshot (the
                # supervisor pre-unlinks it at launch; the worker's
                # first export lands only after jax init) is a normal
                # boot window, not a scrape failure
                continue
            except (OSError, ValueError, AssertionError, IndexError):
                errors += 1
                continue
            for name in sorted(sample):
                for label_key in sorted(sample[name]):
                    if len(lines) >= self.max_series:
                        dropped += 1
                        continue
                    merged = dict(label_key)
                    merged.update(fed_labels)  # federation labels win
                    pairs = sorted(merged.items())
                    lines.append(
                        f"{name}{_label_str((), (), extra=pairs)} "
                        f"{_format_value(sample[name][label_key])}")
        if dropped:
            self._m_dropped.inc(dropped)
        if errors:
            self._m_errors.inc(errors)
        return lines

    def exposition(self) -> str:
        text = self.local.exposition()
        fed = self.federated_lines()
        if fed:
            text += "\n".join(fed) + "\n"
        return text


class FleetMetricsServer:
    """Minimal observability front-end for supervisor processes: GET
    ``/metrics`` (the :class:`FleetRegistry` union, Prometheus text),
    ``/healthz``, and ``/alerts`` when an ``AlertManager`` is attached —
    the ModelServer's HTTP plumbing without the model surface."""

    def __init__(self, registry, *, host: str = "127.0.0.1", port: int = 0,
                 alerts=None):
        self.registry = registry
        self.host = host
        self.port = int(port)
        self.alerts = alerts
        self._httpd = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        """Bind (port 0 → ephemeral) and serve on a daemon thread;
        returns the bound port."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from deeplearning4j_tpu.observe.metrics import respond, respond_json
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # silence
                pass

            def do_GET(self):
                from urllib.parse import urlparse
                path = urlparse(self.path).path
                if path == "/metrics":
                    respond(self, 200,
                            server.registry.exposition().encode(),
                            "text/plain; version=0.0.4")
                elif path == "/healthz":
                    respond_json(self, {"status": "ok"})
                elif path == "/alerts":
                    if server.alerts is None:
                        respond_json(self,
                                     {"error": "no alert manager attached"},
                                     404)
                    else:
                        respond_json(self, server.alerts.describe())
                else:
                    respond_json(self, {"error": "not found"}, 404)

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="fleet-metrics-server")
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def url(self) -> str:
        return f"http://{self.host}:{self.port}"


# ---------------------------------------------------------------------------
# crash-durable span streaming
# ---------------------------------------------------------------------------

# the ONE attr sanitization rule, shared with every exporter
from deeplearning4j_tpu.observe.export import sanitize_attr as _safe_attr


class SpanFileWriter(TraceRecorder):
    """A :class:`TraceRecorder` drop-in that ALSO appends every completed
    span as one JSON line to ``path`` — crash-durable: a SIGKILLed worker
    keeps every span up to its last finished iteration, which is exactly
    what the incident bundle and the merged fleet trace need from a
    victim.  The first line is a ``meta`` record carrying the process's
    monotonic↔epoch anchor (``observe.trace.EPOCH_ANCHOR``), the
    clock-alignment datum :func:`read_span_file` hands to
    ``merge_chrome_traces``.  A dead stream (disk full) detaches; the
    in-memory ring keeps recording (the ``LogHub`` contract).

    The file is TRUNCATED on open: one stream = one process = one
    anchor. A re-run supervisor re-using the same checkpoint dir (and
    therefore the same per-generation filenames) must not leave a stale
    process's spans under a fresh anchor — the merge rule is that a
    mis-aligned row is worse than a missing one."""

    def __init__(self, path: str, *, label: str, capacity: int = 65536,
                 extra_meta: Optional[Dict[str, Any]] = None):
        super().__init__(capacity)
        self.path = str(path)
        self.label = label
        self._file_lock = threading.Lock()
        self._fh = open(self.path, "w", encoding="utf-8")
        meta: Dict[str, Any] = {
            "kind": "meta", "label": label, "pid": os.getpid(),
            "anchor_perf_ns": EPOCH_ANCHOR[0],
            "anchor_epoch_us": EPOCH_ANCHOR[1],
        }
        if extra_meta:
            meta.update({str(k): _safe_attr(v)
                         for k, v in extra_meta.items()})
        self._write_line(meta)

    def add(self, span: Span) -> None:
        super().add(span)
        rec: Dict[str, Any] = {
            "kind": "span", "name": span.name, "cat": span.category,
            "trace": span.trace_id, "span": span.span_id,
            "parent": span.parent_id, "start_ns": span.start_ns,
            "end_ns": span.end_ns, "tid": span.thread_id,
            "tname": span.thread_name,
        }
        if span.attrs:
            rec["attrs"] = {str(k): _safe_attr(v)
                            for k, v in span.attrs.items()}
        if span.error:
            rec["error"] = span.error
        if span.links:
            rec["links"] = [{"trace": l.trace_id, "span": l.span_id}
                            for l in span.links]
        self._write_line(rec)

    def _write_line(self, obj: Dict[str, Any]) -> None:
        with self._file_lock:
            fh = self._fh
            if fh is None:
                return
            try:
                fh.write(json.dumps(obj) + "\n")
                fh.flush()
            except Exception:  # noqa: BLE001 - a dead stream must never
                # raise into an instrumented hot path; the ring records on
                self._fh = None
                try:
                    fh.close()
                except Exception:  # noqa: BLE001
                    pass

    def close(self) -> None:
        with self._file_lock:
            fh, self._fh = self._fh, None
            if fh is not None:
                fh.close()


def read_span_file(path: str) -> Dict[str, Any]:
    """Parse one :class:`SpanFileWriter` output file:
    ``{"label", "pid", "anchor": (perf_ns, epoch_us), "spans": [dict]}``.
    Torn final lines (the writer was SIGKILLed mid-write) and unparseable
    lines are skipped — the surviving spans are the point."""
    out: Dict[str, Any] = {"label": os.path.basename(path), "pid": None,
                           "anchor": None, "meta": {}, "spans": []}
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            if not line.endswith("\n"):
                continue  # torn tail: that span never fully landed
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if not isinstance(rec, dict):
                continue
            kind = rec.get("kind")
            if kind == "meta":
                if out["anchor"] is not None:
                    # defense in depth: the writer truncates on open, so
                    # a second meta line means two processes wrote one
                    # file — only the FIRST anchor can align the spans
                    # that follow it; keep it
                    continue
                out["label"] = rec.get("label", out["label"])
                out["pid"] = rec.get("pid")
                out["meta"] = {k: v for k, v in rec.items()
                               if k not in ("kind",)}
                try:
                    out["anchor"] = (int(rec["anchor_perf_ns"]),
                                     int(rec["anchor_epoch_us"]))
                except (KeyError, TypeError, ValueError):
                    pass
            elif kind == "span":
                if not isinstance(rec.get("start_ns"), int) \
                        or not isinstance(rec.get("end_ns"), int):
                    continue
                out["spans"].append(rec)
    return out
