"""Dependency-free metrics core: counters, gauges, histograms → Prometheus.

The shared observability seam of the WHOLE stack (the role
Micrometer/Dropwizard plays behind the reference's Play endpoints): a
thread-safe registry of labeled instruments with text exposition in the
Prometheus 0.0.4 format at ``/metrics``. Born in the serving tier
(``serving.metrics``, which remains as a deprecation re-export), promoted
here so training (``observe.listener.TraceListener``), the batching
dispatcher, the KNN server and the UI server all report through one
registry. Deliberately stdlib-only and duck-typed: lower layers just call
``registry.counter(...)`` on whatever object they are handed.

Conventions follow the Prometheus client library:
- a metric name + label-name set is registered once; lookups with the same
  name return the SAME instrument (get-or-create), mismatched label names
  raise;
- histograms are cumulative (every bucket counts all observations ≤ its
  upper bound, ``+Inf`` always present) with ``_sum`` and ``_count`` series;
- histogram observations made under an active span carry an OpenMetrics
  **exemplar** — the bucket line grows a ``# {trace_id="..."} value ts``
  suffix linking the latest observation that landed in that bucket to its
  trace. The grammar is locked by round-trip tests: exemplar labels are
  escaped exactly like series labels, ``parse_prometheus_text`` captures
  exemplars on its ``.exemplars`` side table (the mapping contract is
  unchanged for existing consumers), and fleet federation re-renders them
  verbatim under relabeling.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

# latency-oriented default buckets (seconds), matching the Prometheus client
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def _escape_label_value(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _format_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _label_str(names: Sequence[str], values: Tuple[str, ...],
               extra: Sequence[Tuple[str, str]] = ()) -> str:
    pairs = list(zip(names, values)) + list(extra)
    if not pairs:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in pairs)
    return "{" + inner + "}"


class Exemplar:
    """One OpenMetrics exemplar: a label set (``trace_id`` by convention),
    the observed value that set it, and an optional unix timestamp."""

    __slots__ = ("labels", "value", "ts")

    def __init__(self, labels: Dict[str, str], value: float,
                 ts: Optional[float] = None):
        self.labels = {str(k): str(v) for k, v in dict(labels).items()}
        self.value = float(value)
        self.ts = None if ts is None else float(ts)

    def __eq__(self, other):
        return (isinstance(other, Exemplar)
                and self.labels == other.labels
                and self.value == other.value and self.ts == other.ts)

    def __repr__(self):
        return f"Exemplar({self.labels!r}, {self.value!r}, {self.ts!r})"


def format_exemplar(ex: Exemplar) -> str:
    """THE exemplar suffix grammar: ``# {k="v",...} value [timestamp]``,
    label values escaped exactly like series labels."""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"'
                     for k, v in ex.labels.items())
    s = f"# {{{inner}}} {_format_value(ex.value)}"
    if ex.ts is not None:
        s += f" {_format_value(ex.ts)}"
    return s


# lazily bound: metrics must stay importable without pulling trace first
_trace_ctx = None


def _current_trace_id() -> Optional[str]:
    global _trace_ctx
    if _trace_ctx is None:
        from deeplearning4j_tpu.observe import trace as _t
        _trace_ctx = _t._current_ctx
    cur = _trace_ctx.get()
    return None if cur is None else cur[0]


class _Metric:
    """Base: a named instrument with a fixed label-name schema."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 label_names: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()

    def _key(self, labels: Dict[str, str]) -> Tuple[str, ...]:
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, "
                f"got {tuple(sorted(labels))}")
        return tuple(str(labels[n]) for n in self.label_names)

    def expose(self) -> List[str]:  # pragma: no cover - overridden
        raise NotImplementedError

    def _header(self) -> List[str]:
        lines = []
        if self.help:
            lines.append(f"# HELP {self.name} {self.help}")
        lines.append(f"# TYPE {self.name} {self.kind}")
        return lines


class Counter(_Metric):
    """Monotonically increasing count, per label combination."""

    kind = "counter"

    def __init__(self, name, help="", label_names=()):
        super().__init__(name, help, label_names)
        self._values: Dict[Tuple[str, ...], float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        key = self._key(labels)
        with self._lock:
            return self._values.get(key, 0.0)

    def total(self) -> float:
        """Sum over every label combination (reconciliation checks)."""
        with self._lock:
            return sum(self._values.values())

    def expose(self) -> List[str]:
        lines = self._header()
        with self._lock:
            items = sorted(self._values.items())
        for key, v in items:
            lines.append(f"{self.name}{_label_str(self.label_names, key)}"
                         f" {_format_value(v)}")
        return lines


class Gauge(_Metric):
    """A value that can go up and down (queue depth, live version, ...)."""

    kind = "gauge"

    def __init__(self, name, help="", label_names=()):
        super().__init__(name, help, label_names)
        self._values: Dict[Tuple[str, ...], float] = {}

    def set(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels) -> float:
        key = self._key(labels)
        with self._lock:
            return self._values.get(key, 0.0)

    def expose(self) -> List[str]:
        lines = self._header()
        with self._lock:
            items = sorted(self._values.items())
        for key, v in items:
            lines.append(f"{self.name}{_label_str(self.label_names, key)}"
                         f" {_format_value(v)}")
        return lines


class Histogram(_Metric):
    """Cumulative-bucket histogram (request latency, batch sizes)."""

    kind = "histogram"

    def __init__(self, name, help="", label_names=(),
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help, label_names)
        bs = sorted(float(b) for b in buckets)
        if not bs or bs[-1] != math.inf:
            bs.append(math.inf)
        self.buckets = tuple(bs)
        self._counts: Dict[Tuple[str, ...], List[int]] = {}
        self._sums: Dict[Tuple[str, ...], float] = {}
        # (series key, bucket index) -> latest Exemplar landing there
        self._exemplars: Dict[Tuple[Tuple[str, ...], int], Exemplar] = {}

    def observe(self, value: float, **labels) -> None:
        key = self._key(labels)
        value = float(value)
        # an observation made inside an active span links the bucket to
        # its trace — the p99 bucket names a trace you can actually open
        trace_id = _current_trace_id()
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    counts[i] += 1
                    if trace_id is not None:
                        self._exemplars[(key, i)] = Exemplar(
                            {"trace_id": trace_id}, value, time.time())
                    break
            self._sums[key] = self._sums.get(key, 0.0) + value

    def exemplars(self, **labels) -> Dict[float, Exemplar]:
        """One series' exemplars keyed by bucket upper bound."""
        key = self._key(labels)
        with self._lock:
            return {self.buckets[i]: ex
                    for (k, i), ex in self._exemplars.items() if k == key}

    def count(self, **labels) -> int:
        key = self._key(labels)
        with self._lock:
            return sum(self._counts.get(key, ()))

    def total_count(self) -> int:
        with self._lock:
            return sum(sum(c) for c in self._counts.values())

    def sum(self, **labels) -> float:
        key = self._key(labels)
        with self._lock:
            return self._sums.get(key, 0.0)

    def expose(self) -> List[str]:
        lines = self._header()
        with self._lock:
            items = sorted((k, list(c), self._sums.get(k, 0.0))
                           for k, c in self._counts.items())
            exemplars = dict(self._exemplars)
        for key, counts, total in items:
            cum = 0
            for i, (ub, c) in enumerate(zip(self.buckets, counts)):
                cum += c
                le = _label_str(self.label_names, key,
                                extra=[("le", _format_value(ub))])
                line = f"{self.name}_bucket{le} {cum}"
                ex = exemplars.get((key, i))
                if ex is not None:
                    line += " " + format_exemplar(ex)
                lines.append(line)
            lbl = _label_str(self.label_names, key)
            lines.append(f"{self.name}_sum{lbl} {_format_value(total)}")
            lines.append(f"{self.name}_count{lbl} {cum}")
        return lines


class MetricsRegistry:
    """Get-or-create instrument factory + exposition."""

    def __init__(self):
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name, help, label_names, **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls) or \
                        m.label_names != tuple(label_names):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{m.kind}{m.label_names}")
                return m
            m = cls(name, help, label_names, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "",
                label_names: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, label_names)

    def gauge(self, name: str, help: str = "",
              label_names: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, label_names)

    def histogram(self, name: str, help: str = "",
                  label_names: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, label_names,
                                   buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def exposition(self) -> str:
        """Prometheus text format 0.0.4 (the ``/metrics`` payload)."""
        with self._lock:
            metrics = [self._metrics[n] for n in sorted(self._metrics)]
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"


_default_registry = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """Process-wide shared registry (the KNN/UI servers default to it)."""
    return _default_registry


def instrument_http(registry: MetricsRegistry,
                    server: str) -> Callable[[str, int, float], None]:
    """Uniform HTTP instrumentation every front-end shares: returns
    ``observe(path, status, seconds)`` recording into
    ``http_requests_total{server,path,status}`` and
    ``http_request_latency_seconds{server,path}``."""
    requests = registry.counter(
        "http_requests_total", "HTTP requests by server, path and status",
        ("server", "path", "status"))
    latency = registry.histogram(
        "http_request_latency_seconds", "HTTP request latency",
        ("server", "path"))

    def observe(path: str, status: int, seconds: float) -> None:
        requests.inc(server=server, path=path, status=str(status))
        latency.observe(seconds, server=server, path=path)

    return observe


def respond(handler, code: int, body: bytes, content_type: str,
            headers: Sequence[Tuple[str, str]] = ()) -> None:
    """The one HTTP response shape every front-end shares (ModelServer,
    the fleet metrics server): status + Content-Type/Length + extra
    headers + any trace-correlation headers the handler staged on
    ``_trace_headers`` — so keep-alive clients always get an exact
    Content-Length and traced requests always echo their ids."""
    handler.send_response(code)
    handler.send_header("Content-Type", content_type)
    handler.send_header("Content-Length", str(len(body)))
    for k, v in headers:
        handler.send_header(k, v)
    for k, v in getattr(handler, "_trace_headers", ()):
        handler.send_header(k, v)
    handler.end_headers()
    handler.wfile.write(body)


def respond_json(handler, obj, code: int = 200,
                 headers: Sequence[Tuple[str, str]] = ()) -> None:
    import json
    respond(handler, code, json.dumps(obj).encode(), "application/json",
            headers)


class HTTPObserverMixin:
    """Handler mixin recording request count + latency through an
    ``instrument_http`` observer. Mix in BEFORE ``BaseHTTPRequestHandler``:

        class Handler(HTTPObserverMixin, BaseHTTPRequestHandler):
            observe = my_observe            # or None → zero overhead
            route_label = staticmethod(fn)  # optional path → label mapping
                                            # (keep label cardinality bounded)
    """

    observe = None  # (path, status, seconds) -> None, or None to disable

    @staticmethod
    def route_label(path: str) -> str:
        return path

    def send_response(self, code, message=None):
        self._status = code
        super().send_response(code, message)

    def handle_one_request(self):
        # class-level access: a plain function assigned as `observe = fn`
        # must NOT be bound as a method (fn takes no self)
        observe = type(self).observe
        if observe is None:
            return super().handle_one_request()
        import time
        from urllib.parse import urlparse
        t0 = time.perf_counter()
        self._status = None
        super().handle_one_request()
        if self._status is not None:  # a request was actually answered;
            # self.path may be unset when parse_request rejected the line
            path = urlparse(getattr(self, "path", "") or "").path
            observe(self.route_label(path), self._status,
                    time.perf_counter() - t0)


class ParsedExposition(dict):
    """``parse_prometheus_text``'s result: the plain
    ``{series: {sorted label pairs: value}}`` mapping every existing
    consumer indexes, plus an ``exemplars`` side table keyed by
    ``(series, sorted label pairs)`` so federation and the tail sampler
    can round-trip exemplars without a second parse."""

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self.exemplars: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                             Exemplar] = {}


def _scan_labels(s: str, i: int) -> Tuple[Dict[str, str], int]:
    """Quote-aware label-block scanner: ``s[i]`` is ``{``; returns the
    label dict and the index just past the closing ``}``. Left-to-right
    with escape handling, so a ``}`` (or ``#``) INSIDE a label value can
    never truncate the block — the property the exemplar suffix (which
    contains its own ``}``) depends on."""
    labels: Dict[str, str] = {}
    i += 1
    while True:
        while s[i] in ", ":
            i += 1
        if s[i] == "}":
            return labels, i + 1
        eq = s.index("=", i)
        key = s[i:eq].strip()
        assert s[eq + 1] == '"'
        j = eq + 2
        buf = []
        while s[j] != '"':
            if s[j] == "\\":
                nxt = s[j + 1]
                buf.append({"n": "\n", "\\": "\\", '"': '"'}.get(nxt, nxt))
                j += 2
            else:
                buf.append(s[j])
                j += 1
        labels[key] = "".join(buf)
        i = j + 1


def _parse_scalar(token: str) -> float:
    if token == "+Inf":
        return math.inf
    if token == "-Inf":
        return -math.inf
    return float(token)


def parse_prometheus_text(text: str) -> ParsedExposition:
    """Parse an exposition back into ``{series: {sorted label pairs: value}}``
    — the reconciliation half of the round trip used by the tests, the
    alert engine, fleet federation and the client's ``metrics()`` scrape.
    Handles escaped label values; exemplar suffixes
    (``# {trace_id="..."} v ts``) land on the result's ``.exemplars``."""
    out = ParsedExposition()
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        brace = line.find("{")
        space = line.find(" ")
        if brace != -1 and (space == -1 or brace < space):
            name = line[:brace]
            labels, i = _scan_labels(line, brace)
            rest = line[i:].strip()
        else:
            name, rest = line.split(None, 1)
            labels = {}
        exemplar = None
        hash_pos = rest.find("#")
        if hash_pos != -1:
            value_tok = rest[:hash_pos].strip()
            ex_part = rest[hash_pos + 1:].strip()
            if ex_part.startswith("{"):
                ex_labels, k = _scan_labels(ex_part, 0)
                tail = ex_part[k:].split()
                if tail:
                    exemplar = Exemplar(
                        ex_labels, _parse_scalar(tail[0]),
                        _parse_scalar(tail[1]) if len(tail) > 1 else None)
        else:
            value_tok = rest
        key = tuple(sorted(labels.items()))
        out.setdefault(name, {})[key] = _parse_scalar(value_tok)
        if exemplar is not None:
            out.exemplars[(name, key)] = exemplar
    return out


def exemplar_trace_ids(source) -> set:
    """Every ``trace_id`` referenced by an exemplar in ``source`` (a
    registry — anything with ``exposition()`` — or raw exposition text).
    Reads through the ``parse_prometheus_text`` contract, so it works on
    local and federated registries alike; the ``TailSampler``'s
    exemplar-referenced keep set."""
    text = source.exposition() if hasattr(source, "exposition") \
        else str(source)
    parsed = parse_prometheus_text(text)
    return {ex.labels["trace_id"] for ex in parsed.exemplars.values()
            if "trace_id" in ex.labels}
