"""Distributed training & inference over a TPU device mesh.

TPU-native re-design of the reference's scale-out stack (SURVEY.md §2.b, §3.3,
§3.4): `ParallelWrapper.java:58` (single-node data parallel),
`ParameterAveragingTrainingMaster.java:308` (periodic parameter averaging),
`EncodedGradientsAccumulator.java:33` / `EncodingHandler.java:139` (threshold-
compressed gradient sharing), and `ParallelInference.java:32` (multi-device
batched inference).

Instead of thread replication + NCCL/Aeron messaging, everything is expressed
as sharded jitted computations over a `jax.sharding.Mesh`: per-step gradient
synchronization is what XLA GSPMD emits automatically when the batch is
sharded over the 'data' axis and params are replicated (the all-reduce rides
ICI); parameter averaging is a `shard_map` with K local steps then `pmean`;
tensor parallelism is a `PartitionSpec` on the weight matrices.
"""

from deeplearning4j_tpu.parallel.mesh import make_mesh, local_mesh  # noqa: F401
from deeplearning4j_tpu.parallel.sharding import (  # noqa: F401
    batch_sharding,
    replicated,
    tp_param_specs,
    shard_model,
)
from deeplearning4j_tpu.parallel.trainer import ParallelWrapper  # noqa: F401
from deeplearning4j_tpu.parallel.compression import (  # noqa: F401
    threshold_encode,
    threshold_decode,
    EncodingHandler,
)
from deeplearning4j_tpu.parallel.inference import ParallelInference  # noqa: F401
from deeplearning4j_tpu.parallel.dcn import CrossSliceGradientBridge  # noqa: F401
from deeplearning4j_tpu.parallel.master import (  # noqa: F401
    DistributedMultiLayerNetwork,
    ParameterAveragingTrainingMaster,
    SharedTrainingMaster,
    TrainingMaster,
    TrainingStats,
    init_distributed,
)
from deeplearning4j_tpu.parallel.elastic import (  # noqa: F401
    BackoffPolicy,
    ElasticJobFailed,
    ElasticJobResult,
    ElasticJobSupervisor,
    ElasticWorkerContext,
    StaleGenerationError,
    WorkerSpec,
    run_elastic_worker,
)
from deeplearning4j_tpu.parallel.time_source import (  # noqa: F401
    NTPTimeSource,
    SystemClockTimeSource,
    TimeSource,
    get_time_source,
    set_time_source,
)
