"""ParallelInference — dynamic-batching inference server.

Reference: ``ParallelInference.java:32`` — requests from many client threads
are queued, a background worker coalesces them into batches
(``InferenceMode.BATCHED``, ``:52,82``) and dispatches to per-device model
replicas.

TPU-native design: one jitted forward specialized per bucketed batch size
(powers of two, to bound recompilation), requests coalesced by a single
dispatcher thread; multi-device throughput comes from sharding the coalesced
batch over the mesh 'data' axis rather than from model replicas.
"""

from __future__ import annotations

import queue
import threading
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from deeplearning4j_tpu.parallel.sharding import batch_sharding


class _Request:
    __slots__ = ("x", "event", "result", "error")

    def __init__(self, x):
        self.x = x
        self.event = threading.Event()
        self.result = None
        self.error = None


def _bucket(n: int) -> int:
    b = 1
    while b < n:
        b <<= 1
    return b


class ParallelInference:
    """Batched inference front-end over a model's ``output``.

    mode (``ParallelInference.java:52`` ``InferenceMode``):
    - 'inplace' (alias 'sequential'): the request runs in the calling
      thread against the shared model. The reference clones one model per
      worker thread because its layers carry mutable buffers; here the
      compiled forward is a pure function, so every thread can call the
      SAME jitted executable concurrently — replica cloning vanishes.
    - 'batched': requests are coalesced by a dispatcher thread up to
      ``max_batch_size`` within a ``wait_ms`` TTL window measured from the
      oldest queued request (the ObservablesProvider nanos-TTL semantics).
    """

    def __init__(self, model, *, mode: str = "batched", max_batch_size: int = 32,
                 queue_limit: int = 64, wait_ms: float = 2.0,
                 mesh: Optional[Mesh] = None):
        if mode not in ("sequential", "inplace", "batched"):
            raise ValueError(f"unknown mode {mode!r} (inplace|sequential|batched)")
        self.model = model
        self.mode = mode
        self.max_batch_size = int(max_batch_size)
        self.wait_s = wait_ms / 1e3
        self.mesh = mesh
        self._model_lock = threading.Lock()
        self._q: "queue.Queue[_Request]" = queue.Queue(maxsize=queue_limit)
        self._shutdown = False
        self._worker = None
        if mode == "batched":
            self._worker = threading.Thread(target=self._run, daemon=True)
            self._worker.start()

    # ----------------------------------------------------------- client API
    def output(self, x) -> np.ndarray:
        x = np.asarray(x)
        if self.mode in ("sequential", "inplace"):
            return np.asarray(self._model().output(x))
        if self._shutdown:
            raise RuntimeError("ParallelInference is shut down")
        req = _Request(x)
        self._q.put(req)
        req.event.wait()
        if req.error is not None:
            raise req.error
        return req.result

    def update_model(self, model) -> None:
        """Atomically swap the served model (``ParallelInference.updateModel``)
        — lets a training loop publish fresh weights without stopping
        serving. In-flight batches finish on the old model."""
        with self._model_lock:
            self.model = model

    def _model(self):
        with self._model_lock:
            return self.model

    def shutdown(self) -> None:
        self._shutdown = True
        if self._worker is not None:
            self._worker.join(timeout=1.0)
        # fail any requests still queued so no client blocks forever
        while True:
            try:
                r = self._q.get_nowait()
            except queue.Empty:
                break
            r.error = RuntimeError("ParallelInference shut down")
            r.event.set()

    # ------------------------------------------------------------ dispatcher
    def _run(self) -> None:
        while not self._shutdown:
            try:
                first = self._q.get(timeout=0.05)
            except queue.Empty:
                continue
            batch: List[_Request] = [first]
            n = first.x.shape[0]
            deadline = self.wait_s
            import time
            t0 = time.monotonic()
            while n < self.max_batch_size:
                remaining = deadline - (time.monotonic() - t0)
                if remaining <= 0:
                    break
                try:
                    r = self._q.get(timeout=remaining)
                except queue.Empty:
                    break
                batch.append(r)
                n += r.x.shape[0]
            self._dispatch(batch, n)

    def _dispatch(self, batch: List[_Request], n: int) -> None:
        try:
            x = np.concatenate([r.x for r in batch], axis=0)
            # pad to bucket size → bounded set of compiled shapes
            target = _bucket(n)
            if self.mesh is not None:
                d = self.mesh.shape.get("data", 1)
                target = -(-target // d) * d
            if target > n:
                pad = np.zeros((target - n,) + x.shape[1:], x.dtype)
                x = np.concatenate([x, pad], axis=0)
            xj = jnp.asarray(x)
            if self.mesh is not None:
                xj = jax.device_put(xj, batch_sharding(self.mesh, xj.ndim))
            out = np.asarray(self._model().output(xj))
            off = 0
            for r in batch:
                k = r.x.shape[0]
                r.result = out[off:off + k]
                off += k
                r.event.set()
        except Exception as e:  # deliver errors to waiting clients
            for r in batch:
                r.error = e
                r.event.set()
