"""ParallelInference — dynamic-batching inference server.

Reference: ``ParallelInference.java:32`` — requests from many client threads
are queued, a background worker coalesces them into batches
(``InferenceMode.BATCHED``, ``:52,82``) and dispatches to per-device model
replicas.

TPU-native design: one jitted forward specialized per bucketed batch size
(powers of two by default, or an explicit declared ``buckets`` list, to
bound recompilation), requests coalesced by a single dispatcher thread;
multi-device throughput comes from sharding the coalesced batch over the
mesh 'data' axis rather than from model replicas.

Serving fast path (the round-9 perf campaign):
- ``warmup`` executes the forward for every declared bucket through the
  EXACT dispatch path (same host dtype, same ``jnp.asarray`` conversion,
  same mesh sharding) so steady-state serving never pays an XLA compile —
  ``jit(...).lower().compile()`` AOT executables do NOT seed the jit call
  cache (verified on jax 0.4.37), so warmup executes the real jitted
  callable instead;
- the coalesce-and-pad hot path writes request rows straight into ONE
  preallocated per-bucket host buffer (``reuse_pad_buffer``) instead of a
  concatenate + pad-concatenate pair — two fewer full-batch host copies
  per dispatch (safe because the dispatcher is serial and the device
  result is materialized before the buffer is reused);
- a dispatch that lands on an UNDECLARED bucket (cold: a client batch
  larger than anything warmed) is counted in
  ``inference_cold_dispatches_total`` — the alarm that a compile spike hit
  a live request.

Serving-tier contract (the guarantees ``serving/server.py`` maps to HTTP
status codes):
- a request carries an optional absolute deadline; a request whose deadline
  has passed is NEVER dispatched to the device — it fails with
  ``InferenceDeadlineExceeded`` (the 504 path) and wastes no device time;
- a dispatcher-thread crash fails every queued AND future request with
  ``DispatcherCrashed`` instead of stranding waiters forever (the 503 path);
  ``healthy`` / ``dispatcher_error`` surface the state;
- with ``max_restarts > 0`` the crash is no longer terminal: the next
  request restarts the dispatcher thread in place, under the elastic
  supervisor's exponential-backoff ladder (deterministic jitter, an
  injectable ``restart_clock`` so tests never sleep). While the backoff
  runs, requests fail fast with a ``retry_after_s`` hint (the serving
  tier turns that into 503 + ``Retry-After``); the ``dispatched`` flag
  on the exception distinguishes a request that was IN the dying batch
  (a real forward failure — circuit-breaker food) from one shed while
  the restart was pending. ``serving_dispatcher_restarts_total{model}``
  counts every restart.
- an optional duck-typed metrics registry (``observe.metrics``-shaped)
  records the batch-size distribution and live queue depth.

Tracing (``observe.trace``): when a tracer is active, every batched
request runs inside an ``inference_request`` span; the dispatcher records
a ``queue_wait`` span per request (parented to the REQUEST's context — the
explicit cross-thread handoff) and a ``batch_execute`` span around the
device call, flow-linked to every request it served, so an XLA compile of
a new batch bucket nests visibly under the batch that paid for it.
"""

from __future__ import annotations

import queue
import threading
import time
import weakref
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from deeplearning4j_tpu.observe import trace as _trace
from deeplearning4j_tpu.parallel.sharding import batch_sharding
from deeplearning4j_tpu.util import faultinject as _faultinject


class InferenceDeadlineExceeded(TimeoutError):
    """The request's deadline expired before a result was produced."""


class DispatcherCrashed(RuntimeError):
    """The batching dispatcher thread died.

    ``retry_after_s`` is set when a supervised restart is pending (the
    failure is transient — come back after the backoff); ``None`` means
    terminal (no supervision, or budget exhausted). ``dispatched`` is True
    only for a request that was part of the dying batch — its forward
    actually ran and crashed the thread, the signal the per-version
    circuit breaker counts; fast-fail rejections while a restart is
    pending never carry it."""

    def __init__(self, msg: str, *, retry_after_s: Optional[float] = None,
                 dispatched: bool = False):
        super().__init__(msg)
        self.retry_after_s = retry_after_s
        self.dispatched = dispatched


# _Request lifecycle: PENDING -(dispatcher)-> CLAIMED, or
#                     PENDING -(client timeout)-> CANCELLED.
# The tiny per-request lock arbitrates the race between the dispatcher
# claiming a queued request and its client giving up on the deadline.
_PENDING, _CLAIMED, _CANCELLED = 0, 1, 2


class _Request:
    __slots__ = ("x", "event", "result", "error", "deadline", "_state",
                 "_lock", "served_model", "ctx", "t_enqueue", "t_claim")

    def __init__(self, x, deadline: Optional[float] = None,
                 ctx: Optional[_trace.SpanContext] = None):
        self.x = x
        self.event = threading.Event()
        self.result = None
        self.error = None
        self.served_model = None  # the model object that actually served
        self.deadline = deadline  # absolute time.monotonic() stamp
        self._state = _PENDING
        self._lock = threading.Lock()
        self.ctx = ctx  # trace context handed across the dispatcher hop
        # timestamps exist only for traced requests: the untraced hot path
        # must stay a bare `is None` check, paying nothing
        self.t_enqueue = time.perf_counter_ns() if ctx is not None else None
        self.t_claim: Optional[int] = None

    def claim(self) -> bool:
        """Dispatcher-side: take ownership for dispatch. Returns False if
        the client cancelled OR the deadline already passed — in the latter
        case the error is delivered here so the waiter unblocks."""
        with self._lock:
            if self._state != _PENDING:
                return False
            if self.deadline is not None and time.monotonic() >= self.deadline:
                self._state = _CANCELLED
                self.error = InferenceDeadlineExceeded(
                    "deadline expired while queued")
                self.event.set()
                return False
            self._state = _CLAIMED
            return True

    def cancel(self, error: Exception) -> bool:
        """Client-side: abandon a still-queued request."""
        with self._lock:
            if self._state != _PENDING:
                return False
            self._state = _CANCELLED
            self.error = error
            self.event.set()
            return True

    def fail_unclaimed(self, error: Exception) -> bool:
        """Fail the request if nobody owns it yet (shutdown/crash paths)."""
        with self._lock:
            if self._state == _CLAIMED:
                return False
            self._state = _CANCELLED
            self.error = error
            self.event.set()
            return True


def _bucket(n: int) -> int:
    b = 1
    while b < n:
        b <<= 1
    return b


class ParallelInference:
    """Batched inference front-end over a model's ``output``.

    mode (``ParallelInference.java:52`` ``InferenceMode``):
    - 'inplace' (alias 'sequential'): the request runs in the calling
      thread against the shared model. The reference clones one model per
      worker thread because its layers carry mutable buffers; here the
      compiled forward is a pure function, so every thread can call the
      SAME jitted executable concurrently — replica cloning vanishes.
    - 'batched': requests are coalesced by a dispatcher thread up to
      ``max_batch_size`` within a ``wait_ms`` TTL window measured from the
      oldest queued request (the ObservablesProvider nanos-TTL semantics).

    ``metrics``: optional duck-typed registry (``observe.metrics``
    interface). When provided, records ``inference_batch_size`` (histogram,
    label ``model``), ``inference_queue_depth`` (gauge) and
    ``inference_dispatcher_up`` (gauge).
    """

    def __init__(self, model, *, mode: str = "batched", max_batch_size: int = 32,
                 queue_limit: int = 64, wait_ms: float = 2.0,
                 mesh: Optional[Mesh] = None, metrics=None,
                 metrics_name: str = "default",
                 buckets: Optional[Sequence[int]] = None,
                 reuse_pad_buffer: bool = True,
                 max_restarts: int = 0, restart_backoff=None,
                 restart_clock=time.monotonic, cost=None):
        if mode not in ("sequential", "inplace", "batched"):
            raise ValueError(f"unknown mode {mode!r} (inplace|sequential|batched)")
        self.model = model
        self.mode = mode
        self.max_batch_size = int(max_batch_size)
        self.wait_s = wait_ms / 1e3
        self.mesh = mesh
        self.reuse_pad_buffer = bool(reuse_pad_buffer)
        # declared buckets: the batch shapes warmup compiles ahead of time
        # and the dispatcher pads to. Default: powers of two up to
        # max_batch_size. Every bucket is rounded up to a multiple of the
        # mesh data-axis size so the padded batch always shards evenly.
        d = 1 if mesh is None else mesh.shape.get("data", 1)
        if buckets is None:
            raw = []
            b = 1
            while b < self.max_batch_size:
                raw.append(b)
                b <<= 1
            raw.append(_bucket(self.max_batch_size))
        else:
            raw = [int(b) for b in buckets]
            if not raw or min(raw) < 1:
                raise ValueError("buckets must be positive batch sizes")
        self.buckets: Tuple[int, ...] = tuple(sorted(
            {-(-b // d) * d for b in raw}))
        # bounded: clients choose row shape/dtype on the binary path, so
        # unchecked growth here would be a dispatcher memory leak
        self._pad_buffers: Dict[tuple, np.ndarray] = {}
        self._max_pad_buffers = max(16, 2 * len(self.buckets))
        # PER MODEL: (bucket, row_shape, dtype) signatures warmup() has
        # executed — a declared bucket hit with a never-warmed dtype still
        # compiles, and so does a model swapped in via update_model()
        # without its own warmup (each model object owns a fresh jit call
        # cache, so warm state cannot transfer across a swap). Weak keys:
        # retired versions must not be pinned by their signature sets.
        self._warmed_keys: "weakref.WeakKeyDictionary" = (
            weakref.WeakKeyDictionary())
        self._model_lock = threading.Lock()
        self._q: "queue.Queue[_Request]" = queue.Queue(maxsize=queue_limit)
        self._shutdown = False
        self._worker = None
        self.dispatcher_error: Optional[BaseException] = None
        self.batches_dispatched = 0
        self._inflight_batch: List[_Request] = []
        self._carry: Optional[_Request] = None  # claimed, awaiting next batch
        self._metrics_name = metrics_name
        # optional observe.cost.CostLedger: each batch_execute span's
        # device time is apportioned row-weighted across its requests
        # (compile time excluded — attributed to the model instead)
        self.cost = cost
        # dispatcher supervision: restart-in-place under the elastic
        # backoff ladder. max_restarts=0 keeps the old terminal-crash
        # contract; the clock is injectable so tests drive the backoff
        # window without sleeping (batching TTLs stay on time.monotonic)
        self.max_restarts = int(max_restarts)
        self.restarts_used = 0
        if restart_backoff is None:
            from deeplearning4j_tpu.parallel.elastic import BackoffPolicy
            restart_backoff = BackoffPolicy()
        self._restart_backoff = restart_backoff
        self._restart_clock = restart_clock
        self._restart_at: Optional[float] = None  # restart_clock stamp
        self._restart_lock = threading.Lock()
        self._forward_seq = 0  # per-model dispatch counter (chaos keying)
        self._m_batch = self._m_depth = self._m_up = self._m_cold = None
        self._m_restarts = None
        if metrics is not None:
            self._m_batch = metrics.histogram(
                "inference_batch_size",
                "Coalesced rows per dispatched device batch", ("model",),
                buckets=[2 ** i for i in range(0, 11)])
            self._m_depth = metrics.gauge(
                "inference_queue_depth", "Requests waiting for dispatch",
                ("model",))
            self._m_up = metrics.gauge(
                "inference_dispatcher_up",
                "1 while the batching dispatcher thread is alive", ("model",))
            self._m_up.set(1, model=metrics_name)
            self._m_cold = metrics.counter(
                "inference_cold_dispatches_total",
                "Dispatches padded to an UNDECLARED (never-warmed) bucket — "
                "each one may pay a live XLA compile", ("model",))
            self._m_restarts = metrics.counter(
                "serving_dispatcher_restarts_total",
                "Supervised in-place restarts of a crashed batching "
                "dispatcher thread", ("model",))
        if mode == "batched":
            self._worker = threading.Thread(target=self._run, daemon=True)
            self._worker.start()

    # ----------------------------------------------------------- client API
    @property
    def healthy(self) -> bool:
        """False once the dispatcher thread has crashed or after shutdown."""
        if self.mode in ("sequential", "inplace"):
            return not self._shutdown
        return (not self._shutdown and self.dispatcher_error is None)

    def output(self, x, *, deadline_s: Optional[float] = None,
               return_model: bool = False) -> np.ndarray:
        """Predict; ``deadline_s`` is a relative per-request deadline.

        Raises ``InferenceDeadlineExceeded`` past the deadline — whether the
        request was still queued (it will never be dispatched) or its batch
        simply finished too late — and ``DispatcherCrashed`` when the
        batching thread is gone.

        ``return_model=True`` returns ``(result, model)`` where ``model`` is
        the object that actually served the batch — the only truthful
        attribution under concurrent hot-swaps (in-flight batches finish on
        the OLD model).
        """
        x = np.asarray(x)
        if x.ndim == 0:
            # a 0-d request would crash the shared dispatcher on shape[0]
            raise ValueError("request must be at least 1-d (a batch of rows)")
        if self.mode in ("sequential", "inplace"):
            model = self._model()
            with _trace.span("inference_request", category="serve",
                             attrs={"model": self._metrics_name,
                                    "rows": int(x.shape[0]),
                                    "mode": self.mode}):
                res = np.asarray(model.output(x))
            return (res, model) if return_model else res
        if self._shutdown:
            raise RuntimeError("ParallelInference is shut down")
        if self.dispatcher_error is not None:
            self._ensure_dispatcher()
        deadline = (time.monotonic() + deadline_s
                    if deadline_s is not None else None)
        tracer = _trace.get_active_tracer()
        if tracer is None:
            return self._output_batched(x, deadline, deadline_s,
                                        return_model, None)
        # per-request span: covers enqueue → wait → result; its context
        # rides the _Request across the dispatcher thread
        with tracer.span("inference_request", category="serve",
                         attrs={"model": self._metrics_name,
                                "rows": int(x.shape[0]),
                                "mode": "batched"}) as sp:
            return self._output_batched(x, deadline, deadline_s,
                                        return_model, sp.context)

    def _output_batched(self, x, deadline, deadline_s, return_model, ctx):
        req = _Request(x, deadline=deadline, ctx=ctx)
        self._q.put(req)
        # re-check AFTER the put: a crash/shutdown that drained the queue
        # concurrently with this enqueue would otherwise strand the request
        # (nobody will ever claim it from the dead queue). The exception
        # carries the pending restart window — "no hint" means terminal
        if self.dispatcher_error is not None:
            req.fail_unclaimed(DispatcherCrashed(
                "inference dispatcher died",
                retry_after_s=self.restart_state()["retry_after_s"]))
        elif self._shutdown:
            req.fail_unclaimed(RuntimeError("ParallelInference shut down"))
        if self._m_depth is not None:
            self._m_depth.set(self._q.qsize(), model=self._metrics_name)
        if deadline is None:
            req.event.wait()
        else:
            remaining = deadline - time.monotonic()
            if not req.event.wait(max(remaining, 0.0)):
                # still queued → cancel so the dispatcher skips it; already
                # claimed → the batch is in flight, await it but report the
                # deadline anyway (the result is past its SLO either way)
                req.cancel(InferenceDeadlineExceeded(
                    f"deadline of {deadline_s}s expired"))
                req.event.wait()
                if req.error is None:
                    raise InferenceDeadlineExceeded(
                        f"deadline of {deadline_s}s expired (late batch)")
        if req.error is not None:
            raise req.error
        return (req.result, req.served_model) if return_model else req.result

    def update_model(self, model) -> None:
        """Atomically swap the served model (``ParallelInference.updateModel``)
        — lets a training loop publish fresh weights without stopping
        serving. In-flight batches finish on the old model."""
        with self._model_lock:
            self.model = model

    def _model(self):
        with self._model_lock:
            return self.model

    # ----------------------------------------------------------- supervision
    def _ensure_dispatcher(self) -> None:
        """Crashed-dispatcher gate on the request path: restart the
        thread in place once the backoff window has passed, or raise
        ``DispatcherCrashed`` — with a ``retry_after_s`` hint while the
        window runs, terminally once the budget is gone. Lazy (no
        supervisor thread): the restart happens on the first request
        that finds the window elapsed, which keeps the whole ladder
        deterministic under an injected clock."""
        with self._restart_lock:
            if self.dispatcher_error is None or self._shutdown:
                return  # restarted concurrently (or shutting down)
            cause = self.dispatcher_error
            if self._restart_at is None:
                msg = ("inference dispatcher died"
                       if self.max_restarts == 0 else
                       f"inference dispatcher died (restart budget of "
                       f"{self.max_restarts} exhausted)")
                raise DispatcherCrashed(msg) from cause
            remaining = self._restart_at - self._restart_clock()
            if remaining > 0:
                raise DispatcherCrashed(
                    "inference dispatcher died; restart pending",
                    retry_after_s=remaining) from cause
            # the dying thread is past the point where it published the
            # error (same lock), but may still be failing its casualties —
            # let it finish before a new thread shares the queue, or it
            # could fail requests that belong to the NEW dispatcher
            old = self._worker
            if old is not None and old is not threading.current_thread():
                old.join(timeout=5.0)
            self.restarts_used += 1
            self.dispatcher_error = None
            self._restart_at = None
            self._inflight_batch = []
            self._carry = None
            self._worker = threading.Thread(target=self._run, daemon=True)
            self._worker.start()
            if self._m_up is not None:
                self._m_up.set(1, model=self._metrics_name)
            if self._m_restarts is not None:
                self._m_restarts.inc(model=self._metrics_name)

    def restart_state(self) -> dict:
        """Supervision snapshot for health probes: whether the dispatcher
        is crashed, whether a restart is still possible, and how long
        until the backoff window opens."""
        with self._restart_lock:
            crashed = self.dispatcher_error is not None
            pending = crashed and self._restart_at is not None
            retry_after = None
            if pending:
                retry_after = max(
                    0.0, self._restart_at - self._restart_clock())
            return {"crashed": crashed,
                    "restart_pending": pending,
                    "retry_after_s": retry_after,
                    "restarts_used": self.restarts_used,
                    "max_restarts": self.max_restarts,
                    "terminal": crashed and self._restart_at is None}

    # ------------------------------------------------------------ fast path
    def _bucket_for(self, n: int) -> Tuple[int, bool]:
        """Smallest declared bucket holding ``n`` rows, or (cold) the
        power-of-two fallback when ``n`` exceeds every declared bucket.
        Returns ``(target_rows, declared)``."""
        for b in self.buckets:
            if b >= n:
                return b, True
        target = _bucket(n)
        if self.mesh is not None:
            d = self.mesh.shape.get("data", 1)
            target = -(-target // d) * d
        return target, False

    def _to_device(self, x: np.ndarray, mesh=None):
        """Host batch → device array, exactly as the dispatcher ships it
        (shared by the dispatch hot path and warmup so the compiled shapes
        and shardings are identical). ``mesh`` overrides the dispatcher
        mesh — warmup of a NOT-yet-activated version placed on its own
        mesh must ship batches the way that version's dispatches will."""
        xj = jnp.asarray(x)
        mesh = self.mesh if mesh is None else mesh
        if mesh is not None:
            xj = jax.device_put(xj, batch_sharding(mesh, xj.ndim))
        return xj

    def set_mesh(self, mesh: Optional[Mesh]) -> None:
        """Repoint batch sharding at ``mesh`` (None = single-device) and
        re-round the declared buckets to its data-axis size. Called by the
        registry when a hot-swap activates a version placed on a different
        mesh than the dispatcher's current one — batches for a GSPMD-
        sharded version must land on ITS device set or the forward raises
        an incompatible-devices error. With the power-of-two defaults the
        re-rounding is a no-op for any data axis that divides the old one
        (a shrink keeps every bucket); a grow may widen small buckets."""
        self.mesh = mesh
        d = 1 if mesh is None else mesh.shape.get("data", 1)
        self.buckets = tuple(sorted({-(-b // d) * d for b in self.buckets}))

    def warmup(self, row_shape: Sequence[int], *, dtype=np.float32,
               model=None, buckets: Optional[Sequence[int]] = None,
               mesh=None) -> dict:
        """Execute the forward for every declared bucket ahead of time.

        ``row_shape`` is the per-row feature shape (no batch dim); ``model``
        defaults to the live model but a NOT-yet-activated version can be
        warmed before its hot-swap (the registry does exactly that, so a
        swap lands on an already-compiled forward). Runs the real jitted
        callable through the real transfer path — an AOT
        ``lower().compile()`` would leave the jit call cache cold and the
        first live request would compile anyway.

        Returns ``{bucket: seconds}`` for the buckets warmed by THIS call.
        ``mesh`` overrides the batch placement (see ``_to_device``).
        """
        model = self._model() if model is None else model
        report = {}
        for b in (self.buckets if buckets is None else
                  [self._bucket_for(int(x))[0] for x in buckets]):
            x = np.zeros((b,) + tuple(row_shape), dtype)
            t0 = time.perf_counter()
            np.asarray(model.output(self._to_device(x, mesh=mesh)))
            report[b] = time.perf_counter() - t0
            try:
                self._warmed_keys.setdefault(model, set()).add(
                    (b, tuple(row_shape), np.dtype(dtype).str))
            except TypeError:  # non-weakref-able duck-typed model: its
                pass           # dispatches conservatively count cold
        return report

    def shutdown(self) -> None:
        self._shutdown = True
        if self._worker is not None:
            self._worker.join(timeout=1.0)
        # fail any requests still queued so no client blocks forever
        self._fail_queued(RuntimeError("ParallelInference shut down"))
        if self._m_up is not None:
            self._m_up.set(0, model=self._metrics_name)

    def _fail_queued(self, error: Exception) -> None:
        while True:
            try:
                r = self._q.get_nowait()
            except queue.Empty:
                break
            r.fail_unclaimed(error)
        if self._m_depth is not None:
            self._m_depth.set(0, model=self._metrics_name)

    # ------------------------------------------------------------ dispatcher
    def _run(self) -> None:
        try:
            self._run_loop()
        except BaseException as e:  # noqa: BLE001 — containment seam
            # the crash must not strand waiters: record it, fail everything
            # queued, and let output() fail fast from now on (the serving
            # layer turns this into 503s instead of hung connections).
            # Under supervision the restart window is scheduled BEFORE the
            # error becomes visible (same lock as _ensure_dispatcher), so
            # a racing request can never read "crashed" without a window
            # and conclude the crash is terminal.
            retry_after = None
            with self._restart_lock:
                if self.restarts_used < self.max_restarts \
                        and not self._shutdown:
                    retry_after = self._restart_backoff.delay(
                        self.restarts_used + 1, seed=self._metrics_name)
                    self._restart_at = self._restart_clock() + retry_after
                else:
                    self._restart_at = None
                self.dispatcher_error = e
            if self._m_up is not None:
                self._m_up.set(0, model=self._metrics_name)
            # requests already claimed into the dying batch are no longer in
            # the queue — unblock them too (the thread is dead, no race);
            # same for a claimed carry request awaiting the next batch.
            # These requests' forwards DIED (dispatched=True — what the
            # circuit breaker counts); the still-queued ones never ran.
            crash = DispatcherCrashed(
                f"inference dispatcher died: {e!r}",
                retry_after_s=retry_after, dispatched=True)
            for r in self._inflight_batch:
                if not r.event.is_set():
                    r.error = crash
                    r.event.set()
            # the carry was claimed but its forward never ran — like the
            # queued requests it is a casualty, not breaker evidence
            undispatched = DispatcherCrashed(
                f"inference dispatcher died: {e!r}",
                retry_after_s=retry_after)
            if self._carry is not None and not self._carry.event.is_set():
                self._carry.error = undispatched
                self._carry.event.set()
                self._carry = None
            self._fail_queued(undispatched)

    def _run_loop(self) -> None:
        # a claimed request that would overflow the largest declared bucket
        # is carried into the NEXT batch instead of forcing a cold shape
        # (held on self so the crash handler can fail it — it is neither
        # queued nor in the in-flight batch while it waits)
        cap = self.buckets[-1]
        while not self._shutdown:
            if self._carry is not None:
                first, self._carry = self._carry, None
            else:
                try:
                    first = self._q.get(timeout=0.05)
                except queue.Empty:
                    continue
                if not first.claim():  # cancelled or expired while queued
                    continue
                if first.ctx is not None:
                    first.t_claim = time.perf_counter_ns()
            batch: List[_Request] = [first]
            # publish the batch list BEFORE coalescing: a crash anywhere
            # past the first claim must be able to fail these waiters
            # (appends below mutate this same list)
            self._inflight_batch = batch
            n = first.x.shape[0]
            deadline = self.wait_s
            t0 = time.monotonic()
            while n < self.max_batch_size:
                remaining = deadline - (time.monotonic() - t0)
                if remaining <= 0:
                    break
                try:
                    r = self._q.get(timeout=remaining)
                except queue.Empty:
                    break
                if not r.claim():
                    continue
                if r.ctx is not None:
                    r.t_claim = time.perf_counter_ns()
                if n + r.x.shape[0] > cap:
                    # keep every dispatched shape inside the declared
                    # bucket set: this request opens the next batch
                    self._carry = r
                    break
                batch.append(r)
                n += r.x.shape[0]
            if self._m_depth is not None:
                self._m_depth.set(self._q.qsize(), model=self._metrics_name)
            self._dispatch(batch, n)
            self._inflight_batch = []
        if self._carry is not None and not self._carry.event.is_set():
            self._carry.error = RuntimeError("ParallelInference shut down")
            self._carry.event.set()
            self._carry = None

    def _dispatch(self, batch: List[_Request], n: int) -> None:
        tracer = _trace.get_active_tracer()
        if tracer is None:
            return self._dispatch_batch(batch, n, None)
        # queue-wait attribution first: parented to each REQUEST's span
        # (the explicit handoff — contextvars never cross the thread hop)
        for r in batch:
            if r.ctx is not None and r.t_claim is not None:
                tracer.record("queue_wait", r.t_enqueue, r.t_claim,
                              parent=r.ctx, category="serve",
                              attrs={"model": self._metrics_name})
        # the device call runs INSIDE this span on the dispatcher thread, so
        # a compile of a new batch bucket nests under the batch that paid.
        # Compiles run synchronously on THIS thread, so the per-thread
        # compile-seconds delta around the span is exactly the compile
        # time the cost ledger must exclude from request attribution.
        compile_s0 = tracer.thread_compile_seconds()
        with tracer.span("batch_execute", category="serve",
                         attrs={"model": self._metrics_name, "rows": n,
                                "requests": len(batch)}) as sp:
            for r in batch:
                sp.add_link(r.ctx)
            self._dispatch_batch(batch, n, sp)
        if self.cost is not None and sp.end_ns is not None:
            compile_ms = (tracer.thread_compile_seconds() - compile_s0) * 1e3
            self.cost.record_batch(
                self._metrics_name,
                span_ms=(sp.end_ns - sp.start_ns) / 1e6,
                compile_ms=compile_ms,
                requests=[(r.ctx.trace_id if r.ctx is not None else None,
                           int(r.x.shape[0])) for r in batch])

    def _assemble(self, batch: List[_Request], n: int,
                  target: int) -> np.ndarray:
        """Coalesce request rows into ONE padded host batch.

        Hot path: rows are written straight into a preallocated per-bucket
        buffer (one host copy per row) instead of the old
        concatenate-then-pad-concatenate (three full-batch copies). Reuse
        is safe because the dispatcher is serial and ``_dispatch_batch``
        materializes the device result (``np.asarray``) before returning —
        by the time the buffer is rewritten, nothing reads the old batch.
        """
        first = batch[0].x
        row_shape, dtype = first.shape[1:], first.dtype
        homogeneous = all(r.x.shape[1:] == row_shape and r.x.dtype == dtype
                          for r in batch[1:])
        if not (self.reuse_pad_buffer and homogeneous):
            x = np.concatenate([np.asarray(r.x) for r in batch], axis=0)
            if target > n:
                pad = np.zeros((target - n,) + x.shape[1:], x.dtype)
                x = np.concatenate([x, pad], axis=0)
            return x
        key = (target, row_shape, dtype.str)
        buf = self._pad_buffers.get(key)
        if buf is None:
            while len(self._pad_buffers) >= self._max_pad_buffers:
                self._pad_buffers.pop(next(iter(self._pad_buffers)))
            buf = np.zeros((target,) + tuple(row_shape), dtype)
            self._pad_buffers[key] = buf
        off = 0
        for r in batch:
            k = r.x.shape[0]
            buf[off:off + k] = r.x
            off += k
        if off < target:
            buf[off:] = 0  # stale rows from the last batch must not leak
        return buf

    def _dispatch_batch(self, batch: List[_Request], n: int, sp) -> None:
        try:
            # pad to a declared bucket → bounded, pre-warmed compiled shapes
            target, declared = self._bucket_for(n)
            x = self._assemble(batch, n, target)
            model = self._model()
            # cold = off-bucket, OR a declared bucket whose (shape, dtype)
            # signature warmup never executed FOR THIS MODEL (an int batch
            # against a float-warmed model, or a model published through
            # update_model() without its own warmup — either way a new jit
            # signature, a live compile). Lazy mode (no warmup ever ran)
            # keeps declared buckets uncounted.
            keys = None
            any_warmed = len(self._warmed_keys) > 0
            if any_warmed:
                try:
                    keys = self._warmed_keys.get(model)
                except TypeError:
                    keys = None
            cold = not declared or (
                any_warmed and
                (keys is None or
                 (target, x.shape[1:], x.dtype.str) not in keys))
            if cold and self._m_cold is not None:
                self._m_cold.inc(model=self._metrics_name)
            if sp is not None:
                sp.set_attribute("padded_to", int(target))
                if cold:
                    sp.set_attribute("cold_bucket", True)
            # serving chaos seam: keyed on (model, dispatch seq). A
            # crash_forward raises a BaseException that deliberately
            # escapes this handler and kills the dispatcher thread
            seq = self._forward_seq
            self._forward_seq += 1
            _faultinject.on_forward(self._metrics_name, seq)
            out = np.asarray(model.output(self._to_device(x)))
            self.batches_dispatched += 1
            if self._m_batch is not None:
                self._m_batch.observe(n, model=self._metrics_name)
            off = 0
            for r in batch:
                k = r.x.shape[0]
                r.result = out[off:off + k]
                r.served_model = model
                off += k
                r.event.set()
        except Exception as e:  # deliver errors to waiting clients
            if sp is not None:
                sp.error = f"{type(e).__name__}: {e}"
            for r in batch:
                r.error = e
                r.event.set()
