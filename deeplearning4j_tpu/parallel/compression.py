"""Threshold gradient compression — jittable, fixed-capacity.

Reference: ``EncodingHandler.java`` (thresholdEncode at ``:139``, adaptive
threshold decay/"shake" at ``:28,69-94``) and
``EncodedGradientsAccumulator.java`` (decode ``:257,292``, worst-case buffer
sizing ``getOptimalBufferSize:127-134``). The reference encodes each gradient
update as a sparse list of indices whose residual magnitude exceeds a
threshold, transmits ±threshold per index over Aeron UDP, and keeps the
*residual* (un-sent remainder) locally — Strom-style 1-bit compression.

On TPU, intra-slice sync is a hardware all-reduce over ICI and needs no
compression; this codec exists for the **DCN / cross-pod** path and for
capability parity. The design constraint is XLA-compatibility: encoding is
data-dependent, so we use a *fixed-capacity* index buffer (the reference
sizes for the worst case too) with scatter-in-bounds drop semantics — static
shapes, fully jittable, usable inside pjit/shard_map programs.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp


class Encoded(NamedTuple):
    """Sparse threshold-encoded gradient chunk (fixed capacity)."""

    indices: jax.Array   # [capacity] int32, -1 = empty slot
    signs: jax.Array     # [capacity] int8 (+1 / -1, 0 for empty)
    count: jax.Array     # [] int32 — number of valid entries
    threshold: jax.Array  # [] float32 — the step magnitude


def optimal_capacity(size: int, sparsity: float = 1.0 / 16.0, floor: int = 16) -> int:
    """Fixed buffer size for a given worst-case sparsity (EncodedGradientsAccumulator
    getOptimalBufferSize:127-134 sizes for paramsLength/16 + overhead, hence
    the 1/16 default)."""
    return max(floor, int(size * sparsity))


from functools import partial


@partial(jax.jit, static_argnums=2)
def _encode(residual: jax.Array, threshold: jax.Array, capacity: int
            ) -> Tuple[Encoded, jax.Array]:
    r = residual.ravel()
    flags = jnp.abs(r) >= threshold
    pos = jnp.cumsum(flags) - 1  # slot for each flagged element
    fits = flags & (pos < capacity)
    slot = jnp.where(fits, pos, capacity)  # capacity = out-of-bounds → dropped
    idx_buf = jnp.full((capacity,), -1, jnp.int32)
    idx_buf = idx_buf.at[slot].set(jnp.arange(r.shape[0], dtype=jnp.int32),
                                   mode="drop")
    sign_buf = jnp.zeros((capacity,), jnp.int8)
    sign_buf = sign_buf.at[slot].set(jnp.sign(r).astype(jnp.int8), mode="drop")
    count = jnp.minimum(jnp.sum(flags), capacity).astype(jnp.int32)
    # residual keeps the un-sent remainder: sent elements lose ±threshold
    sent = fits * jnp.sign(r) * threshold
    new_residual = (r - sent).reshape(residual.shape)
    return Encoded(idx_buf, sign_buf, count,
                   jnp.asarray(threshold, jnp.float32)), new_residual


def threshold_encode(residual: jax.Array, threshold, capacity: Optional[int] = None
                     ) -> Tuple[Encoded, jax.Array]:
    """Encode ``residual`` → (sparse message, new residual). Jittable."""
    if capacity is None:
        capacity = optimal_capacity(residual.size)
    return _encode(residual, jnp.asarray(threshold, residual.dtype), capacity)


@partial(jax.jit, static_argnums=1)
def threshold_decode(msg: Encoded, size: int) -> jax.Array:
    """Decode a sparse message into a dense update of ``size`` elements
    (EncodedGradientsAccumulator.java:257 applies this to local params)."""
    out = jnp.zeros((size,), jnp.float32)
    vals = msg.signs.astype(jnp.float32) * msg.threshold
    idx = jnp.where(msg.indices >= 0, msg.indices, size)  # -1 → dropped
    return out.at[idx].add(vals, mode="drop")


class EncodingHandler:
    """Stateful residual/threshold manager (EncodingHandler.java parity).

    Keeps the residual between calls and adapts the threshold: if an encode
    pass sends too few elements, decay the threshold; if the buffer
    saturates, boost it ("shake", ``EncodingHandler.java:69-94``).
    """

    def __init__(self, threshold: float = 1e-3, *, min_threshold: float = 1e-5,
                 decay: float = 0.95, boost: float = 1.2,
                 capacity: Optional[int] = None):
        self.threshold = float(threshold)
        self.min_threshold = float(min_threshold)
        self.decay = float(decay)
        self.boost = float(boost)
        self.capacity = capacity
        self._residual = None

    def encode(self, update: jax.Array) -> Encoded:
        if self._residual is None:
            self._residual = jnp.zeros_like(update)
        cap = self.capacity or optimal_capacity(update.size)
        msg, self._residual = threshold_encode(
            self._residual + update, self.threshold, cap)
        n = int(msg.count)
        if n >= cap:  # saturated → raise threshold next round
            self.threshold *= self.boost
        elif n < max(1, cap // 8):  # sparse → lower threshold (decay)
            self.threshold = max(self.min_threshold, self.threshold * self.decay)
        return msg

    def reset(self) -> None:
        self._residual = None
