"""Elastic training supervisor: automatic failure recovery + shrink.

The reference fixes worker membership at job start
(``SharedTrainingWrapper.java:131-156``) and delegates fault tolerance to
Spark task retry — losing a worker permanently ends the job. Every
ingredient for doing better already exists in this repo (kill-and-resume
choreography in ``tests/test_multiprocess.py``, ``util/preemption.py``,
``util/orbax_checkpoint.py`` rotation, ``SharedTrainingMaster.save_state``)
but lived in test code. This module is the library composition:

``ElasticJobSupervisor`` launches N worker processes from a
:class:`WorkerSpec`, tracks liveness via per-worker heartbeat files on an
injectable clock, and on worker death (SIGKILL-style, no grace) runs the
full recovery loop automatically:

1. first observed death is the *primary* victim; the surviving peers are
   killed too (their collectives can never complete) and treated as
   collateral — restarted free of charge;
2. decide **restart-in-place** (the victim still has restart budget:
   exponential backoff + deterministic jitter, so a crash-looping worker
   cannot storm) vs **shrink to the surviving slice** (budget exhausted,
   and the remaining slots still satisfy ``min_workers``) vs **fail
   loudly** (cannot shrink further);
3. re-form the world: fresh coordinator port, process ids renumbered
   0..M-1 over the surviving slots, a new generation token;
4. workers restore the latest *eligible* orbax rotation checkpoint and
   resume ``SharedTrainingMaster`` training.

**Generation fencing** makes checkpoints written by stale workers from a
previous world un-restorable: every generation gets a token; workers
stamp each committed checkpoint step with their token, and re-read the
supervisor's ``elastic_generation.json`` before each save (a stale token
aborts the save). When a generation ends, the supervisor *fences* its
token in a persistent ledger together with a snapshot of the steps it had
committed — a stamp carrying a fenced token that is NOT in the snapshot
(i.e. written after the fence by a zombie) is never restored. The ledger
survives supervisor restarts, so a brand-new supervisor over an existing
checkpoint directory resumes from the previous lineage's snapshot.

At pod scale (round 12) the substrate grows three capabilities:

- **Host failure domains** (``num_hosts``/``min_hosts``): workers are
  grouped into host groups and the whole decision ladder operates on
  hosts — any worker death victimizes its host group, budgets charge
  the host (one lost machine = one fault), shrink removes whole hosts
  so per-host slice shapes stay valid. The coordinator bind/advertise
  address is configurable (``WorkerSpec.bind_host``/``advertise_host``,
  ``DL4J_TPU_ELASTIC_BIND_HOST``/``_ADVERTISE_HOST``) instead of
  hardcoded loopback.
- **Async sharded checkpointing** (:class:`AsyncCheckpointSession`,
  ``run_elastic_worker(save_mode="async")``): every rank snapshots its
  shard on the training thread and a bounded background pipeline does
  the writes; the stamp commits only after ALL ranks' finalize landed,
  so a crash at any phase of an overlapped save leaves a torn step that
  is never restorable, and a slow filesystem backpressures through the
  in-flight window instead of accumulating.
- **Partition tolerance** (``progress_timeout_s``): a step-progress
  watchdog distinguishes a partition (heartbeats alive — workers beat
  from a background thread when armed — but no step progress anywhere)
  from a slow worker, and resolves it as death of the least-progressed
  side.

Failure paths are CI-provable on subprocess CPU workers via the
deterministic fault harness (``util/faultinject.py``,
``DL4J_TPU_FAULT_PLAN`` — incl. host-scoped ``kill_host``/``partition``/
``slow_save`` and commit-phase kills). Everything reports through the
existing observability stack: ``elastic_restarts_total`` /
``elastic_world_size`` / ``elastic_hosts`` / ``elastic_partitions_total``
metrics, ``elastic_recovery``/``elastic_async_save`` spans, structured
logs, and the shipped restart-storm alert rule
(``examples/elastic_alert_rules.json``).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import socket
import subprocess
import sys
import threading
import uuid
from typing import Dict, List, Optional, Sequence

from deeplearning4j_tpu.util.fsio import atomic_write_text as _atomic_write

# Environment seam between supervisor and workers. Everything a worker
# needs to join its generation arrives through these variables.
ENV_COORDINATOR = "DL4J_TPU_ELASTIC_COORDINATOR"
ENV_NUM_PROCESSES = "DL4J_TPU_ELASTIC_NUM_PROCESSES"
ENV_PROCESS_ID = "DL4J_TPU_ELASTIC_PROCESS_ID"
ENV_SLOT = "DL4J_TPU_ELASTIC_SLOT"
ENV_HOST = "DL4J_TPU_ELASTIC_HOST"
ENV_NUM_HOSTS = "DL4J_TPU_ELASTIC_NUM_HOSTS"
ENV_GENERATION = "DL4J_TPU_ELASTIC_GENERATION"
ENV_TOKEN = "DL4J_TPU_ELASTIC_TOKEN"
ENV_CKPT_DIR = "DL4J_TPU_ELASTIC_CKPT_DIR"
ENV_HEARTBEAT = "DL4J_TPU_ELASTIC_HEARTBEAT_FILE"
ENV_RESTORE_STEP = "DL4J_TPU_ELASTIC_RESTORE_STEP"
ENV_ELIGIBLE_STEPS = "DL4J_TPU_ELASTIC_ELIGIBLE_STEPS"
# pod mesh over the elastic env: the per-host mesh slice shape
# (``parse_mesh_axes`` grammar, e.g. "model=2" — the data axis is always
# the generation's process count) and an optional sharding-rules JSON
# path workers place params with (absent → DEFAULT_2D_RULES)
ENV_MESH = "DL4J_TPU_ELASTIC_MESH"
ENV_SHARDING_RULES = "DL4J_TPU_ELASTIC_SHARDING_RULES"
ENV_PROGRESS_BEAT = "DL4J_TPU_ELASTIC_PROGRESS_BEAT_S"
# operator-level coordinator addressing (read by WorkerSpec, overridable
# per-spec): where process 0 binds its coordination service and the
# address peers dial — the pod-scale replacement for hardcoded loopback
ENV_BIND_HOST = "DL4J_TPU_ELASTIC_BIND_HOST"
ENV_ADVERTISE_HOST = "DL4J_TPU_ELASTIC_ADVERTISE_HOST"
# fleet observability seam: the supervisor's per-generation elastic_job
# span context (W3C traceparent — worker spans parent into the job
# trace), the directory workers stream their spans into (crash-durable
# JSONL, merged by observe.export.merge_chrome_traces), and the file a
# worker writes its Prometheus exposition snapshots to (scraped by the
# supervisor's FleetRegistry). All three absent → every hook is a no-op.
ENV_TRACEPARENT = "DL4J_TPU_ELASTIC_TRACEPARENT"
ENV_TRACE_DIR = "DL4J_TPU_ELASTIC_TRACE_DIR"
ENV_METRICS_FILE = "DL4J_TPU_ELASTIC_METRICS_FILE"

GENERATION_FILE = "elastic_generation.json"
LEDGER_FILE = "elastic_ledger.json"
_STAMP_PREFIX = "elastic_step_"


def _free_port(bind_host: str = "127.0.0.1") -> int:
    family = socket.AF_INET6 if ":" in bind_host else socket.AF_INET
    s = socket.socket(family)
    s.bind((bind_host, 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _join_host_port(host: str, port) -> str:
    """``host:port`` with IPv6 literals bracketed — ``fd00::1`` must
    become ``[fd00::1]:4711`` or the joined address is unparseable."""
    if ":" in host and not host.startswith("["):
        return f"[{host}]:{port}"
    return f"{host}:{port}"


def _stamp_path(ckpt_dir: str, step: int) -> str:
    return os.path.join(ckpt_dir, f"{_STAMP_PREFIX}{int(step):08d}.json")


def write_step_stamp(ckpt_dir: str, step: int, token: str, generation: int,
                     world_size: int) -> None:
    """Commit marker for a checkpoint step: written only after the orbax
    save finalized AND every rank's master state landed. Carries the
    generation token — the fencing unit."""
    _atomic_write(_stamp_path(ckpt_dir, step), json.dumps(
        {"step": int(step), "token": token, "generation": int(generation),
         "world_size": int(world_size)}))


def read_step_stamps(ckpt_dir: str) -> List[dict]:
    """All committed step stamps, oldest first. Unreadable/partial stamps
    are skipped (a torn stamp simply means that step never committed)."""
    out = []
    try:
        names = sorted(os.listdir(ckpt_dir))
    except OSError:
        return []
    for name in names:
        if not (name.startswith(_STAMP_PREFIX) and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(ckpt_dir, name), encoding="utf-8") as fh:
                s = json.load(fh)
            out.append({"step": int(s["step"]), "token": str(s["token"]),
                        "generation": int(s.get("generation", 0)),
                        "world_size": int(s.get("world_size", 0))})
        except (OSError, ValueError, KeyError, TypeError):
            continue
    return out


class GenerationLedger:
    """Persistent record of every generation this job lineage formed.

    Eligibility rule for restoring a stamped checkpoint step:

    - its token belongs to a generation this ledger knows, AND
    - that generation is still open, OR the step is in the snapshot taken
      when the generation was fenced.

    A zombie worker from a fenced generation can still *write* files, but
    nothing it writes after the fence can ever be chosen for restore.
    Loading an existing ledger fences every recorded generation against
    the stamps currently on disk — a new supervisor inherits the old
    lineage's committed steps and nothing more.
    """

    def __init__(self, ckpt_dir: str):
        self.ckpt_dir = ckpt_dir
        self.path = os.path.join(ckpt_dir, LEDGER_FILE)
        self.generations: List[dict] = []
        if os.path.exists(self.path):
            with open(self.path, encoding="utf-8") as fh:
                self.generations = json.load(fh)["generations"]
            known = read_step_stamps(ckpt_dir)
            for g in self.generations:
                if not g.get("fenced"):
                    g["fenced"] = True
                    g["known_steps"] = sorted(
                        s["step"] for s in known if s["token"] == g["token"])
            self._persist()

    def _persist(self) -> None:
        os.makedirs(self.ckpt_dir, exist_ok=True)
        _atomic_write(self.path,
                      json.dumps({"generations": self.generations}, indent=1))

    def open_generation(self, generation: int, token: str,
                        world: Sequence[int]) -> None:
        self.generations.append({"generation": int(generation),
                                 "token": token, "world": list(world),
                                 "fenced": False, "known_steps": []})
        self._persist()

    def fence(self, token: str) -> None:
        """Close a generation: snapshot the steps it committed so far;
        later writes under its token become un-restorable."""
        known = [s["step"] for s in read_step_stamps(self.ckpt_dir)
                 if s["token"] == token]
        for g in self.generations:
            if g["token"] == token:
                g["fenced"] = True
                g["known_steps"] = sorted(known)
        self._persist()

    def eligible(self, token: str, step: int) -> bool:
        for g in self.generations:
            if g["token"] != token:
                continue
            return (not g["fenced"]) or int(step) in g["known_steps"]
        return False


# -- supervisor --------------------------------------------------------------

@dataclasses.dataclass
class WorkerSpec:
    """How to launch one worker process. The elastic context (coordinator,
    world size, renumbered process id, generation token, checkpoint dir,
    heartbeat path, restore step) is injected through the environment —
    ``argv`` stays the user's command line."""

    argv: List[str]
    env: Optional[Dict[str, str]] = None  # base env; default os.environ
    cwd: Optional[str] = None
    # each worker must own exactly ONE local device; a host-device
    # multiplier inherited from a test/bench parent would make every
    # worker claim the whole virtual mesh
    single_device: bool = True
    # where process 0's jax.distributed coordinator listens and the
    # address the generation's workers dial. None → the
    # DL4J_TPU_ELASTIC_BIND_HOST / DL4J_TPU_ELASTIC_ADVERTISE_HOST env
    # vars, then loopback — the pre-pod behavior stays the default
    bind_host: Optional[str] = None
    advertise_host: Optional[str] = None
    # pod mesh: each worker owns a mesh SLICE of this shape (ICI inside
    # the host); the data axis always spans the generation's processes
    # (DCN across hosts) and must be -1/absent here. E.g.
    # ``{"model": 2}`` → every worker gets 2 local devices sharded over
    # the model axis while training stays data-parallel across workers.
    mesh_axes: Optional[Dict[str, int]] = None
    # sharding-rules JSON path forwarded to workers (None → the shipped
    # DEFAULT_2D_RULES)
    sharding_rules: Optional[str] = None

    def local_mesh_devices(self) -> int:
        """Devices each worker's mesh slice needs (the product of the
        non-data axes; 1 = classic one-device-per-worker)."""
        n = 1
        for name, size in (self.mesh_axes or {}).items():
            if name == "data":
                continue
            n *= max(1, int(size))
        return n

    def resolved_bind_host(self) -> str:
        if self.bind_host:
            return self.bind_host
        return os.environ.get(ENV_BIND_HOST) or "127.0.0.1"

    def resolved_advertise_host(self) -> str:
        """The address workers dial; defaults to the bind host — except
        a wildcard bind (0.0.0.0 / ::), which is not dialable and must
        be advertised as something routable."""
        if self.advertise_host:
            return self.advertise_host
        adv = os.environ.get(ENV_ADVERTISE_HOST)
        if adv:
            return adv
        bind = self.resolved_bind_host()
        if bind in ("0.0.0.0", "::"):
            return socket.gethostname()
        return bind

    def environment(self) -> Dict[str, str]:
        env = dict(os.environ if self.env is None else self.env)
        if self.single_device and "XLA_FLAGS" in env:
            # strip ONLY the host-device multiplier; the operator's other
            # XLA flags (dump dirs, tuning) must reach the workers
            kept = [t for t in env["XLA_FLAGS"].split()
                    if not t.startswith(
                        "--xla_force_host_platform_device_count")]
            if kept:
                env["XLA_FLAGS"] = " ".join(kept)
            else:
                del env["XLA_FLAGS"]
        n_local = self.local_mesh_devices()
        if n_local > 1:
            # the worker owns a multi-device mesh slice: on the CPU
            # (host) platform that slice must be forced into existence;
            # on real accelerators the flag is inert and the host's
            # locally-attached chips form the slice
            kept = [t for t in env.get("XLA_FLAGS", "").split()
                    if t and not t.startswith(
                        "--xla_force_host_platform_device_count")]
            kept.append(f"--xla_force_host_platform_device_count={n_local}")
            env["XLA_FLAGS"] = " ".join(kept)
        return env


@dataclasses.dataclass
class BackoffPolicy:
    """Restart budgeting: exponential backoff with deterministic jitter.

    ``max_restarts`` is the per-slot budget of post-liveness restarts; a
    slot that exhausts it is shrunk away (or, at ``min_workers``, fails
    the job). Jitter is hashed from ``(seed, attempt)`` — reproducible,
    no RNG state, but still de-synchronizes a fleet of supervisors."""

    base_s: float = 1.0
    factor: float = 2.0
    max_s: float = 60.0
    jitter: float = 0.1
    max_restarts: int = 2

    def delay(self, attempt: int, seed: str = "") -> float:
        d = min(self.max_s, self.base_s * self.factor ** max(0, attempt - 1))
        if self.jitter:
            h = int(hashlib.sha256(f"{seed}:{attempt}".encode())
                    .hexdigest()[:8], 16)
            d *= 1.0 + self.jitter * (2.0 * (h / 0xffffffff) - 1.0)
        return d


class SubprocessLauncher:
    """Default process backend (injectable: unit tests drive the
    supervisor with fake handles and a manual clock)."""

    def launch(self, argv: List[str], env: Dict[str, str],
               cwd: Optional[str], log_path: str):
        fh = open(log_path, "wb")
        proc = subprocess.Popen(argv, env=env, cwd=cwd, stdout=fh,
                                stderr=subprocess.STDOUT)
        proc._elastic_log = fh  # closed on reap
        return proc


@dataclasses.dataclass
class _Slot:
    """Supervisor-internal per-slot state (survives generations; restart
    budgets live on the slot's failure domain — :class:`_Domain`)."""

    slot_id: int
    # per-generation fields:
    proc: object = None
    log_path: str = ""
    hb_path: str = ""
    last_beat: Optional[str] = None
    last_beat_at_ms: int = 0
    live: bool = False        # has this incarnation ever heartbeat?
    done: bool = False
    exit_code: Optional[int] = None
    death_reason: Optional[str] = None
    # step-progress tracking (partition watchdog): the newest training
    # step parsed out of the heartbeat payload, when it changed, and
    # whether it ever ADVANCED past the first reported value this
    # generation (a generation that never progressed is starting up —
    # first-step compile — not partitioned)
    last_step: Optional[int] = None
    last_step_at_ms: int = 0
    progressed: bool = False


@dataclasses.dataclass
class _Domain:
    """Restart budget for one failure domain — a host group when the job
    has host grouping, a single slot otherwise. Charging the domain (not
    the slot) is what makes a lost HOST one fault instead of
    workers-per-host simultaneous budget exhaustions."""

    domain_id: object
    restarts_used: int = 0
    startup_retries_used: int = 0


@dataclasses.dataclass
class GenerationRecord:
    generation: int
    token: str
    world: List[int]
    restore_step: Optional[int]
    outcome: str = "running"          # completed | recovered | failed
    dead_slots: List[int] = dataclasses.field(default_factory=list)
    primary_slot: Optional[int] = None
    decision: Optional[str] = None    # restart | shrink | fail
    primary_host: Optional[int] = None  # victim host group (host mode)


@dataclasses.dataclass
class ElasticJobResult:
    status: str                       # completed | failed
    reason: Optional[str] = None
    generations: List[GenerationRecord] = dataclasses.field(
        default_factory=list)
    restarts_total: int = 0
    backoff_delays: List[float] = dataclasses.field(default_factory=list)

    @property
    def final_world(self) -> List[int]:
        return self.generations[-1].world if self.generations else []


class ElasticJobFailed(RuntimeError):
    """The job could not be kept alive (restart budget exhausted and the
    world cannot shrink below ``min_workers``, or the job deadline
    passed). Carries the full :class:`ElasticJobResult`."""

    def __init__(self, message: str, result: ElasticJobResult):
        super().__init__(message)
        self.result = result


class ElasticJobSupervisor:
    """Launch, watch and heal an elastic data-parallel training job.

    Every time-dependent decision runs on an injectable
    :class:`~deeplearning4j_tpu.parallel.time_source.TimeSource` +
    ``sleep_fn`` pair, and process management goes through an injectable
    launcher — the whole state machine is unit-testable with a manual
    clock and fake processes, no real sleeps or subprocesses.
    """

    def __init__(self, spec: WorkerSpec, num_workers: int, *,
                 min_workers: int = 1, ckpt_dir: str,
                 num_hosts: Optional[int] = None, min_hosts: int = 1,
                 backoff: Optional[BackoffPolicy] = None,
                 heartbeat_timeout_s: float = 120.0,
                 startup_timeout_s: float = 300.0,
                 startup_retries: int = 3,
                 poll_interval_s: float = 0.25,
                 job_deadline_s: Optional[float] = None,
                 progress_timeout_s: Optional[float] = None,
                 clock=None, sleep_fn=None, launcher=None,
                 metrics=None, port_fn=_free_port,
                 job_id: str = "elastic",
                 fleet=None, metrics_port: Optional[int] = None,
                 incidents: bool = True,
                 incident_dir: Optional[str] = None):
        if num_workers < 1 or min_workers < 1 or min_workers > num_workers:
            raise ValueError(
                f"need 1 <= min_workers <= num_workers, got "
                f"{min_workers}/{num_workers}")
        if num_hosts is not None:
            if num_hosts < 1 or num_workers % num_hosts != 0:
                raise ValueError(
                    f"num_hosts must divide num_workers evenly (per-host "
                    f"slice shapes), got {num_hosts}/{num_workers}")
            if min_hosts < 1 or min_hosts > num_hosts:
                raise ValueError(
                    f"need 1 <= min_hosts <= num_hosts, got "
                    f"{min_hosts}/{num_hosts}")
        self.spec = spec
        self.num_workers = num_workers
        self.min_workers = min_workers
        #: None → each worker is its own failure domain (the pre-pod
        #: behavior); N → workers are grouped into N host groups of
        #: num_workers/N slots and EVERY recovery decision operates on
        #: whole hosts (a worker death marks its host the victim,
        #: shrink removes the host, budgets charge the host)
        self.num_hosts = num_hosts
        self.min_hosts = min_hosts
        self.progress_timeout_s = progress_timeout_s
        self.ckpt_dir = os.path.abspath(ckpt_dir)
        self.backoff = backoff if backoff is not None else BackoffPolicy()
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.startup_timeout_s = startup_timeout_s
        self.startup_retries = startup_retries
        self.poll_interval_s = poll_interval_s
        self.job_deadline_s = job_deadline_s
        if clock is None:
            from deeplearning4j_tpu.parallel.time_source import (
                get_time_source)
            clock = get_time_source()
        self.clock = clock
        import time as _time
        self.sleep_fn = sleep_fn if sleep_fn is not None else _time.sleep
        self.launcher = launcher if launcher is not None \
            else SubprocessLauncher()
        if metrics is None:
            from deeplearning4j_tpu.observe import default_registry
            metrics = default_registry()
        self.metrics = metrics
        self.port_fn = port_fn
        self.job_id = job_id
        os.makedirs(self.ckpt_dir, exist_ok=True)
        self.ledger = GenerationLedger(self.ckpt_dir)
        from deeplearning4j_tpu.observe import get_logger
        self._log = get_logger("elastic")
        self._restarts = metrics.counter(
            "elastic_restarts_total",
            "Elastic recovery events by decision", ("decision",))
        self._deaths = metrics.counter(
            "elastic_worker_deaths_total",
            "Worker deaths observed by the supervisor", ("reason",))
        self._world_gauge = metrics.gauge(
            "elastic_world_size", "Current elastic world size")
        self._gen_gauge = metrics.gauge(
            "elastic_generation", "Current elastic generation number")
        self._hosts_gauge = metrics.gauge(
            "elastic_hosts", "Current number of live host groups")
        self._partitions = metrics.counter(
            "elastic_partitions_total",
            "Network partitions resolved by the step-progress watchdog")
        self._domains: Dict[object, _Domain] = {}
        # -- fleet observability (each piece a no-op when absent) ---------
        #: FleetRegistry serving the job-wide metrics union; created
        #: automatically when --metrics-port asks for the scrape endpoint
        self.fleet = fleet
        if self.fleet is None and metrics_port is not None:
            from deeplearning4j_tpu.observe.fleet import FleetRegistry
            self.fleet = FleetRegistry(local=metrics)
        self.metrics_port = metrics_port
        self.metrics_server = None
        #: optional AlertManager surfaced at the metrics server's
        #: /alerts endpoint (the CLI attaches its --alerts manager here
        #: before run())
        self.alerts = None
        #: optional SLOSet surfaced at the metrics server's /slo
        #: endpoint (the CLI attaches its --slo set here before run())
        self.slo = None
        #: where workers stream crash-durable span files (set per
        #: generation only while a tracer is active in THIS process)
        self.trace_dir = os.path.join(self.ckpt_dir, "trace")
        self.incidents = None
        if incidents:
            from deeplearning4j_tpu.observe.incident import IncidentRecorder
            self.incidents = IncidentRecorder(
                incident_dir if incident_dir is not None
                else os.path.join(self.ckpt_dir, "incidents"))

    # -- failure domains ---------------------------------------------------
    def host_of(self, slot_id: int) -> Optional[int]:
        """Host group of a slot (stable across generations: assignment is
        by the ORIGINAL world, so renumbering never moves a worker
        between failure domains). None without host grouping."""
        if self.num_hosts is None:
            return None
        return slot_id // (self.num_workers // self.num_hosts)

    def _domain_of(self, slot_id: int) -> _Domain:
        did = ("host", self.host_of(slot_id)) if self.num_hosts is not None \
            else ("slot", slot_id)
        if did not in self._domains:
            self._domains[did] = _Domain(domain_id=did)
        return self._domains[did]

    def _domain_slots(self, slot_id: int, world: List[int]) -> List[int]:
        """Every slot of ``slot_id``'s failure domain still in the
        world — the unit the decision ladder kills/shrinks together."""
        if self.num_hosts is None:
            return [slot_id]
        h = self.host_of(slot_id)
        return [s for s in world if self.host_of(s) == h]

    def _live_hosts(self, world: List[int]) -> int:
        if self.num_hosts is None:
            return len(world)
        return len({self.host_of(s) for s in world})

    # -- checkpoint eligibility ------------------------------------------
    def eligible_steps(self) -> List[int]:
        """Every committed checkpoint step whose generation stamp passes
        the fence, ascending — the ONLY steps a worker may restore
        (including its corrupt-step fallback walk: a zombie's unfenced
        write must not become restorable just because the newest eligible
        step is torn)."""
        return sorted({s["step"] for s in read_step_stamps(self.ckpt_dir)
                       if self.ledger.eligible(s["token"], s["step"])})

    def latest_eligible_step(self) -> Optional[int]:
        """Newest committed checkpoint step whose generation stamp passes
        the fence — what the next generation restores."""
        steps = self.eligible_steps()
        return steps[-1] if steps else None

    # -- main loop --------------------------------------------------------
    def run(self, *, raise_on_failure: bool = True) -> ElasticJobResult:
        if self.metrics_port is not None and self.metrics_server is None:
            from deeplearning4j_tpu.observe.fleet import FleetMetricsServer
            self.metrics_server = FleetMetricsServer(
                self.fleet, port=self.metrics_port, alerts=self.alerts,
                slo=getattr(self, "slo", None))
            self.metrics_server.start()
            self._log.info("fleet metrics server up",
                           url=self.metrics_server.url())
        try:
            return self._run(raise_on_failure=raise_on_failure)
        finally:
            if self.metrics_server is not None:
                self.metrics_server.stop()
                self.metrics_server = None

    def _gen_span_start(self, generation, token, world, restore_step):
        """Per-generation ``elastic_job`` root span (None while tracing
        is off). Its context ships to workers as a W3C traceparent, so
        every worker's train/recovery/checkpoint spans parent into one
        job trace per generation."""
        from deeplearning4j_tpu.observe import get_active_tracer
        tr = get_active_tracer()
        if tr is None:
            return None
        return tr.start_span(
            "elastic_job", category="elastic",
            attrs={"job_id": self.job_id, "generation": generation,
                   "token": token, "world": str(world),
                   "restore_step": restore_step})

    def _gen_span_end(self, gen_span, outcome: str) -> None:
        if gen_span is None:
            return
        from deeplearning4j_tpu.observe import get_active_tracer
        tr = get_active_tracer()
        gen_span.set_attribute("outcome", outcome)
        if tr is not None:
            tr.end_span(gen_span)

    def _record_decision(self, gen_span, generation, decision, primary,
                         reason) -> None:
        """The supervisor's restart/shrink/fail call as a point-in-time
        span (category ``decision`` — merge_chrome_traces renders it as
        an instant event on the supervisor row)."""
        from deeplearning4j_tpu.observe import get_active_tracer
        tr = get_active_tracer()
        if tr is None:
            return
        import time as _time
        now = _time.perf_counter_ns()
        tr.record(f"elastic_{decision}", now, now, category="decision",
                  parent=None if gen_span is None else gen_span.context,
                  attrs={"generation": generation, "decision": decision,
                         "primary_slot": primary.slot_id,
                         "reason": reason or ""})

    def _run(self, *, raise_on_failure: bool) -> ElasticJobResult:
        # the trace dir holds THIS run's span streams: a previous run on
        # the same ckpt_dir reuses generation numbering, and its stale
        # files would contaminate write_fleet_trace's merge (hours-old
        # anchors stretch the timeline) and incident bundles (old
        # evidence presented as current)
        try:
            for name in os.listdir(self.trace_dir):
                if name.endswith(".jsonl"):
                    os.unlink(os.path.join(self.trace_dir, name))
        except OSError:
            pass
        result = ElasticJobResult(status="failed")
        world = list(range(self.num_workers))
        generation = 0
        deadline_ms = None
        if self.job_deadline_s is not None:
            deadline_ms = self.clock.current_time_millis() \
                + int(self.job_deadline_s * 1000)
        slots = {i: _Slot(slot_id=i) for i in world}
        while True:
            generation += 1
            token = f"g{generation}-{uuid.uuid4().hex[:12]}"
            eligible = self.eligible_steps()
            restore_step = eligible[-1] if eligible else None
            record = GenerationRecord(generation=generation, token=token,
                                      world=list(world),
                                      restore_step=restore_step)
            result.generations.append(record)
            self.ledger.open_generation(generation, token, world)
            _atomic_write(os.path.join(self.ckpt_dir, GENERATION_FILE),
                          json.dumps({"generation": generation,
                                      "token": token,
                                      "world_size": len(world)}))
            gen_span = self._gen_span_start(generation, token, world,
                                            restore_step)
            self._launch_generation(generation, token, world, slots,
                                    restore_step, eligible,
                                    gen_span=gen_span)
            self._world_gauge.set(len(world))
            self._gen_gauge.set(generation)
            self._hosts_gauge.set(self._live_hosts(world))
            self._log.info("generation started", generation=generation,
                           token=token, world=world,
                           restore_step=restore_step)
            outcome, dead = self._watch(
                [slots[s] for s in world], deadline_ms)
            self.ledger.fence(token)
            if outcome == "completed":
                record.outcome = "completed"
                result.status = "completed"
                self._gen_span_end(gen_span, "completed")
                self._log.info("job completed", generation=generation,
                               world=world)
                return result
            if outcome == "deadline":
                record.outcome = "failed"
                self._kill_world([slots[s] for s in world])
                self._gen_span_end(gen_span, "deadline")
                result.reason = (f"job deadline "
                                 f"({self.job_deadline_s}s) exceeded")
                return self._fail(result, raise_on_failure)

            # ---- recovery -------------------------------------------------
            from deeplearning4j_tpu.observe import span
            primary = dead[0]
            record.outcome = "recovered"
            record.dead_slots = [d.slot_id for d in dead]
            record.primary_slot = primary.slot_id
            record.primary_host = self.host_of(primary.slot_id)
            with span("elastic_recovery", category="elastic",
                      parent=None if gen_span is None else gen_span.context,
                      attrs={"generation": generation,
                             "primary_slot": primary.slot_id,
                             "primary_host": record.primary_host,
                             "dead_slots": record.dead_slots,
                             "reason": primary.death_reason}):
                self._kill_world([slots[s] for s in world])
                for d in dead:
                    self._deaths.inc(reason=d.death_reason or "exit")
                decision, delay, new_world, ladder = self._decide(
                    primary, world, result)
                record.decision = decision
                if decision == "fail":
                    record.outcome = "failed"
                    domain = (f"host {record.primary_host}"
                              if record.primary_host is not None
                              else f"slot {primary.slot_id}")
                    result.reason = (
                        f"{domain} exhausted its restart "
                        f"budget ({self.backoff.max_restarts}) and the "
                        f"world cannot shrink below min_workers="
                        f"{self.min_workers}"
                        + (f" / min_hosts={self.min_hosts}"
                           if self.num_hosts is not None else ""))
                    self._record_decision(gen_span, generation, decision,
                                          primary, result.reason)
                    self._write_incident(generation, decision,
                                         result.reason, 0.0, ladder,
                                         primary, dead, world, world,
                                         slots, restore_step)
                    self._gen_span_end(gen_span, "failed")
                    self._log.error("job failed",
                                    generation=generation,
                                    slot=primary.slot_id,
                                    reason=result.reason)
                    return self._fail(result, raise_on_failure)
                self._restarts.inc(decision=decision)
                result.restarts_total += 1
                reason = (f"{primary.death_reason or 'exit'} on slot "
                          f"{primary.slot_id}")
                self._record_decision(gen_span, generation, decision,
                                      primary, reason)
                self._write_incident(generation, decision, reason, delay,
                                     ladder, primary, dead, world,
                                     new_world, slots, restore_step)
                self._log.warning(
                    "recovering", generation=generation,
                    decision=decision, primary_slot=primary.slot_id,
                    death_reason=primary.death_reason,
                    backoff_s=round(delay, 3), next_world=new_world)
            self._gen_span_end(gen_span, "recovered")
            if delay > 0:
                result.backoff_delays.append(delay)
                self.sleep_fn(delay)
            world = new_world

    def _fail(self, result: ElasticJobResult,
              raise_on_failure: bool) -> ElasticJobResult:
        result.status = "failed"
        if raise_on_failure:
            raise ElasticJobFailed(result.reason or "elastic job failed",
                                   result)
        return result

    # -- recovery decision -------------------------------------------------
    def _decide(self, primary: _Slot, world: List[int],
                result: ElasticJobResult):
        """(decision, backoff_delay, new_world, ladder) for one recovery
        round; ``ladder`` is the per-rung reasoning the incident bundle
        records (which rungs were considered, which one was taken, why).

        Only the PRIMARY victim's failure DOMAIN is charged: peers die
        as collateral when the world breaks (their collectives can never
        complete) and a budget charge for each would turn one fault into
        a cascade of budget exhaustion. With host grouping the domain is
        the whole host — shrink removes every slot of the victim host,
        keeping per-host slice shapes intact down to ``min_hosts``."""
        ladder: List[dict] = []
        domain = self._domain_of(primary.slot_id)
        startup_eligible = not primary.live \
            and domain.startup_retries_used < self.startup_retries
        ladder.append({
            "rung": "startup_retry", "taken": startup_eligible,
            "detail": (f"never live, retries used "
                       f"{domain.startup_retries_used}/"
                       f"{self.startup_retries}" if not primary.live
                       else "worker was live: not a startup flake")})
        if startup_eligible:
            # never became live: a port race / startup flake, not a
            # training fault — retry in place without touching the budget
            domain.startup_retries_used += 1
            return "restart", 0.0, list(world), ladder
        budget_left = domain.restarts_used < self.backoff.max_restarts
        ladder.append({
            "rung": "restart", "taken": budget_left,
            "detail": (f"domain budget {domain.restarts_used}/"
                       f"{self.backoff.max_restarts} used")})
        if budget_left:
            domain.restarts_used += 1
            host = self.host_of(primary.slot_id)
            seed = f"{self.job_id}:h{host}" if host is not None \
                else f"{self.job_id}:{primary.slot_id}"
            delay = self.backoff.delay(domain.restarts_used, seed=seed)
            return "restart", delay, list(world), ladder
        victims = set(self._domain_slots(primary.slot_id, world))
        survivors = [s for s in world if s not in victims]
        can_shrink = len(survivors) >= self.min_workers \
            and self._live_hosts(survivors) >= self.min_hosts
        ladder.append({
            "rung": "shrink", "taken": can_shrink,
            "detail": (f"survivors {survivors} vs floors min_workers="
                       f"{self.min_workers}, min_hosts={self.min_hosts}")})
        if can_shrink:
            return "shrink", 0.0, survivors, ladder
        ladder.append({"rung": "fail", "taken": True,
                       "detail": "cannot restart or shrink further"})
        return "fail", 0.0, list(world), ladder

    # -- incident flight recorder ------------------------------------------
    def _write_incident(self, generation: int, decision: str, reason: str,
                        delay: float, ladder: List[dict], primary: _Slot,
                        dead: List[_Slot], world_before: List[int],
                        world_after: List[int], slots: Dict[int, _Slot],
                        restore_step: Optional[int]) -> None:
        """Assemble the bounded incident bundle for one recovery
        decision. Best-effort by design: a broken flight recorder is a
        log line, never a second incident."""
        if self.incidents is None:
            return
        try:
            dead_ids = {d.slot_id for d in dead}
            workers = []
            for slot_id in world_before:
                s = slots[slot_id]
                workers.append({
                    "slot": slot_id, "host": self.host_of(slot_id),
                    "last_step": s.last_step, "live": s.live,
                    "death_reason": s.death_reason,
                    "exit_code": s.exit_code})
            log_tails = {d.slot_id: self.tail_log(d.slot_id, generation)
                         for d in dead}
            span_files = []
            try:
                # only the dying generation's streams: a long job writes
                # one file per generation per worker, and copying them
                # ALL into every bundle would grow incident disk with
                # job age (the flight recorder must stay bounded)
                tag = f".gen{generation:03d}."
                span_files = sorted(
                    os.path.join(self.trace_dir, n)
                    for n in os.listdir(self.trace_dir)
                    if n.endswith(".jsonl") and tag in n)
            except OSError:
                pass
            live_spans = None
            from deeplearning4j_tpu.observe import get_active_tracer
            tr = get_active_tracer()
            if tr is not None:
                live_spans = ("supervisor", tr.recorder.spans())
            metrics_text = (self.fleet.exposition() if self.fleet is not None
                            else self.metrics.exposition())
            env = self.spec.environment()
            path = self.incidents.record(
                job_id=self.job_id, generation=generation,
                ts_ms=self.clock.current_time_millis(),
                decision=decision, reason=reason, backoff_s=delay,
                ladder=ladder,
                victim={"slot": primary.slot_id,
                        "host": self.host_of(primary.slot_id),
                        "death_reason": primary.death_reason},
                dead_slots=sorted(dead_ids),
                world_before=world_before, world_after=world_after,
                workers=workers,
                checkpoint={"restore_step": restore_step,
                            # the generation is already fenced at
                            # decision time: this is the exact step the
                            # recovered world will resume from
                            "next_restore_step": self.latest_eligible_step(),
                            "eligible_steps": self.eligible_steps()},
                fault_plan_env=env.get("DL4J_TPU_FAULT_PLAN"),
                metrics_text=metrics_text,
                span_files=span_files, live_spans=live_spans,
                log_tails=log_tails)
            self._log.info("incident bundle written", path=path,
                           decision=decision, generation=generation)
        except Exception as e:  # noqa: BLE001 - never fail recovery
            self._log.error("incident bundle failed", error=str(e))

    # -- process management ------------------------------------------------
    def _launch_generation(self, generation: int, token: str,
                           world: List[int], slots: Dict[int, _Slot],
                           restore_step: Optional[int],
                           eligible: Optional[Sequence[int]] = None,
                           gen_span=None) -> None:
        if eligible is None:
            eligible = self.eligible_steps()
        eligible_env = ",".join(str(s) for s in eligible)
        bind = self.spec.resolved_bind_host()
        port = _free_port(bind) if self.port_fn is _free_port \
            else self.port_fn()
        coordinator = _join_host_port(
            self.spec.resolved_advertise_host(), port)
        log_dir = os.path.join(self.ckpt_dir, "logs")
        os.makedirs(log_dir, exist_ok=True)
        if gen_span is not None:
            os.makedirs(self.trace_dir, exist_ok=True)
        if self.fleet is not None:
            # the federation follows the CURRENT world: sources of shrunk
            # slots drop out (their series go absent, which the absence
            # rules can alert on) and survivors re-register under the new
            # generation label
            self.fleet.clear_sources()
        now = self.clock.current_time_millis()
        for pid, slot_id in enumerate(sorted(world)):
            s = slots[slot_id]
            s.hb_path = os.path.join(
                self.ckpt_dir, f"heartbeat.slot{slot_id}")
            try:
                # a stale beat from the previous generation would mark the
                # relaunched worker live before it ever runs — turning a
                # startup flake into a budget charge
                os.unlink(s.hb_path)
            except OSError:
                pass
            s.log_path = os.path.join(
                log_dir, f"gen{generation:03d}_slot{slot_id}.log")
            s.last_beat = None
            s.last_beat_at_ms = now
            s.live = False
            s.done = False
            s.exit_code = None
            s.death_reason = None
            s.last_step = None
            s.last_step_at_ms = now
            s.progressed = False
            env = self.spec.environment()
            env.update({
                ENV_COORDINATOR: coordinator,
                ENV_NUM_PROCESSES: str(len(world)),
                ENV_PROCESS_ID: str(pid),
                ENV_SLOT: str(slot_id),
                ENV_GENERATION: str(generation),
                ENV_TOKEN: token,
                ENV_CKPT_DIR: self.ckpt_dir,
                ENV_HEARTBEAT: s.hb_path,
                ENV_RESTORE_STEP: "" if restore_step is None
                else str(restore_step),
                ENV_ELIGIBLE_STEPS: eligible_env,
            })
            if self.spec.mesh_axes:
                from deeplearning4j_tpu.parallel.mesh import format_mesh_axes
                env[ENV_MESH] = format_mesh_axes(self.spec.mesh_axes)
            if self.spec.sharding_rules:
                env[ENV_SHARDING_RULES] = self.spec.sharding_rules
            host = self.host_of(slot_id)
            if host is not None:
                env[ENV_HOST] = str(host)
                env[ENV_NUM_HOSTS] = str(self.num_hosts)
            if gen_span is not None:
                # worker spans parent into this generation's job trace
                # and stream crash-durably into the supervisor's trace dir
                env[ENV_TRACEPARENT] = gen_span.context.traceparent()
                env[ENV_TRACE_DIR] = self.trace_dir
            if self.fleet is not None:
                metrics_path = os.path.join(
                    self.ckpt_dir, f"metrics.slot{slot_id}.prom")
                try:
                    # a stale snapshot from the previous generation must
                    # not masquerade as this incarnation's series
                    os.unlink(metrics_path)
                except OSError:
                    pass
                env[ENV_METRICS_FILE] = metrics_path
                labels = {"slot": slot_id, "generation": generation}
                if host is not None:
                    labels["host"] = host
                self.fleet.set_source(slot_id, metrics_path, labels)
            if bind != "127.0.0.1":
                # process 0 must LISTEN on the bind interface while peers
                # dial the advertised one (ctx.init_distributed forwards
                # this as jax's coordinator_bind_address)
                env[ENV_BIND_HOST] = bind
            if self.progress_timeout_s is not None:
                # the partition signature is liveness WITHOUT progress:
                # workers must keep beating from a background thread
                # while a step blocks, at a cadence the watchdog can see
                env[ENV_PROGRESS_BEAT] = str(
                    max(0.05, min(1.0, self.progress_timeout_s / 5.0)))
            s.proc = self.launcher.launch(self.spec.argv, env,
                                          self.spec.cwd, s.log_path)

    def _watch(self, live_slots: List[_Slot], deadline_ms: Optional[int]):
        """Poll until every worker exits 0 ("completed") or a death/stall
        is observed (returns the dead slots, primary first)."""
        while True:
            now = self.clock.current_time_millis()
            if deadline_ms is not None and now > deadline_ms:
                return "deadline", []
            dead: List[_Slot] = []
            all_done = True
            for s in live_slots:
                if s.done:
                    continue
                rc = s.proc.poll()
                if rc is not None:
                    self._reap(s)
                    if rc == 0:
                        s.done = True
                        continue
                    s.exit_code = rc
                    s.death_reason = "signal" if rc < 0 else "exit"
                    dead.append(s)
                    continue
                all_done = False
                beat = self._read_heartbeat(s)
                if beat is not None and beat != s.last_beat:
                    s.last_beat = beat
                    s.last_beat_at_ms = now
                    s.live = True
                    step = self._parse_heartbeat_step(beat)
                    if step is not None and step != s.last_step:
                        if s.last_step is not None:
                            s.progressed = True
                        s.last_step = step
                        s.last_step_at_ms = now
                    elif beat.rstrip().endswith(":save"):
                        # a declared in-progress checkpoint holds the
                        # partition watchdog: a save stall (slow
                        # filesystem, backpressured async window) is not
                        # a partition — the job deadline still backstops
                        # a save that never ends
                        s.last_step_at_ms = now
                else:
                    timeout = (self.heartbeat_timeout_s if s.live
                               else self.startup_timeout_s)
                    if now - s.last_beat_at_ms > timeout * 1000:
                        s.proc.kill()
                        self._reap(s)
                        s.death_reason = "stall"
                        dead.append(s)
            if not dead:
                dead = self._check_progress(live_slots, now)
            if dead:
                # signal-killed victims ahead of error exits: when a kill
                # and its collateral land in one poll round, the victim is
                # the primary
                dead.sort(key=lambda d: (0 if d.death_reason == "signal"
                                         else 1 if d.death_reason == "stall"
                                         else 2, d.slot_id))
                return "dead", dead
            if all_done:
                return "completed", []
            self.sleep_fn(self.poll_interval_s)

    @staticmethod
    def _parse_heartbeat_step(beat: str) -> Optional[int]:
        """Training step out of a ``generation:step:beats`` heartbeat
        payload; None for any other format (legacy workers — progress
        tracking simply stays inactive for them)."""
        parts = beat.split(":")
        if len(parts) >= 2:
            try:
                return int(parts[1])
            except ValueError:
                return None
        return None

    def _check_progress(self, live_slots: List[_Slot], now: int):
        """The partition watchdog: every live worker still heartbeating
        (alive) but NO worker advancing its training step for
        ``progress_timeout_s`` is the signature of a network partition —
        a collective across the cut can never complete, so both sides
        stall mid-step while staying perfectly healthy. A mere slow
        worker never trips this: as long as steps complete anywhere,
        progress timestamps keep moving. Neither does a generation that
        has not completed a single step yet — a long first-step compile
        stalls everyone globally and is startup, not a partition (the
        startup/heartbeat timeouts own that window).

        Resolution: the side that stopped progressing FIRST (lowest
        heartbeat step) is the partitioned minority — it is killed and
        charged like a death, and the decision ladder restarts or
        shrinks it away. Ties resolve against the smaller host group,
        then the higher host id (deterministic; with a symmetric cut
        someone must die, and the survivors keep the job)."""
        if self.progress_timeout_s is None:
            return []
        candidates = [s for s in live_slots if not s.done and s.live]
        if not candidates:
            return []
        if any(s.last_step is None for s in candidates):
            return []  # someone never reported a step — not a partition
        # a generation where nobody ever advanced is usually starting up
        # (first-step compile) — give it the STARTUP window instead of
        # the step window, but not forever: a generation relaunched into
        # a still-active cut also never completes a step, and with
        # background beats alive nothing else would ever resolve it
        window = self.progress_timeout_s
        if not any(s.progressed for s in candidates):
            window = max(window, self.startup_timeout_s)
        if any(now - s.last_step_at_ms <= window * 1000
               for s in candidates):
            return []
        # group by failure domain; victim = least-progressed group
        groups: Dict[object, List[_Slot]] = {}
        for s in candidates:
            key = self.host_of(s.slot_id)
            key = s.slot_id if key is None else key
            groups.setdefault(key, []).append(s)
        if len(groups) < 2:
            return []  # one domain left: nothing to resolve a cut against
        victim_key = min(
            groups,
            key=lambda k: (max(s.last_step for s in groups[k]),
                           len(groups[k]), -(k if isinstance(k, int) else 0)))
        victims = sorted(groups[victim_key], key=lambda s: s.slot_id)
        for v in victims:
            v.proc.kill()
            self._reap(v)
            v.death_reason = "partition"
        self._partitions.inc()
        self._log.warning(
            "partition resolved", victim_domain=victim_key,
            victim_slots=[v.slot_id for v in victims],
            progress_timeout_s=self.progress_timeout_s)
        return victims

    def _read_heartbeat(self, s: _Slot) -> Optional[str]:
        try:
            with open(s.hb_path, encoding="utf-8") as fh:
                return fh.read()
        except OSError:
            return None

    def _kill_world(self, live_slots: List[_Slot]) -> None:
        for s in live_slots:
            if s.done or s.proc is None:
                continue
            if s.proc.poll() is None:
                s.proc.kill()
            self._reap(s)

    @staticmethod
    def _reap(s: _Slot) -> None:
        try:
            s.proc.wait(timeout=30)
        except Exception:  # noqa: BLE001 - last resort; do not hang recovery
            pass
        fh = getattr(s.proc, "_elastic_log", None)
        if fh is not None:
            fh.close()
            s.proc._elastic_log = None

    #: hard cap on one tail_log read — the ring-buffer discipline: a
    #: multi-GB worker log must never be slurped whole into the
    #: supervisor (or an incident bundle) because a caller asked big
    TAIL_LOG_CAP = 1 << 20

    def tail_log(self, slot_id: int, generation: int,
                 n_bytes: int = 4000) -> str:
        """Last bytes of one worker incarnation's captured output.
        Tolerates the worker truncating/rotating its own log mid-read
        (the computed tail offset may no longer exist — re-read from the
        top instead of returning garbage or raising) and caps the read
        at :data:`TAIL_LOG_CAP` regardless of ``n_bytes``."""
        n_bytes = max(0, min(int(n_bytes), self.TAIL_LOG_CAP))
        path = os.path.join(self.ckpt_dir, "logs",
                            f"gen{generation:03d}_slot{slot_id}.log")
        try:
            with open(path, "rb") as fh:
                fh.seek(0, os.SEEK_END)
                size = fh.tell()
                fh.seek(max(0, size - n_bytes))
                data = fh.read(n_bytes)
                if not data and size > 0:
                    # truncated/rotated between tell() and read(): the
                    # offset we computed is past the new EOF
                    fh.seek(0)
                    data = fh.read(n_bytes)
                return data.decode(errors="replace")
        except (OSError, ValueError):
            return ""

    def write_fleet_trace(self, path: str) -> int:
        """Stitch every worker span stream under ``trace_dir`` plus the
        supervisor's own recorded spans into ONE Perfetto-loadable
        timeline at ``path`` (``observe.export.merge_chrome_traces``);
        returns the event count (0 = tracing never ran)."""
        from deeplearning4j_tpu.observe import get_active_tracer
        from deeplearning4j_tpu.observe.export import merge_chrome_traces
        from deeplearning4j_tpu.observe.trace import EPOCH_ANCHOR
        sources: List[object] = []
        try:
            sources.extend(sorted(
                os.path.join(self.trace_dir, n)
                for n in os.listdir(self.trace_dir)
                if n.endswith(".jsonl")))
        except OSError:
            pass
        tr = get_active_tracer()
        if tr is not None and len(tr.recorder):
            sources.append({"label": "supervisor",
                            "spans": tr.recorder.spans(),
                            "anchor": EPOCH_ANCHOR})
        obj = merge_chrome_traces(sources, out=path)
        return len(obj["traceEvents"])


# -- worker side -------------------------------------------------------------

class StaleGenerationError(RuntimeError):
    """This worker's generation token no longer matches the supervisor's
    current generation — the world moved on; nothing this process writes
    may be trusted."""


def _parse_env_mesh(spec: Optional[str]) -> Optional[Dict[str, int]]:
    if not spec:
        return None
    from deeplearning4j_tpu.parallel.mesh import parse_mesh_axes
    return parse_mesh_axes(spec)


@dataclasses.dataclass
class ElasticWorkerContext:
    """A worker's view of its elastic world, decoded from the supervisor's
    environment variables."""

    coordinator: str
    num_processes: int
    process_id: int
    slot: int
    generation: int
    token: str
    ckpt_dir: str
    heartbeat_path: str
    restore_step: Optional[int]
    #: fence-eligible steps as computed by the supervisor at launch; the
    #: corrupt-step fallback walk is restricted to these (None = launched
    #: outside a supervisor, no fence to honor)
    eligible_steps: Optional[List[int]] = None
    #: host failure domain (None = no host grouping)
    host: Optional[int] = None
    num_hosts: Optional[int] = None
    #: per-host mesh slice shape from the supervisor (non-data axes of
    #: the pod mesh; None = classic one-device-per-worker data
    #: parallelism) and the sharding-rules JSON path to place params with
    mesh_axes: Optional[Dict[str, int]] = None
    sharding_rules_path: Optional[str] = None
    #: background-heartbeat cadence; set by the supervisor when its
    #: step-progress (partition) watchdog is armed
    progress_beat_s: Optional[float] = None
    #: interface process 0's coordinator must LISTEN on when it differs
    #: from the advertised address (None → jax binds the advertised one)
    bind_host: Optional[str] = None
    #: fleet observability seam (all None outside a fleet-observing
    #: supervisor — every dependent hook is then a no-op): the
    #: supervisor's per-generation elastic_job span context, the
    #: directory this worker streams its spans into, and the file it
    #: writes Prometheus exposition snapshots to
    traceparent: Optional[str] = None
    trace_dir: Optional[str] = None
    metrics_file: Optional[str] = None
    _beats: int = 0
    _last_step: int = 0
    _beat_thread: object = None
    _beat_stop: object = None
    # one lock guards the heartbeat write AND the saving counter: the
    # training, beat and async-saver threads all pass through here
    _beat_lock: object = dataclasses.field(default_factory=threading.Lock)
    # >0 while a checkpoint is in flight anywhere (blocking save, async
    # submit, background write); heartbeats then declare the save so the
    # supervisor's partition watchdog holds fire — a save stall is not a
    # partition
    _saving: int = 0

    @classmethod
    def from_env(cls, environ=None) -> Optional["ElasticWorkerContext"]:
        env = os.environ if environ is None else environ
        if ENV_TOKEN not in env:
            return None
        restore = env.get(ENV_RESTORE_STEP, "")
        eligible = env.get(ENV_ELIGIBLE_STEPS)
        host = env.get(ENV_HOST)
        ctx = cls(
            coordinator=env[ENV_COORDINATOR],
            num_processes=int(env[ENV_NUM_PROCESSES]),
            process_id=int(env[ENV_PROCESS_ID]),
            slot=int(env[ENV_SLOT]),
            generation=int(env[ENV_GENERATION]),
            token=env[ENV_TOKEN],
            ckpt_dir=env[ENV_CKPT_DIR],
            heartbeat_path=env[ENV_HEARTBEAT],
            restore_step=int(restore) if restore else None,
            eligible_steps=None if eligible is None
            else [int(s) for s in eligible.split(",") if s],
            host=int(host) if host is not None else None,
            num_hosts=int(env[ENV_NUM_HOSTS])
            if ENV_NUM_HOSTS in env else None,
            mesh_axes=_parse_env_mesh(env.get(ENV_MESH)),
            sharding_rules_path=env.get(ENV_SHARDING_RULES) or None,
            progress_beat_s=float(env[ENV_PROGRESS_BEAT])
            if env.get(ENV_PROGRESS_BEAT) else None,
            bind_host=env.get(ENV_BIND_HOST) or None,
            traceparent=env.get(ENV_TRACEPARENT) or None,
            trace_dir=env.get(ENV_TRACE_DIR) or None,
            metrics_file=env.get(ENV_METRICS_FILE) or None)
        if ctx.host is not None:
            from deeplearning4j_tpu.util import faultinject
            faultinject.set_host(ctx.host)  # host-scoped faults key on it
        return ctx

    # -- liveness ---------------------------------------------------------
    def heartbeat(self, step: int) -> None:
        from deeplearning4j_tpu.util import faultinject
        self._last_step = int(step)
        if not faultinject.on_heartbeat(self.slot, step):
            return
        # serialized against the background beat thread: the atomic-write
        # tmp name is keyed by PID only, so two same-process writers
        # would race on one tmp file (os.replace stealing it mid-write)
        with self._beat_lock:
            self._beats += 1
            busy = ":save" if self._saving > 0 else ""
            _atomic_write(self.heartbeat_path,
                          f"{self.generation}:{step}:{self._beats}{busy}")

    def _mark_saving(self, delta: int) -> None:
        """Adjust the in-progress-checkpoint count (lock-guarded: the
        training thread and the async saver thread both touch it)."""
        with self._beat_lock:
            self._saving += delta

    def start_heartbeat_thread(self) -> None:
        """Keep beating from a daemon thread at ``progress_beat_s`` while
        the main thread is inside a step — liveness and step progress
        become independently observable, which is exactly what lets the
        supervisor tell a partition (alive, stuck) from a dead worker.
        The beat repeats the LAST step the main thread reported; only
        the main thread ever advances it."""
        if self._beat_thread is not None or not self.progress_beat_s:
            return
        self._beat_stop = threading.Event()

        def _loop():
            while not self._beat_stop.wait(self.progress_beat_s):
                self.heartbeat(self._last_step)

        self._beat_thread = threading.Thread(
            target=_loop, name=f"elastic-beat-slot{self.slot}", daemon=True)
        self._beat_thread.start()

    def stop_heartbeat_thread(self) -> None:
        if self._beat_thread is not None:
            self._beat_stop.set()
            self._beat_thread.join(timeout=5)
            self._beat_thread = None

    # -- world formation --------------------------------------------------
    def init_distributed(self) -> None:
        from deeplearning4j_tpu.parallel.master import init_distributed
        bind_address = None
        if self.process_id == 0 and self.bind_host:
            # listen on the bind interface, advertise the dialable one —
            # same port (the supervisor probed it on the BIND interface;
            # rsplit keeps a bracketed IPv6 advertise address intact)
            port = self.coordinator.rsplit(":", 1)[-1]
            bind_address = _join_host_port(self.bind_host, port)
        init_distributed(coordinator_address=self.coordinator,
                         num_processes=self.num_processes,
                         process_id=self.process_id,
                         coordinator_bind_address=bind_address)

    # -- fenced checkpointing ---------------------------------------------
    def check_fence(self) -> None:
        """Abort (loudly) when the supervisor has moved to a newer
        generation: a stale worker must not write checkpoints."""
        try:
            with open(os.path.join(self.ckpt_dir, GENERATION_FILE),
                      encoding="utf-8") as fh:
                current = json.load(fh)
        except (OSError, ValueError):
            return  # no generation file yet — standalone run
        if current.get("token") != self.token:
            raise StaleGenerationError(
                f"generation {self.generation} ({self.token}) has been "
                f"superseded by {current.get('generation')} "
                f"({current.get('token')}); refusing to checkpoint")

    def master_state_path(self, step: int, rank: Optional[int] = None,
                          world: Optional[int] = None) -> str:
        """Rank-local compression state for one committed step. Keyed by
        world size: residual shards only make sense on the world shape
        that wrote them — a shrunk world skips them and re-accumulates."""
        rank = self.process_id if rank is None else rank
        world = self.num_processes if world is None else world
        return os.path.join(
            self.ckpt_dir,
            f"master_state.step{int(step):08d}.w{world}.r{rank}.npz")

    def pod_mesh_axes(self) -> Dict[str, int]:
        """The generation's pod mesh shape: ``data`` spans the CURRENT
        processes (DCN across hosts), any supervisor-forwarded extra
        axes live inside each host's slice (ICI). Shrinks change only
        the data extent — the model sharding survives a generation."""
        axes = {"data": self.num_processes}
        for name, size in (self.mesh_axes or {}).items():
            if name != "data":
                axes[name] = int(size)
        return axes

    def save_checkpoint_sharded(self, step: int, model, manager,
                                peer_wait_s: float = 120.0) -> None:
        """Pod-mesh commit: EVERY rank participates in one collective
        orbax save — each process writes exactly the model shards its
        devices own (genuinely sharded bytes, not a replicated copy from
        rank 0) — then rank 0 alone runs the fencing commit (stamp,
        prune). No master residual shards on this path: GSPMD owns the
        gradient exchange, so the stamp waits on no peer files."""
        from deeplearning4j_tpu.util import faultinject
        self.check_fence()
        self._mark_saving(+1)
        try:
            faultinject.on_save_phase(self.slot, step, "pre_write",
                                      host=self.host)
            ok = manager.save(step, model,
                              overwrite_existing=(self.process_id == 0))
            faultinject.on_save_phase(self.slot, step, "mid_shard",
                                      host=self.host)
            if self.process_id == 0:
                self._commit_step(step, manager, save_model_fn=lambda: ok,
                                  expect_shards=False,
                                  peer_wait_s=peer_wait_s)
        finally:
            self._mark_saving(-1)

    def save_checkpoint(self, step: int, model, master=None, manager=None,
                        peer_wait_s: float = 120.0) -> None:
        """One committed checkpoint step: every rank saves its own master
        compression state; rank 0 writes the orbax model checkpoint, waits
        for every peer's state file, applies any planned
        ``corrupt_checkpoint`` fault, then writes the step stamp (the
        commit marker the supervisor's restore choice reads). The
        ``on_save_phase`` fault hooks fire at the same protocol points as
        on the async path — a phase-scoped fault plan behaves identically
        under both save modes."""
        from deeplearning4j_tpu.util import faultinject
        self.check_fence()
        self._mark_saving(+1)
        try:
            faultinject.on_save_phase(self.slot, step, "pre_write",
                                      host=self.host)
            if master is not None:
                master.save_state(self.master_state_path(step))
            faultinject.on_save_phase(self.slot, step, "mid_shard",
                                      host=self.host)
            if manager is not None:  # rank 0 owns the model checkpoint
                self._commit_step(
                    step, manager,
                    # overwrite_existing: a finalized-but-corrupt dir for
                    # this step (fenced-lineage leftover the fallback
                    # restore walked past) makes a plain orbax save
                    # silently decline — stamping then would re-advertise
                    # the corrupt bytes under OUR token
                    save_model_fn=lambda: manager.save(
                        step, model, overwrite_existing=True),
                    expect_shards=master is not None,
                    peer_wait_s=peer_wait_s)
        finally:
            self._mark_saving(-1)

    def _commit_step(self, step: int, manager, *, save_model_fn,
                     expect_shards: bool, peer_wait_s: float) -> None:
        """The committing rank's barrier — ONE implementation for the
        sync and async paths (the fencing protocol must never diverge
        between them): orbax write + finalize, every rank's shard file
        landed, the planned ``corrupt_checkpoint`` fault, the pre_stamp
        hook, a fence re-check, the step stamp, retention pruning."""
        import time as _time
        from deeplearning4j_tpu.util import faultinject
        if not save_model_fn():
            raise RuntimeError(
                f"orbax declined to save checkpoint step {step}; "
                f"refusing to stamp a step that was not written")
        manager.wait_until_finished()
        if expect_shards:
            deadline = _time.time() + peer_wait_s
            for r in range(self.num_processes):
                path = self.master_state_path(step, rank=r)
                while not os.path.exists(path):
                    if _time.time() > deadline:
                        raise RuntimeError(
                            f"rank {r} shard for step {step} never "
                            f"appeared at {path}; leaving the step "
                            f"torn (unstamped)")
                    _time.sleep(0.1)
        step_dir = os.path.join(self.ckpt_dir, str(int(step)))
        if os.path.isdir(step_dir):
            faultinject.on_checkpoint_saved(self.slot, step, step_dir)
        faultinject.on_save_phase(self.slot, step, "pre_stamp",
                                  host=self.host)
        self.check_fence()
        write_step_stamp(self.ckpt_dir, step, self.token,
                         self.generation, self.num_processes)
        self._prune_unretained(manager)

    def _prune_unretained(self, manager) -> None:
        """Drop step stamps and master-state shards whose model
        checkpoint fell out of the orbax retention window: nothing can
        restore them, and the per-rank residual shards are model-sized —
        ``max_to_keep`` caps orbax disk, this caps the rest (otherwise a
        long job fills the checkpoint volume the supervisor depends on)."""
        try:
            retained = set(manager.all_steps())
        except Exception:  # noqa: BLE001 - pruning must never fail a save
            return
        for name in os.listdir(self.ckpt_dir):
            step = None
            if name.startswith(_STAMP_PREFIX) and name.endswith(".json"):
                step = name[len(_STAMP_PREFIX):-len(".json")]
            elif name.startswith("master_state.step"):
                step = name[len("master_state.step"):][:8]
            if step is None:
                continue
            try:
                step = int(step)
            except ValueError:
                continue
            if step not in retained:
                try:
                    os.unlink(os.path.join(self.ckpt_dir, name))
                except OSError:
                    pass


class AsyncCheckpointSession:
    """Asynchronous sharded checkpointing as the elastic recovery
    substrate: every rank hands its shard (the rank-local master
    compression state, snapshotted on the training thread) plus — on the
    manager-owning rank — a host-numpy snapshot of the model state to a
    single background saver thread, and trains on while the bytes hit
    disk. The generation-fencing commit protocol is unchanged, just
    moved off the step path: the step stamp is written only after the
    orbax save finalized AND every rank's shard landed, so a crash at
    ANY phase of an overlapped save leaves a torn step that is never
    restorable (the fallback walk only sees stamped steps).

    In-flight saves are bounded by ``max_in_flight``: once the window is
    full, :meth:`submit` blocks until the oldest save completes — a slow
    filesystem backpressures training instead of accumulating unbounded
    snapshots (the time spent blocked is accounted in
    ``submit_stall_s``). All checkpoint-manager calls happen on the
    saver thread; do not use the manager from other threads while a
    session is open."""

    def __init__(self, ctx: "ElasticWorkerContext", *, manager=None,
                 master=None, max_in_flight: int = 2,
                 peer_wait_s: float = 120.0):
        import queue
        import threading
        if max_in_flight < 1:
            raise ValueError("max_in_flight must be >= 1")
        self.ctx = ctx
        self.manager = manager
        self.master = master
        self.peer_wait_s = peer_wait_s
        self._sem = threading.Semaphore(max_in_flight)
        self._q: "queue.Queue" = queue.Queue()
        self._pending: List[object] = []
        self.errors: List[str] = []
        self.committed: List[int] = []
        self.submitted = 0
        #: seconds the TRAINING thread spent blocked on the in-flight
        #: window — the measured save stall of the async path
        self.submit_stall_s = 0.0
        self._thread = threading.Thread(
            target=self._run, name=f"elastic-ckpt-slot{ctx.slot}",
            daemon=True)
        self._thread.start()

    # -- training-thread side --------------------------------------------
    def submit(self, step: int, model) -> None:
        """Snapshot and enqueue one checkpoint step. Blocks only when
        ``max_in_flight`` saves are already in the pipe (backpressure);
        otherwise returns as soon as the device arrays are copied to
        host — the save overlaps the next training step."""
        import threading
        import time as _time
        # heartbeats declare the save from here until the SAVER thread
        # finishes the item (released in _run) — the whole in-flight
        # window, including the final flush, holds the supervisor's
        # partition watchdog, not just the submit/backpressure slice
        self.ctx._mark_saving(+1)
        try:
            t0 = _time.perf_counter()
            self._sem.acquire()
            self.submit_stall_s += _time.perf_counter() - t0
            try:
                self.ctx.check_fence()  # fail fast on the training thread
                master_snap = None if self.master is None \
                    else self.master.state_snapshot()
                state = None
                if self.manager is not None:
                    from deeplearning4j_tpu.util.orbax_checkpoint import (
                        snapshot_state)
                    state = snapshot_state(model)
            except BaseException:
                self._sem.release()
                raise
        except BaseException:
            self.ctx._mark_saving(-1)  # nothing was enqueued
            raise
        done = threading.Event()
        item = {"step": int(step), "model": model, "state": state,
                "master_snap": master_snap, "done": done}
        # keep only in-flight events: a long per-step-checkpoint run must
        # not grow this list (and every flush walk) without bound
        self._pending = [ev for ev in self._pending if not ev.is_set()]
        self._pending.append(done)
        self.submitted += 1
        self._q.put(item)

    def flush(self, timeout: Optional[float] = None) -> bool:
        """Wait for every submitted save to finish (committed or failed);
        True when all landed within ``timeout`` seconds."""
        import time as _time
        deadline = None if timeout is None else _time.time() + timeout
        for ev in list(self._pending):
            remaining = None if deadline is None \
                else max(0.0, deadline - _time.time())
            if not ev.wait(remaining):
                return False
        return True

    def close(self, timeout: Optional[float] = None) -> bool:
        """Flush, then stop the saver thread. Returns the flush result."""
        ok = self.flush(timeout)
        self._q.put(None)
        self._thread.join(timeout=5)
        return ok

    # -- saver-thread side ------------------------------------------------
    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            try:
                self._do_save(item)
            except BaseException as e:  # noqa: BLE001 - a failed save is
                # a torn step (no stamp), NOT a dead worker: record it
                # and keep training; restore falls back to the previous
                # committed step
                self.errors.append(
                    f"step {item['step']}: {type(e).__name__}: {e}")
            finally:
                item["done"].set()
                self._sem.release()
                self.ctx._mark_saving(-1)  # paired with submit's +1

    def _do_save(self, item: dict) -> None:
        from deeplearning4j_tpu.observe import span
        from deeplearning4j_tpu.util import faultinject
        ctx, step = self.ctx, item["step"]
        with span("elastic_async_save", category="elastic",
                  attrs={"step": step, "slot": ctx.slot,
                         "rank": ctx.process_id}):
            faultinject.on_save_phase(ctx.slot, step, "pre_write",
                                      host=ctx.host)
            if item["master_snap"] is not None:
                # the rank-local shard; its (atomic) existence is this
                # rank's "finalize landed" signal to the committing rank
                self.master.write_state_snapshot(
                    item["master_snap"], ctx.master_state_path(step))
            faultinject.on_save_phase(ctx.slot, step, "mid_shard",
                                      host=ctx.host)
            if self.manager is None:
                return
            # the committing rank: the SAME barrier the sync path runs
            # (orbax finalize → all shards → pre_stamp → fence → stamp),
            # just fed from the snapshot instead of the live model
            ctx._commit_step(
                step, self.manager,
                save_model_fn=lambda: self.manager.save(
                    step, item["model"], overwrite_existing=True,
                    state=item["state"]),
                expect_shards=item["master_snap"] is not None,
                peer_wait_s=self.peer_wait_s)
            self.committed.append(step)


def run_elastic_worker(build_model, build_iterator, *, epochs: int,
                       master_kwargs: Optional[dict] = None,
                       checkpoint_every: int = 1,
                       max_to_keep: Optional[int] = None,
                       save_mode: str = "sync",
                       max_in_flight: int = 2,
                       flush_timeout_s: float = 300.0,
                       on_done=None, ctx: Optional[ElasticWorkerContext]
                       = None):
    """Generic elastic worker runloop — the library composition the
    recovery tests used to hand-roll (``tests/failover_worker.py``):

    join the generation's ``jax.distributed`` world → restore the
    supervisor-chosen checkpoint step (with corrupt-step fallback) →
    rebuild the mesh at the CURRENT world size → resume
    ``SharedTrainingMaster`` training with per-iteration heartbeats +
    fault hooks → write fenced rotation checkpoints every
    ``checkpoint_every`` epochs.

    ``save_mode="async"`` routes checkpoints through an
    :class:`AsyncCheckpointSession`: saves overlap the next training
    steps, bounded at ``max_in_flight`` in the pipe, and the final flush
    (capped at ``flush_timeout_s``) happens before the manager closes. A
    save that fails asynchronously is a torn (never-restorable) step,
    not a worker death — it is logged and the job trains on.

    ``build_model()`` must be deterministic (fresh start only);
    ``build_iterator()`` is called once per epoch. ``on_done(net, ctx)``
    runs after the final epoch (e.g. rank 0 dumps params).
    Returns the trained network.
    """
    if save_mode not in ("sync", "async"):
        raise ValueError(f"save_mode must be sync|async, got {save_mode!r}")
    if ctx is None:
        ctx = ElasticWorkerContext.from_env()
    if ctx is None:
        raise RuntimeError(
            "run_elastic_worker needs the supervisor environment "
            f"({ENV_TOKEN} etc.) — launch through ElasticJobSupervisor")
    # fleet observability: both hooks ride the supervisor env and are
    # no-ops without it (standalone workers pay one None check)
    tracer = None
    exporter = None
    obs_registry = None
    if ctx.metrics_file is not None:
        from deeplearning4j_tpu.observe import default_registry
        from deeplearning4j_tpu.observe.fleet import MetricsFileExporter
        obs_registry = default_registry()
        exporter = MetricsFileExporter(obs_registry, ctx.metrics_file)
    if ctx.trace_dir is not None:
        os.makedirs(ctx.trace_dir, exist_ok=True)
        from deeplearning4j_tpu.observe import Tracer, enable_tracing
        from deeplearning4j_tpu.observe.fleet import SpanFileWriter
        span_writer = SpanFileWriter(
            os.path.join(
                ctx.trace_dir,
                f"spans.gen{ctx.generation:03d}.slot{ctx.slot}.jsonl"),
            label=f"slot {ctx.slot} gen {ctx.generation}",
            extra_meta={"slot": ctx.slot, "generation": ctx.generation,
                        "host": ctx.host, "rank": ctx.process_id})
        tracer = enable_tracing(Tracer(span_writer),
                                metrics=obs_registry)
    ctx.init_distributed()
    from deeplearning4j_tpu.parallel.master import (
        DistributedMultiLayerNetwork, SharedTrainingMaster)
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    from deeplearning4j_tpu.util import faultinject
    from deeplearning4j_tpu.util.orbax_checkpoint import (
        OrbaxCheckpointManager)

    pod_axes = ctx.pod_mesh_axes()
    model_parallel = any(k != "data" and int(v) > 1
                         for k, v in pod_axes.items())
    pod_mesh = make_mesh(pod_axes) if model_parallel else None
    rules = None
    if ctx.sharding_rules_path:
        from deeplearning4j_tpu.parallel.sharding import load_sharding_rules
        rules = load_sharding_rules(ctx.sharding_rules_path)
    if model_parallel and save_mode == "async":
        # the overlapped session snapshots to host numpy, which would
        # gather the model shards; pod-mesh saves go through orbax's own
        # collective sharded writer instead
        print(f"[slot {ctx.slot}] pod mesh active: async save_mode "
              f"falls back to sync collective saves", flush=True)
        save_mode = "sync"

    if ctx.restore_step is not None:
        # every process restores independently (active_processes={pid}:
        # read-only restores need no cross-process barrier); fallback
        # walks to an older retained step when the chosen one is corrupt.
        # On a pod mesh the restore reshards STRAIGHT INTO this
        # generation's mesh — a 2×4 checkpoint restores onto a 1×4
        # world after a host-failure shrink (the data extent changed,
        # the rules re-place every param on the surviving slice)
        with OrbaxCheckpointManager(
                ctx.ckpt_dir, active_processes={ctx.process_id},
                barrier_sync_key_prefix=(
                    f"restore_g{ctx.generation}_p{ctx.process_id}")) as mgr:
            net = mgr.restore(ctx.restore_step, fallback=True,
                              fallback_steps=ctx.eligible_steps,
                              mesh=pod_mesh, sharding_rules=rules)
            restored_step = mgr.restored_step
    else:
        net = build_model()
        restored_step = None
        if pod_mesh is not None:
            from deeplearning4j_tpu.parallel.sharding import (
                shard_model_with_rules)
            shard_model_with_rules(net, pod_mesh, rules)

    if pod_mesh is not None:
        # DP×MP via GSPMD: the jitted train step IS the distributed
        # program (batch over data, params over model — gradient
        # exchange compiled in); no deterministic-broadcast master
        from deeplearning4j_tpu.parallel.mesh import format_mesh_axes
        print(f"[slot {ctx.slot}] pod mesh "
              f"{format_mesh_axes(pod_axes)} (GSPMD 2-D)", flush=True)
        mesh = pod_mesh
        master = None
        front = net
    else:
        mesh = make_mesh({"data": ctx.num_processes})
        master = SharedTrainingMaster(mesh=mesh, **(master_kwargs or {}))
        if restored_step is not None:
            state_path = ctx.master_state_path(restored_step)
            if os.path.exists(state_path):
                # same world size as the writer → exact resume including
                # residuals; after a shrink the file (keyed by world
                # size) does not exist and residuals re-accumulate
                master.load_state(state_path)
        front = DistributedMultiLayerNetwork(net, master)

    if tracer is not None or exporter is not None:
        # per-iteration train_iteration spans (parented into the job
        # trace via the root span below) + training_* series for the
        # supervisor's federation — appended BEFORE _Beat so the span
        # for step S is crash-durably written before a planned kill at
        # S fires in the heartbeat listener
        from deeplearning4j_tpu.observe import TraceListener
        net.listeners.append(TraceListener(
            tracer=tracer, metrics=obs_registry, model_name="elastic"))

    class _Beat:
        def iteration_done(self, model, iteration, epoch):
            # the fault hook runs BEFORE the heartbeat: a worker blocked
            # by a partition fault at step S never advertises S — its
            # heartbeat step freezes at S-1, which is exactly the
            # lowest-progress signature the supervisor's watchdog keys
            # its victim choice on
            faultinject.on_step(ctx.slot, iteration, host=ctx.host)
            ctx.heartbeat(iteration)
            if exporter is not None:
                exporter.export()

    net.listeners.append(_Beat())

    manager = None
    if pod_mesh is not None and ctx.num_processes > 1:
        # params are sharded ACROSS processes: every rank owns shards
        # only it can write, so every rank joins the collective save
        manager = OrbaxCheckpointManager(
            ctx.ckpt_dir, max_to_keep=max_to_keep,
            barrier_sync_key_prefix=f"save_g{ctx.generation}")
    elif ctx.process_id == 0:
        manager = OrbaxCheckpointManager(
            ctx.ckpt_dir, max_to_keep=max_to_keep,
            active_processes={0},
            barrier_sync_key_prefix=f"save_g{ctx.generation}")
    ctx.heartbeat(0)  # first beat: the world formed, jax is up
    if exporter is not None:
        exporter.export()  # series visible to the fleet before step 1
    ctx.start_heartbeat_thread()  # no-op unless the supervisor armed it
    session = None
    if save_mode == "async":
        session = AsyncCheckpointSession(ctx, manager=manager,
                                         master=master,
                                         max_in_flight=max_in_flight)
    start_epoch = int(net.epoch)
    flushed = True
    import contextlib
    root_cm = contextlib.nullcontext()
    if tracer is not None:
        # the ambient context for everything this worker records:
        # parented to the supervisor's per-generation elastic_job span,
        # so train_iteration / checkpoint / DCN spans join the job trace
        from deeplearning4j_tpu.observe import parse_traceparent
        root_cm = tracer.span(
            "elastic_worker", parent=parse_traceparent(ctx.traceparent),
            category="elastic",
            attrs={"slot": ctx.slot, "rank": ctx.process_id,
                   "generation": ctx.generation,
                   "restored_step": restored_step})
    try:
        with root_cm:
            for epoch in range(start_epoch, epochs):
                front.fit(build_iterator(), epochs=1)
                step = epoch + 1
                ctx.heartbeat(net.iteration)
                if step % max(1, checkpoint_every) == 0 or step == epochs:
                    if session is not None:
                        session.submit(step, net)
                    elif pod_mesh is not None:
                        ctx.save_checkpoint_sharded(step, net, manager)
                    else:
                        ctx.save_checkpoint(step, net, master, manager)
    finally:
        if session is not None:
            flushed = session.close(timeout=flush_timeout_s)
            if not flushed:
                print(f"[slot {ctx.slot}] async checkpoint flush timed "
                      f"out after {flush_timeout_s}s", flush=True)
            for err in session.errors:
                print(f"[slot {ctx.slot}] async checkpoint torn: {err}",
                      flush=True)
        ctx.stop_heartbeat_thread()
        if exporter is not None:
            exporter.export()  # final snapshot: the last committed step
        # a timed-out flush means the saver thread may still be INSIDE a
        # manager call — closing the manager under it would crash the
        # worker; the in-flight step stays torn (unstamped) and the
        # process exit reclaims everything
        if manager is not None and flushed:
            manager.close()
    if on_done is not None:
        on_done(net, ctx)
    return net
