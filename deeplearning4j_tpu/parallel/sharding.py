"""Sharding rules: how model pytrees map onto a Mesh.

This replaces the reference's model replication (`ParallelWrapper.java:78`
clones the net per worker thread) with sharding annotations: a replicated
param lives once per device HBM but is updated by a single SPMD program; a
tensor-parallel param is *split* across the 'model' axis and XLA inserts the
matching collectives (all-gather / reduce-scatter) around the matmuls.
"""

from __future__ import annotations

import json
import re

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, ndim: int, axis: str = DATA_AXIS) -> NamedSharding:
    """Shard the leading (batch) dimension over the data axis."""
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def place_batch(x, mesh: Optional[Mesh], axis: str = DATA_AXIS):
    """Shard one batch array's leading dim over the mesh's data axis —
    the end-to-end input half of a DP×MP step (params carry the model
    axis; the batch carries data). No-op for ``None`` leaves, meshes
    without the axis, and ragged batches that don't divide it (those
    run on the replicated path, same contract as ParallelWrapper's
    tail-batch handling)."""
    if x is None or mesh is None:
        return x
    d = int(mesh.shape.get(axis, 1))
    ndim = getattr(x, "ndim", 0)
    if d <= 1 or ndim == 0 or x.shape[0] % d:
        return x
    return jax.device_put(x, batch_sharding(mesh, ndim, axis))


_COLUMN = "column"
_ROW = "row"


def _dense_like(layer) -> bool:
    """Layers holding one [n_in, n_out] matmul W (+ bias b): the building
    blocks of Megatron column/row pairs. OutputLayer subclasses DenseLayer."""
    from deeplearning4j_tpu.nn.layers.core import DenseLayer
    return isinstance(layer, DenseLayer)


def _is_output_layer(layer) -> bool:
    from deeplearning4j_tpu.nn.layers.output import OutputLayer
    return isinstance(layer, OutputLayer)


def _is_attention(layer) -> bool:
    from deeplearning4j_tpu.nn.layers.attention import SelfAttentionLayer
    return isinstance(layer, SelfAttentionLayer)


def _require_inferred_preprocessors(net) -> None:
    """Pair-breaking reads the conf's preprocessor maps, and the INFERRED
    half (automatic reshape boundaries) only exists after
    ``conf.finalize()`` runs shape inference (ADVICE round 5: specs
    computed before that could pair across a reshape and silently gather
    the activation path). Both network constructors finalize, so this
    only trips for hand-built configuration objects — loudly."""
    if getattr(net.conf, "_finalized", True) is False:
        raise RuntimeError(
            "tp_param_specs/shard_model need the conf's inferred input "
            "preprocessors, which are computed by shape inference: call "
            "net.init() (or conf.finalize()) before requesting "
            "tensor-parallel specs — otherwise column/row pairs could "
            "form across a reshape boundary and the all-gather-free "
            "activation path is silently lost")


def _layer_topology(net):
    """(key, layer, consumers) in forward order for both network kinds.

    MLN: keys are layer indices, consumer of i is [i+1]. ComputationGraph:
    keys are vertex names, consumers from the vertex-input edges (layer
    vertices only — elementwise vertices break pairs, which is correct:
    a residual add merges two activation shardings)."""
    if isinstance(net.params, dict):  # ComputationGraph
        vertices = net.conf.vertices
        consumers = {k: [] for k in vertices}
        n_inputs = {}
        for name, vd in vertices.items():
            n_inputs[name] = len(vd.inputs)
            for src in vd.inputs:
                if src in consumers:
                    consumers[src].append(name)
        # like the MLN branch: a per-vertex input preprocessor reshapes the
        # activation between the pair and would gather the column sharding
        graph_pre = set(getattr(net.conf, "preprocessors", {}) or {})

        def pairable_consumers(name):
            # ANY non-layer or multi-input consumer (residual tap, merge)
            # disqualifies pairing: the column-sharded activation would be
            # gathered on that edge, defeating the pair
            out = []
            for c in consumers[name]:
                if not (vertices[c].is_layer and n_inputs[c] == 1
                        and c not in graph_pre):
                    return []
                out.append(c)
            return out

        return [(name, vd.obj, pairable_consumers(name))
                for name, vd in vertices.items() if vd.is_layer]
    layers = list(net.layers)
    # an input preprocessor (explicit spec or inferred reshape) between two
    # layers breaks the pair, like a non-layer vertex does in a graph: the
    # column-sharded activation would be gathered at the reshape
    pre = set(getattr(net.conf, "preprocessors", {}) or {})
    pre |= set(getattr(net.conf, "input_pre_processors", {}) or {})
    return [(i, layer,
             [i + 1] if i + 1 < len(layers) and (i + 1) not in pre else [])
            for i, layer in enumerate(layers)]


def tp_param_specs(net, axis: str = MODEL_AXIS, mesh: Optional[Mesh] = None):
    """Megatron-pattern tensor-parallel PartitionSpecs (designed, round 5).

    Replaces the round-1 every-layer output-dim rule, which forced a GSPMD
    reshard between every consecutive pair of layers. The designed rule
    shards in *paired* column→row units so the activation between the pair
    stays sharded on the hidden dimension and the only collective is one
    all-reduce after the row matmul (the Megatron-LM MLP/attention
    pattern; SURVEY.md §2.b "Model/tensor parallelism" — the capability
    the reference lacks):

    - **Dense→Dense chains** (position-wise FFN, classifier heads): the
      first layer is column-parallel (``W: P(None, axis)``, ``b: P(axis)``),
      its unique dense consumer row-parallel (``W: P(axis, None)``,
      ``b: P()``). Pairs form greedily along the forward order; an
      OutputLayer may END a pair (its row all-reduce yields full logits
      for the loss) but never starts one (column-sharded logits would
      force a gather at the loss).
    - **Self-attention**: QKV projection column-split / output projection
      row-split within the layer (``Wqkv: P(None, axis)``,
      ``bqkv: P(axis)``, ``Wo: P(axis, None)``, ``bo: P()``) — one
      all-reduce per attention block.
    - Everything else (LayerNorm/BN scale-shift, embeddings, recurrent
      cells, conv) stays replicated: their params are small or their
      access pattern (vocab gather, scan carry) would trade one
      all-reduce for several.

    Measured on the 8-device CPU mesh (dp=2 × tp=4, 3-layer FFN forward:
    ``tests/test_parallel.py::test_megatron_specs_fewer_collectives``):
    the old rule compiles to **12 collectives (6 all-gather + 6
    all-reduce)**; the paired rule compiles to **3 all-reduce** — the
    canonical one-all-reduce-per-pair shape, a 4× reduction in collective
    count with zero all-gathers on the activation path.

    When ``mesh`` is given, a pair whose shared hidden dimension does not
    divide the model-axis size degrades JOINTLY to replicated (a half
    -degraded pair is worse than none: the sharded half's activation
    would be gathered anyway).
    """
    _require_inferred_preprocessors(net)
    topo = _layer_topology(net)
    by_key = {k: layer for k, layer, _ in topo}
    roles: Dict[object, str] = {}

    def tp_size():
        return mesh.shape[axis] if mesh is not None else None

    for key, layer, consumers in topo:
        if key in roles or not _dense_like(layer) or _is_output_layer(layer):
            continue
        if len(consumers) != 1:
            continue
        nxt = consumers[0]
        nxt_layer = by_key.get(nxt)
        if nxt_layer is None or nxt in roles or not _dense_like(nxt_layer):
            continue
        # the pair's shared hidden dim must divide the model axis
        if tp_size() is not None and layer.n_out % tp_size():
            continue
        roles[key] = _COLUMN
        roles[nxt] = _ROW

    def specs_for(key, layer, p: Dict) -> Dict[str, P]:
        if _is_attention(layer):
            # head-major Wqkv propagates through the (n,t,h,3,dh) reshape
            # iff tp divides n_heads (attention.py param_shapes)
            if tp_size() is not None and layer.n_heads % tp_size():
                return {n: P() for n in p}
            d = {"Wqkv": P(None, axis), "bqkv": P(axis)}
            if "Wo" in p:
                d["Wo"] = P(axis, None)
                d["bo"] = P()
            return {n: d.get(n, P()) for n in p}
        role = roles.get(key)
        if role == _COLUMN:
            return {n: (P(None, axis) if n == "W"
                        else P(axis) if n == "b" else P()) for n in p}
        if role == _ROW:
            return {n: (P(axis, None) if n == "W" else P()) for n in p}
        return {n: P() for n in p}

    if isinstance(net.params, dict):
        return {key: specs_for(key, by_key[key], p)
                for key, p in net.params.items() if key in by_key}
    return [specs_for(i, layer, p)
            for (i, layer), p in zip(enumerate(net.layers), net.params)]


# -- rule-based sharding: regex-over-param-path → PartitionSpec --------------
#
# The config-driven layer above tp_param_specs: one rule line shards any
# model without touching layer code. A rule is (regex, PartitionSpec);
# rules are tried in order against the '/'-joined param path ("vertex/W"
# for graphs, "0/W" for MultiLayerNetwork layer lists) and the FIRST
# match wins. Scalar / size-1 leaves are never partitioned; a param no
# rule matches fails loudly — a silently-replicated tensor is how a
# "sharded" job quietly stops fitting in HBM.

Rule = Tuple[str, P]

#: Shipped default rule set for the framework's transformer naming
#: convention (``transformer_encoder_block``/``transformer_decoder_block``
#: vertex names, ``embed``/``out`` heads). Reproduces the Megatron
#: column→row pairs ``tp_param_specs`` derives from topology, PLUS the
#: vocab path the pairing rule refuses on principle: the embedding table
#: is vocab-ROW-sharded (``jnp.take`` over a sharded axis-0 compiles to
#: masked local takes + one all-reduce, no gather) and the LM head is
#: vocab-COLUMN-sharded — its logits stay sharded through the
#: log-sum-exp cross-entropy (``losses.mcxent_logits`` routes softmax
#: losses through ``log_softmax``), so the whole path compiles with ZERO
#: all-gathers (asserted in tests/test_sharding_rules.py against HLO).
DEFAULT_2D_RULES: Tuple[Rule, ...] = (
    # vocab path: row-sharded embedding take …
    (r"(^|/)embed[^/]*/W$", P(MODEL_AXIS, None)),
    # … and column-sharded logits (+LSE loss keeps them sharded)
    (r"(^|/)(out|output|logits|lm_head)[^/]*/W$", P(None, MODEL_AXIS)),
    (r"(^|/)(out|output|logits|lm_head)[^/]*/b$", P(MODEL_AXIS)),
    # Megatron attention block: QKV column-split, output row-split
    (r"/Wqkv$", P(None, MODEL_AXIS)),
    (r"/bqkv$", P(MODEL_AXIS)),
    (r"/Wo$", P(MODEL_AXIS, None)),
    (r"/bo$", P()),
    # Megatron paired FFN: first matmul column, second row
    (r"ff1[^/]*/W$", P(None, MODEL_AXIS)),
    (r"ff1[^/]*/b$", P(MODEL_AXIS)),
    (r"ff2[^/]*/W$", P(MODEL_AXIS, None)),
    # everything else (LayerNorm/BN scale-shift, positional tables,
    # recurrent cells, conv) replicates
    (r".*", P()),
)


def _path_name(path) -> str:
    """'/'-joined name for a tree_util key path: dict keys and sequence
    indices both render bare (``0/W``, ``block0-att/Wqkv``)."""
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        else:  # pragma: no cover - future key kinds
            parts.append(str(k))
    return "/".join(parts)


def _as_spec(spec) -> P:
    if isinstance(spec, P):
        return spec
    if spec is None:
        return P()
    if isinstance(spec, (list, tuple)):
        return P(*[None if (s is None or s == "null") else str(s)
                   for s in spec])
    raise ValueError(f"bad partition spec {spec!r} (want PartitionSpec, "
                     f"None, or a list of axis names / null)")


def normalize_rules(rules: Sequence) -> List[Rule]:
    """Validate + canonicalize a rule list: each entry becomes
    ``(compiled-ok regex string, PartitionSpec)``."""
    out: List[Rule] = []
    for i, entry in enumerate(rules):
        try:
            pattern, spec = entry
        except (TypeError, ValueError):
            raise ValueError(
                f"rule[{i}] must be a (regex, spec) pair, got {entry!r}"
            ) from None
        if not isinstance(pattern, str):
            raise ValueError(f"rule[{i}] pattern must be a string, "
                             f"got {type(pattern).__name__}")
        try:
            re.compile(pattern)
        except re.error as e:
            raise ValueError(f"rule[{i}] regex {pattern!r} invalid: {e}") \
                from None
        out.append((pattern, _as_spec(spec)))
    if not out:
        raise ValueError("empty sharding rule list")
    return out


def load_sharding_rules(source) -> List[Rule]:
    """Load rules from a JSON file path / file object / parsed dict.

    Schema: ``{"rules": [[regex, [axis-or-null, ...]], ...]}`` — the
    spec array gives one entry per tensor dimension (trailing dims may
    be omitted = unsharded), ``null`` meaning replicated on that dim.
    """
    if isinstance(source, dict):
        doc = source
    elif hasattr(source, "read"):
        doc = json.load(source)
    else:
        with open(source, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
    if not isinstance(doc, dict) or "rules" not in doc:
        raise ValueError("sharding rules file must be an object with a "
                         "'rules' array")
    return normalize_rules(doc["rules"])


def _is_scalar_leaf(leaf) -> bool:
    shape = getattr(leaf, "shape", None)
    if shape is None:
        return True
    return len(shape) == 0 or int(np.prod(shape)) == 1


def match_partition_rules(rules: Sequence, params):
    """Map a param pytree to a same-structure PartitionSpec pytree by
    first-match regex over each leaf's '/'-joined path (the
    fmengine/EasyLM ``match_partition_rules`` pattern). Scalar and
    size-1 leaves are never partitioned (always ``P()``); a leaf no rule
    matches raises — add a catch-all ``(".*", P())`` rule to opt into
    replicate-by-default."""
    rules = normalize_rules(rules)

    def match(path, leaf):
        name = _path_name(path)
        if _is_scalar_leaf(leaf):
            return P()
        for pattern, spec in rules:
            if re.search(pattern, name):
                return spec
        raise ValueError(f"Partition rule not found for param: {name}")

    return jax.tree_util.tree_map_with_path(match, params)


def lint_partition_rules(rules: Sequence, params) -> List[str]:
    """Dry-run lint against a sample model's param tree: returns
    warnings (empty = clean) for unmatched params (would raise at
    placement time), dead rules (match nothing), and shadowed rules
    (every leaf they match is claimed by an earlier rule)."""
    rules = normalize_rules(rules)
    leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    names = [_path_name(p) for p, leaf in leaves
             if not _is_scalar_leaf(leaf)]
    problems: List[str] = []
    hits: List[set] = [set() for _ in rules]
    first_hit: Dict[str, int] = {}
    for name in names:
        matched = False
        for i, (pattern, _spec) in enumerate(rules):
            if re.search(pattern, name):
                hits[i].add(name)
                if not matched:
                    first_hit[name] = i
                matched = True
        if not matched:
            problems.append(f"param {name!r} matches no rule (placement "
                            f"would fail loudly)")
    for i, (pattern, _spec) in enumerate(rules):
        if not hits[i]:
            problems.append(f"rule[{i}] {pattern!r} matches no param of "
                            f"the sample model (dead rule?)")
        elif all(first_hit[n] != i for n in hits[i]):
            winners = sorted({first_hit[n] for n in hits[i]})
            problems.append(
                f"rule[{i}] {pattern!r} is fully shadowed by earlier "
                f"rule(s) {winners} — it can never win a match")
    return problems


def shard_model_with_rules(net, mesh: Mesh, rules: Optional[Sequence] = None
                           ) -> None:
    """Place a model on a DP×MP mesh from a rule list, in-place (the
    config-line counterpart of :func:`shard_model`): params by
    first-match rule, updater-state leaves sharing the param's spec when
    shapes match, layer states replicated. Records the mesh on the net
    (``net._mesh``) so ``fit``/``output`` shard incoming batches over
    the ``data`` axis end to end.

    ``rules=None`` uses :data:`DEFAULT_2D_RULES`. A matched leaf whose
    dims do not divide the named axes degrades to replicated (same
    contract as ``shard_model``'s Megatron path)."""
    specs = match_partition_rules(
        DEFAULT_2D_RULES if rules is None else rules, net.params)
    repl = replicated(mesh)
    placed: Dict[str, Tuple[tuple, P]] = {}

    def place_param(path, v, spec):
        if not _leaf_sharding_ok(v.shape, spec, mesh):
            spec = P()
        placed[_path_name(path)] = (tuple(v.shape), spec)
        return jax.device_put(v, NamedSharding(mesh, spec))

    new_params = jax.tree_util.tree_map_with_path(place_param, net.params,
                                                  specs)
    if net.updater_states is not None:
        def upd_sharding(path, s):
            # updater moments live at <param-path>/<slot-name> and share
            # the param's spec when shapes match (momentum etc.)
            shape_spec = placed.get(_path_name(path[:-1]))
            if shape_spec is not None and tuple(s.shape) == shape_spec[0]:
                return NamedSharding(mesh, shape_spec[1])
            return repl
        upd_sh = jax.tree_util.tree_map_with_path(upd_sharding,
                                                  net.updater_states)
        net.updater_states = jax.tree_util.tree_map(
            jax.device_put, net.updater_states, upd_sh)
        net._upd_shardings = upd_sh
    net.params = new_params
    net.states = jax.device_put(net.states, repl)
    net._mesh = mesh
    # the train step pins its updated params/opt-state to these (GSPMD
    # would otherwise pick its own output shardings — one drifted leaf
    # re-layouts every later compile and re-introduces all-gathers)
    net._param_shardings = jax.tree_util.tree_map(
        lambda v: v.sharding, new_params)
    # steps compiled before placement know nothing about the pins
    net._jit_cache.clear()


def _leaf_sharding_ok(shape, spec: P, mesh: Mesh) -> bool:
    for dim, ax in zip(shape, tuple(spec) + (None,) * (len(shape) - len(spec))):
        if ax is None:
            continue
        for a in (ax if isinstance(ax, tuple) else (ax,)):
            # an axis the mesh does not have (e.g. 2-D rules on a
            # data-only mesh) degrades to replicated, same as a
            # non-dividing dim
            if a not in mesh.shape or dim % mesh.shape[a]:
                return False
    return True


def shard_model(net, mesh: Mesh, tp_axis: Optional[str] = None) -> None:
    """Place a model's params / states / updater states on the mesh, in-place.
    Works for both MultiLayerNetwork (list params) and ComputationGraph
    (dict params keyed by vertex name).

    ``tp_axis=None`` → fully replicated (pure data parallel).
    ``tp_axis='model'`` → Megatron paired specs from :func:`tp_param_specs`;
    any leaf whose dims don't divide the axis falls back to replicated.
    """
    repl = replicated(mesh)
    if tp_axis is None:
        net.params = jax.device_put(net.params, repl)
        net.states = jax.device_put(net.states, repl)
        net.updater_states = jax.device_put(net.updater_states, repl)
        return

    specs = tp_param_specs(net, tp_axis, mesh)
    is_graph = isinstance(net.params, dict)
    keys = list(net.params.keys()) if is_graph else range(len(net.params))

    def place(key):
        pd = net.params[key]
        sd = (specs.get(key, {}) if is_graph else specs[key])
        pl, ul = {}, {}
        for n, v in pd.items():
            spec = sd.get(n, P())
            if not _leaf_sharding_ok(v.shape, spec, mesh):
                spec = P()
            sh = NamedSharding(mesh, spec)
            pl[n] = jax.device_put(v, sh)
            # updater state leaves (momentum etc.) share the param's shape/spec
            ul[n] = {
                k: jax.device_put(s, sh if s.shape == v.shape else repl)
                for k, s in net.updater_states[key][n].items()
            }
        return pl, ul

    if is_graph:
        new_params, new_upd = {}, {}
        for key in keys:
            new_params[key], new_upd[key] = place(key)
    else:
        new_params, new_upd = [], []
        for key in keys:
            pl, ul = place(key)
            new_params.append(pl)
            new_upd.append(ul)
    net.params = new_params
    net.updater_states = new_upd
    net.states = jax.device_put(net.states, repl)
