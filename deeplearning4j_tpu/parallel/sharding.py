"""Sharding rules: how model pytrees map onto a Mesh.

This replaces the reference's model replication (`ParallelWrapper.java:78`
clones the net per worker thread) with sharding annotations: a replicated
param lives once per device HBM but is updated by a single SPMD program; a
tensor-parallel param is *split* across the 'model' axis and XLA inserts the
matching collectives (all-gather / reduce-scatter) around the matmuls.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, ndim: int, axis: str = DATA_AXIS) -> NamedSharding:
    """Shard the leading (batch) dimension over the data axis."""
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


_COLUMN = "column"
_ROW = "row"


def _dense_like(layer) -> bool:
    """Layers holding one [n_in, n_out] matmul W (+ bias b): the building
    blocks of Megatron column/row pairs. OutputLayer subclasses DenseLayer."""
    from deeplearning4j_tpu.nn.layers.core import DenseLayer
    return isinstance(layer, DenseLayer)


def _is_output_layer(layer) -> bool:
    from deeplearning4j_tpu.nn.layers.output import OutputLayer
    return isinstance(layer, OutputLayer)


def _is_attention(layer) -> bool:
    from deeplearning4j_tpu.nn.layers.attention import SelfAttentionLayer
    return isinstance(layer, SelfAttentionLayer)


def _require_inferred_preprocessors(net) -> None:
    """Pair-breaking reads the conf's preprocessor maps, and the INFERRED
    half (automatic reshape boundaries) only exists after
    ``conf.finalize()`` runs shape inference (ADVICE round 5: specs
    computed before that could pair across a reshape and silently gather
    the activation path). Both network constructors finalize, so this
    only trips for hand-built configuration objects — loudly."""
    if getattr(net.conf, "_finalized", True) is False:
        raise RuntimeError(
            "tp_param_specs/shard_model need the conf's inferred input "
            "preprocessors, which are computed by shape inference: call "
            "net.init() (or conf.finalize()) before requesting "
            "tensor-parallel specs — otherwise column/row pairs could "
            "form across a reshape boundary and the all-gather-free "
            "activation path is silently lost")


def _layer_topology(net):
    """(key, layer, consumers) in forward order for both network kinds.

    MLN: keys are layer indices, consumer of i is [i+1]. ComputationGraph:
    keys are vertex names, consumers from the vertex-input edges (layer
    vertices only — elementwise vertices break pairs, which is correct:
    a residual add merges two activation shardings)."""
    if isinstance(net.params, dict):  # ComputationGraph
        vertices = net.conf.vertices
        consumers = {k: [] for k in vertices}
        n_inputs = {}
        for name, vd in vertices.items():
            n_inputs[name] = len(vd.inputs)
            for src in vd.inputs:
                if src in consumers:
                    consumers[src].append(name)
        # like the MLN branch: a per-vertex input preprocessor reshapes the
        # activation between the pair and would gather the column sharding
        graph_pre = set(getattr(net.conf, "preprocessors", {}) or {})

        def pairable_consumers(name):
            # ANY non-layer or multi-input consumer (residual tap, merge)
            # disqualifies pairing: the column-sharded activation would be
            # gathered on that edge, defeating the pair
            out = []
            for c in consumers[name]:
                if not (vertices[c].is_layer and n_inputs[c] == 1
                        and c not in graph_pre):
                    return []
                out.append(c)
            return out

        return [(name, vd.obj, pairable_consumers(name))
                for name, vd in vertices.items() if vd.is_layer]
    layers = list(net.layers)
    # an input preprocessor (explicit spec or inferred reshape) between two
    # layers breaks the pair, like a non-layer vertex does in a graph: the
    # column-sharded activation would be gathered at the reshape
    pre = set(getattr(net.conf, "preprocessors", {}) or {})
    pre |= set(getattr(net.conf, "input_pre_processors", {}) or {})
    return [(i, layer,
             [i + 1] if i + 1 < len(layers) and (i + 1) not in pre else [])
            for i, layer in enumerate(layers)]


def tp_param_specs(net, axis: str = MODEL_AXIS, mesh: Optional[Mesh] = None):
    """Megatron-pattern tensor-parallel PartitionSpecs (designed, round 5).

    Replaces the round-1 every-layer output-dim rule, which forced a GSPMD
    reshard between every consecutive pair of layers. The designed rule
    shards in *paired* column→row units so the activation between the pair
    stays sharded on the hidden dimension and the only collective is one
    all-reduce after the row matmul (the Megatron-LM MLP/attention
    pattern; SURVEY.md §2.b "Model/tensor parallelism" — the capability
    the reference lacks):

    - **Dense→Dense chains** (position-wise FFN, classifier heads): the
      first layer is column-parallel (``W: P(None, axis)``, ``b: P(axis)``),
      its unique dense consumer row-parallel (``W: P(axis, None)``,
      ``b: P()``). Pairs form greedily along the forward order; an
      OutputLayer may END a pair (its row all-reduce yields full logits
      for the loss) but never starts one (column-sharded logits would
      force a gather at the loss).
    - **Self-attention**: QKV projection column-split / output projection
      row-split within the layer (``Wqkv: P(None, axis)``,
      ``bqkv: P(axis)``, ``Wo: P(axis, None)``, ``bo: P()``) — one
      all-reduce per attention block.
    - Everything else (LayerNorm/BN scale-shift, embeddings, recurrent
      cells, conv) stays replicated: their params are small or their
      access pattern (vocab gather, scan carry) would trade one
      all-reduce for several.

    Measured on the 8-device CPU mesh (dp=2 × tp=4, 3-layer FFN forward:
    ``tests/test_parallel.py::test_megatron_specs_fewer_collectives``):
    the old rule compiles to **12 collectives (6 all-gather + 6
    all-reduce)**; the paired rule compiles to **3 all-reduce** — the
    canonical one-all-reduce-per-pair shape, a 4× reduction in collective
    count with zero all-gathers on the activation path.

    When ``mesh`` is given, a pair whose shared hidden dimension does not
    divide the model-axis size degrades JOINTLY to replicated (a half
    -degraded pair is worse than none: the sharded half's activation
    would be gathered anyway).
    """
    _require_inferred_preprocessors(net)
    topo = _layer_topology(net)
    by_key = {k: layer for k, layer, _ in topo}
    roles: Dict[object, str] = {}

    def tp_size():
        return mesh.shape[axis] if mesh is not None else None

    for key, layer, consumers in topo:
        if key in roles or not _dense_like(layer) or _is_output_layer(layer):
            continue
        if len(consumers) != 1:
            continue
        nxt = consumers[0]
        nxt_layer = by_key.get(nxt)
        if nxt_layer is None or nxt in roles or not _dense_like(nxt_layer):
            continue
        # the pair's shared hidden dim must divide the model axis
        if tp_size() is not None and layer.n_out % tp_size():
            continue
        roles[key] = _COLUMN
        roles[nxt] = _ROW

    def specs_for(key, layer, p: Dict) -> Dict[str, P]:
        if _is_attention(layer):
            # head-major Wqkv propagates through the (n,t,h,3,dh) reshape
            # iff tp divides n_heads (attention.py param_shapes)
            if tp_size() is not None and layer.n_heads % tp_size():
                return {n: P() for n in p}
            d = {"Wqkv": P(None, axis), "bqkv": P(axis)}
            if "Wo" in p:
                d["Wo"] = P(axis, None)
                d["bo"] = P()
            return {n: d.get(n, P()) for n in p}
        role = roles.get(key)
        if role == _COLUMN:
            return {n: (P(None, axis) if n == "W"
                        else P(axis) if n == "b" else P()) for n in p}
        if role == _ROW:
            return {n: (P(axis, None) if n == "W" else P()) for n in p}
        return {n: P() for n in p}

    if isinstance(net.params, dict):
        return {key: specs_for(key, by_key[key], p)
                for key, p in net.params.items() if key in by_key}
    return [specs_for(i, layer, p)
            for (i, layer), p in zip(enumerate(net.layers), net.params)]


def _leaf_sharding_ok(shape, spec: P, mesh: Mesh) -> bool:
    for dim, ax in zip(shape, tuple(spec) + (None,) * (len(shape) - len(spec))):
        if ax is None:
            continue
        if dim % mesh.shape[ax]:
            return False
    return True


def shard_model(net, mesh: Mesh, tp_axis: Optional[str] = None) -> None:
    """Place a model's params / states / updater states on the mesh, in-place.
    Works for both MultiLayerNetwork (list params) and ComputationGraph
    (dict params keyed by vertex name).

    ``tp_axis=None`` → fully replicated (pure data parallel).
    ``tp_axis='model'`` → Megatron paired specs from :func:`tp_param_specs`;
    any leaf whose dims don't divide the axis falls back to replicated.
    """
    repl = replicated(mesh)
    if tp_axis is None:
        net.params = jax.device_put(net.params, repl)
        net.states = jax.device_put(net.states, repl)
        net.updater_states = jax.device_put(net.updater_states, repl)
        return

    specs = tp_param_specs(net, tp_axis, mesh)
    is_graph = isinstance(net.params, dict)
    keys = list(net.params.keys()) if is_graph else range(len(net.params))

    def place(key):
        pd = net.params[key]
        sd = (specs.get(key, {}) if is_graph else specs[key])
        pl, ul = {}, {}
        for n, v in pd.items():
            spec = sd.get(n, P())
            if not _leaf_sharding_ok(v.shape, spec, mesh):
                spec = P()
            sh = NamedSharding(mesh, spec)
            pl[n] = jax.device_put(v, sh)
            # updater state leaves (momentum etc.) share the param's shape/spec
            ul[n] = {
                k: jax.device_put(s, sh if s.shape == v.shape else repl)
                for k, s in net.updater_states[key][n].items()
            }
        return pl, ul

    if is_graph:
        new_params, new_upd = {}, {}
        for key in keys:
            new_params[key], new_upd[key] = place(key)
    else:
        new_params, new_upd = [], []
        for key in keys:
            pl, ul = place(key)
            new_params.append(pl)
            new_upd.append(ul)
    net.params = new_params
    net.updater_states = new_upd
    net.states = jax.device_put(net.states, repl)
