"""Sharding rules: how model pytrees map onto a Mesh.

This replaces the reference's model replication (`ParallelWrapper.java:78`
clones the net per worker thread) with sharding annotations: a replicated
param lives once per device HBM but is updated by a single SPMD program; a
tensor-parallel param is *split* across the 'model' axis and XLA inserts the
matching collectives (all-gather / reduce-scatter) around the matmuls.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, ndim: int, axis: str = DATA_AXIS) -> NamedSharding:
    """Shard the leading (batch) dimension over the data axis."""
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def tp_param_specs(net, axis: str = MODEL_AXIS) -> List[Dict[str, P]]:
    """Megatron-style tensor-parallel PartitionSpecs for a sequential net.

    Rule of thumb for round-1 TP: shard every weight's output-feature
    dimension (last axis of W / pW / conv kernels, the bias vector, and
    BN scale/shift) over the model axis. XLA GSPMD propagates the resulting
    activation shardings and inserts collectives; this is the capability the
    reference lacks entirely (SURVEY.md §2.b: "Model/tensor parallelism: No").
    """
    specs: List[Dict[str, P]] = []
    for layer, p in zip(net.layers, net.params):
        d: Dict[str, P] = {}
        for n, v in p.items():
            if v.ndim >= 2 and v.shape[-1] > 1:
                d[n] = P(*([None] * (v.ndim - 1)), axis)
            elif v.ndim == 1 and v.shape[0] > 1:
                d[n] = P(axis)
            else:
                d[n] = P()
        specs.append(d)
    return specs


def _leaf_sharding_ok(shape, spec: P, mesh: Mesh) -> bool:
    for dim, ax in zip(shape, tuple(spec) + (None,) * (len(shape) - len(spec))):
        if ax is None:
            continue
        if dim % mesh.shape[ax]:
            return False
    return True


def shard_model(net, mesh: Mesh, tp_axis: Optional[str] = None) -> None:
    """Place a model's params / states / updater states on the mesh, in-place.

    ``tp_axis=None`` → fully replicated (pure data parallel).
    ``tp_axis='model'`` → tensor-parallel specs from :func:`tp_param_specs`;
    any leaf whose dims don't divide the axis falls back to replicated.
    """
    repl = replicated(mesh)
    if tp_axis is None:
        net.params = jax.device_put(net.params, repl)
        net.states = jax.device_put(net.states, repl)
        net.updater_states = jax.device_put(net.updater_states, repl)
        return

    specs = tp_param_specs(net, tp_axis)
    new_params, new_upd = [], []
    for li, (pd, sd) in enumerate(zip(net.params, specs)):
        pl, ul = {}, {}
        for n, v in pd.items():
            spec = sd.get(n, P())
            if not _leaf_sharding_ok(v.shape, spec, mesh):
                spec = P()
            sh = NamedSharding(mesh, spec)
            pl[n] = jax.device_put(v, sh)
            # updater state leaves (momentum etc.) share the param's shape/spec
            ul[n] = {
                k: jax.device_put(s, sh if s.shape == v.shape else repl)
                for k, s in net.updater_states[li][n].items()
            }
        new_params.append(pl)
        new_upd.append(ul)
    net.params = new_params
    net.updater_states = new_upd
    net.states = jax.device_put(net.states, repl)
