"""Device mesh construction helpers.

The reference pins worker threads to devices round-robin
(`ParallelWrapper.java:125-137`, `AffinityManager.attachThreadToDevice`). The
TPU-native equivalent is a named `jax.sharding.Mesh`: axes are logical
parallelism dimensions (data / model / pipeline / sequence / expert) and XLA
lays collectives onto ICI links following the mesh topology.
"""

from __future__ import annotations

import inspect

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

try:  # jax >= 0.4.35 exposes shard_map at top level
    from jax import shard_map as _shard_map_impl
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map_impl


def _shard_map_check_kwarg() -> Optional[str]:
    """Which disable-replication-checking kwarg THIS jax's shard_map takes
    (``check_vma`` on recent jax, ``check_rep`` before, None when neither
    is inspectable). Resolved from the wrapper's signature, NOT by probing
    with try/except TypeError: a bare retry-on-TypeError also swallowed
    genuine TypeErrors raised while tracing the user ``fn`` (e.g. a body
    with the wrong arity), silently re-running the broken trace and then
    reporting a misleading missing-kwarg failure."""
    try:
        params = inspect.signature(_shard_map_impl).parameters
    except (TypeError, ValueError):  # pragma: no cover - C accelerated impl
        return None
    for kw in ("check_vma", "check_rep"):
        if kw in params:
            return kw
    if any(p.kind is inspect.Parameter.VAR_KEYWORD
           for p in params.values()):  # pragma: no cover - jax version
        return "check_vma"
    return None  # pragma: no cover - neither kwarg exists on this jax


_CHECK_KWARG = _shard_map_check_kwarg()


def shard_map(fn, *, mesh, in_specs, out_specs):
    """Version-tolerant shard_map with replication checking disabled
    (the kwarg is ``check_vma`` on recent jax, ``check_rep`` before).
    The kwarg is resolved once from the implementation's signature, so a
    TypeError raised from the user's ``fn`` propagates untouched."""
    kwargs = {} if _CHECK_KWARG is None else {_CHECK_KWARG: False}
    return _shard_map_impl(fn, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, **kwargs)

# Canonical axis names used across the framework.
DATA_AXIS = "data"
MODEL_AXIS = "model"
PIPELINE_AXIS = "pipe"
SEQUENCE_AXIS = "seq"
EXPERT_AXIS = "expert"


def parse_mesh_axes(spec: str) -> Dict[str, int]:
    """Parse the CLI/env mesh-shape grammar ``"data=4,model=2"`` into the
    ``{axis: size}`` dict :func:`make_mesh` takes. ``-1`` (at most one
    axis) means inferred. The string form is what crosses process
    boundaries — the ``train``/``serve`` flags and the elastic
    supervisor→worker environment both carry it."""
    axes: Dict[str, int] = {}
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"bad mesh axis {part!r} in {spec!r} (want name=size, "
                f"e.g. data=4,model=2)")
        name, _, size = part.partition("=")
        name = name.strip()
        if not name or name in axes:
            raise ValueError(f"bad or duplicate mesh axis name in {spec!r}")
        try:
            n = int(size)
        except ValueError:
            raise ValueError(
                f"mesh axis {name!r} has non-integer size {size!r}") from None
        if n == 0 or n < -1:
            raise ValueError(
                f"mesh axis {name!r} size must be positive or -1 "
                f"(inferred), got {n}")
        axes[name] = n
    if not axes:
        raise ValueError(f"empty mesh spec {spec!r}")
    if sum(1 for s in axes.values() if s == -1) > 1:
        raise ValueError(f"at most one mesh axis may be -1: {spec!r}")
    return axes


def format_mesh_axes(axes: Dict[str, int]) -> str:
    """Inverse of :func:`parse_mesh_axes` (axis order preserved)."""
    return ",".join(f"{k}={int(v)}" for k, v in axes.items())


def make_mesh(axes: Optional[Dict[str, int]] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a Mesh from ``{axis_name: size}``.

    At most one axis size may be -1 (inferred, like a reshape). Default is a
    pure data-parallel mesh over all addressable devices.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)
    if axes is None:
        axes = {DATA_AXIS: n}
    names = list(axes.keys())
    sizes = list(axes.values())
    n_infer = sum(1 for s in sizes if s == -1)
    if n_infer > 1:
        raise ValueError("at most one mesh axis may be -1")
    if n_infer == 1:
        known = int(np.prod([s for s in sizes if s != -1])) if len(sizes) > 1 else 1
        if n % known:
            raise ValueError(f"cannot infer axis: {n} devices not divisible by {known}")
        sizes = [n // known if s == -1 else s for s in sizes]
    total = int(np.prod(sizes))
    if total > n:
        raise ValueError(f"mesh wants {total} devices, only {n} available")
    arr = np.asarray(devices[:total]).reshape(sizes)
    return Mesh(arr, tuple(names))


def local_mesh(n: Optional[int] = None, axis: str = DATA_AXIS) -> Mesh:
    """1-D data-parallel mesh over the first ``n`` local devices."""
    devices = jax.local_devices()
    if n is not None:
        devices = devices[:n]
    return make_mesh({axis: len(devices)}, devices)


def is_multiprocess(mesh: Mesh) -> bool:
    """True when the mesh spans devices owned by other processes (a real
    multi-host/multi-process run under ``jax.distributed``)."""
    me = jax.process_index()
    return any(d.process_index != me for d in mesh.devices.flat)


def make_global(tree, mesh: Mesh, spec) -> object:
    """Host-local full copies → GLOBAL jax.Arrays over a multi-process mesh.

    Every process passes the SAME full-value tree (the single-controller
    contract: identical host data everywhere, e.g. replicated params or a
    full batch about to be split over the data axis); each process
    contributes only its addressable shards via ``make_array_from_callback``.
    This is the per-host input seam the reference fills with Spark broadcast
    + ``ExecuteWorkerFlatMap`` (SURVEY §3.3) — here the "broadcast" is the
    deterministic, identical host computation on each process.
    """
    from jax.sharding import NamedSharding

    sharding = NamedSharding(mesh, spec)

    def conv(a):
        a = np.asarray(a)
        return jax.make_array_from_callback(a.shape, sharding,
                                            lambda idx: a[idx])

    return jax.tree_util.tree_map(conv, tree)
