"""Cross-slice (DCN) gradient bridge: threshold-compressed update exchange
between pods.

The reference's cross-node story is Aeron UDP carrying threshold-encoded
sparse gradient messages between every node
(`SharedTrainingMaster.java:493`, `WiredEncodingHandler.java:96`,
`EncodedGradientsAccumulator.java:257` decode-and-apply). On TPU the
*intra-slice* half of that design collapses into `psum` over ICI
(`parallel/master.py`); this module is the *inter-slice* half — slices (or
pods) whose only link is the data-center network exchange quantized updates:

    slice A trains (psum over its own ICI)
        → residual += its aggregate update
        → threshold-encode (native codec, signed-index wire format)
        → frame over the streaming transport (socket / broker / kafka)
    slice B receives → decode → apply to its params (and vice versa)

Updates below the threshold stay in the per-slice residual, exactly the
EncodingHandler semantics; the wire format is the C++ codec's so a message
encoded on one host decodes on any other.
"""

from __future__ import annotations

import json
import logging
import struct
import uuid
from typing import Dict, Optional

import numpy as np

from deeplearning4j_tpu.native import encode_threshold, extract_threshold
# stdlib-only module: safe to import at the top without cycles. While no
# tracer is enabled, every span site below is a single None check
from deeplearning4j_tpu.observe.trace import parse_traceparent
from deeplearning4j_tpu.observe.trace import span as _span

log = logging.getLogger(__name__)


class CrossSliceGradientBridge:
    """One endpoint of the inter-slice exchange.

    ``publisher``/``consumer`` carry opaque frames (SocketPublisher/
    SocketConsumer, an EmbeddedBroker wrapper, or anything with
    ``publish(bytes)`` / ``poll(timeout)->bytes``). Each endpoint tracks its
    own residual per parameter tensor.
    """

    def __init__(self, publisher, consumer, threshold: float = 1e-3,
                 capacity_fraction: float = 0.25, slice_id: str = "slice",
                 host: Optional[int] = None):
        from deeplearning4j_tpu.util import faultinject
        self.publisher = publisher
        self.consumer = consumer
        self.threshold = float(threshold)
        self.capacity_fraction = capacity_fraction
        self.slice_id = slice_id
        # host failure domain this endpoint lives in (rides every frame
        # header so receivers can honor a DCN partition between host
        # groups); defaults to the elastic supervisor's assignment
        self.host = faultinject.current_host() if host is None else host
        # {layer_key: {param_name: flat f32 residual}}; _prev mirrors it with
        # the param values as of the last exchange
        self._residual: Optional[Dict] = None
        self._prev: Optional[Dict] = None
        # monotone per-endpoint frame sequence: receivers drop replays (a
        # re-delivering broker, the duplicate_dcn fault) instead of
        # applying the same update twice. The incarnation token makes a
        # RESTARTED sender (elastic recovery rebuilds the bridge, seq
        # back at 0) distinguishable from a replay — receivers reset the
        # peer's high-water mark when it changes
        self._seq = 0
        self._incarnation = uuid.uuid4().hex[:8]
        # slice -> {incarnation: high-water seq}; PER-incarnation marks
        # (not just the latest) so a broker redelivering a frame from a
        # peer's previous life is still dropped after that peer restarts.
        # One entry per peer restart — bounded by restart budgets.
        self._last_seq: Dict[str, Dict[str, int]] = {}

    # -- param-structure helpers (list of dicts = MLN, dict of dicts = CG) --
    @staticmethod
    def _layers(params):
        if isinstance(params, dict):
            return sorted(params.items())
        return list(enumerate(params))

    # -- tracking the local model ----------------------------------------
    def _ensure_residual(self, params) -> None:
        if self._residual is None:
            self._residual = {
                lk: {k: np.zeros(int(v.size), np.float32)
                     for k, v in layer.items()}
                for lk, layer in self._layers(params)}
            self._prev = {
                lk: {k: np.asarray(v, np.float32).reshape(-1).copy()
                     for k, v in layer.items()}
                for lk, layer in self._layers(params)}

    def publish_update(self, params) -> int:
        """Accumulate the params' movement since the last call into the
        residual, encode what clears the threshold, send ONE frame. Returns
        bytes sent (0 when nothing cleared the threshold — no frame).

        Residual bookkeeping happens only AFTER a successful publish: a
        transport failure leaves the mass in the residual for the next round
        instead of silently dropping it.
        """
        self._ensure_residual(params)
        sections = []
        blobs = []
        pending = []  # (residual, msg_or_None) — applied post-publish
        total = 0
        for lk, layer in self._layers(params):
            for k in sorted(layer):
                cur = np.asarray(layer[k], np.float32).reshape(-1)
                delta = cur - self._prev[lk][k]
                self._prev[lk][k] = cur.copy()
                r = self._residual[lk][k]
                r += delta
                cap = max(16, int(len(r) * self.capacity_fraction))
                msg = encode_threshold(r, self.threshold, capacity=cap)
                if msg is None:
                    # too dense for the sparse format: dense fallback
                    # (count = -1 → raw f32 payload), the WiredEncodingHandler
                    # bitmap-worst-case role — never silently unsynced
                    sections.append({"layer": lk, "param": k, "count": -1,
                                     "size": len(r)})
                    blobs.append(r.astype(np.float32).tobytes())
                    pending.append((r, None))
                    total += len(r)
                elif len(msg):
                    sections.append({"layer": lk, "param": k,
                                     "count": len(msg), "size": len(r)})
                    blobs.append(msg.tobytes())
                    pending.append((r, msg))
                    total += len(msg)
        if total == 0:
            return 0  # nothing to say this round
        seq = self._seq
        # consume the seq BEFORE publishing: a publish that raises after
        # the transport delivered the bytes must not lead to the next
        # exchange reusing this number (receivers would drop it as a
        # replay and the residual extracted below would be lost at every
        # peer); receivers tolerate gaps — the dedup check is <=
        self._seq = seq + 1
        with _span("dcn_send", category="dcn",
                   attrs={"slice": self.slice_id, "seq": seq,
                          "sections": len(sections)}) as sp:
            header_obj = {"slice": self.slice_id, "seq": seq,
                          "inc": self._incarnation,
                          "host": self.host,
                          "threshold": self.threshold,
                          "sections": sections}
            if sp is not None:
                # the send span's identity rides the frame: the receiver
                # links its dcn_recv to it, so a cross-worker exchange
                # renders as a flow arrow in the merged fleet trace
                header_obj["tp"] = sp.context.traceparent()
            header = json.dumps(header_obj).encode()
            frame = struct.pack(">I", len(header)) + header + b"".join(blobs)
            if sp is not None:
                sp.set_attribute("bytes", len(frame))
            from deeplearning4j_tpu.util import faultinject
            for out in faultinject.on_dcn_send(self.slice_id, seq, frame,
                                               host=self.host):
                # an injected [] drops the frame IN TRANSIT: the sender
                # has committed (seq consumed, residual extracted)
                # exactly like a frame lost on the wire after a
                # successful send
                self.publisher.publish(out)  # may raise: residual intact
        for r, msg in pending:
            if msg is None:
                r[:] = 0.0  # dense payload carried the whole residual
            else:
                extract_threshold(r, self.threshold, msg)
        return len(frame)

    def poll_and_apply(self, params, timeout: float = 0.0,
                       max_messages: int = 16):
        """Apply every pending remote frame to ``params``; returns the new
        params pytree (jax arrays stay jax arrays) and the frame count."""
        import jax.numpy as jnp

        from deeplearning4j_tpu.util import faultinject

        self._ensure_residual(params)
        applied = 0
        dense: Optional[Dict] = None
        for _ in range(max_messages):
            frame = self.consumer.poll(timeout=timeout)
            if frame is None:
                break
            try:
                hlen = struct.unpack(">I", frame[:4])[0]
                meta = json.loads(frame[4:4 + hlen].decode())
                slice_tag = meta.get("slice")
                thr = float(meta["threshold"])
                sections = meta["sections"]
            except (struct.error, json.JSONDecodeError, UnicodeDecodeError,
                    KeyError, ValueError, TypeError) as e:
                log.warning("Dropping unparseable frame: %s", e)
                continue
            if slice_tag == self.slice_id:
                # own broadcast echoed back (broker fan-out); skip payload
                continue
            seq = meta.get("seq")
            if seq is not None and not faultinject.on_dcn_recv(
                    self.slice_id, int(seq), frame_host=meta.get("host"),
                    host=self.host):
                log.warning("Dropping frame %s from %s: DCN partition "
                            "between host groups %s and %s", seq,
                            slice_tag, self.host, meta.get("host"))
                continue
            if seq is not None:
                inc = meta.get("inc")
                peer = self._last_seq.setdefault(slice_tag, {})
                last = peer.get(inc)
                if last is not None and int(seq) <= last:
                    log.warning("Dropping duplicate frame %s from %s",
                                seq, slice_tag)
                    continue
                peer[inc] = int(seq)
            if dense is None:
                dense = {lk: {k: np.zeros(int(v.size), np.float32)
                              for k, v in layer.items()}
                         for lk, layer in self._layers(params)}
            with _span("dcn_recv", category="dcn",
                       attrs={"slice": self.slice_id, "from": slice_tag,
                              "seq": seq, "bytes": len(frame)}) as sp:
                if sp is not None:
                    # link to the sender's dcn_send span (flow arrow in
                    # the merged trace); add_link(None) is a no-op for
                    # frames from un-traced peers
                    sp.add_link(parse_traceparent(meta.get("tp")))
                decoded_any = self._decode_frame(frame, hlen, sections,
                                                 thr, dense, meta)
            if decoded_any:
                applied += 1
        if dense is None or applied == 0:
            return params, 0

        def updated(lk, layer):
            out = {}
            for k, v in layer.items():
                upd = dense[lk][k].reshape(v.shape)
                out[k] = v + jnp.asarray(upd, dtype=v.dtype)
            return out

        if isinstance(params, dict):
            new_params = {lk: updated(lk, layer)
                          for lk, layer in self._layers(params)}
        else:
            new_params = [updated(lk, layer)
                          for lk, layer in self._layers(params)]
        # the movement we just applied must not re-enter publish deltas
        for lk, layer in self._layers(new_params):
            for k in layer:
                self._prev[lk][k] = np.asarray(
                    layer[k], np.float32).reshape(-1).copy()
        return new_params, applied

    def _decode_frame(self, frame, hlen, sections, thr, dense, meta) -> bool:
        """Decode one frame's sections into ``dense``; False when the
        frame was malformed (dropped without touching training or the
        frames already decoded this call)."""
        from deeplearning4j_tpu.native import decode_threshold
        off = 4 + hlen
        decoded_any = False
        try:
            for s in sections:
                count, size = int(s["count"]), int(s["size"])
                if count < -1 or size < 0:
                    raise ValueError("negative section count/size")
                is_dense = count == -1
                n_bytes = (size if is_dense else count) * 4
                if off + n_bytes > len(frame):
                    raise ValueError("frame truncated mid-section")
                payload = frame[off:off + n_bytes]
                off += n_bytes
                lk = s["layer"]
                # validate against the LOCAL model: unknown names or size
                # mismatches (version-skewed peer, corrupt frame) are
                # skipped — never an out-of-bounds write in the decoder
                target = dense.get(lk, {}).get(s["param"]) \
                    if isinstance(dense.get(lk), dict) else None
                if target is None or len(target) != size:
                    log.warning("Skipping mismatched section %r/%r from %s",
                                lk, s["param"], meta.get("slice"))
                    continue
                if is_dense:
                    target += np.frombuffer(payload, np.float32)
                else:
                    msg = np.frombuffer(payload, np.int32)
                    decode_threshold(msg, thr, len(target), out=target)
                decoded_any = decoded_any or n_bytes > 0
        except (ValueError, KeyError, TypeError) as e:
            # a malformed frame must not kill training or discard the
            # frames already decoded into `dense` this call
            log.warning("Dropping malformed frame from %s: %s",
                        meta.get("slice"), e)
            return False
        return decoded_any
