"""ParallelWrapper — mesh-sharded distributed training.

Reference semantics being reproduced (SURVEY.md §2.b):

- ``ParallelWrapper.java:58-137``: single-node data parallelism with
  ``TrainingMode.SHARED_GRADIENTS`` (per-step gradient sync via
  ``EncodedGradientsAccumulator``) and ``TrainingMode.AVERAGING``
  (parameter + updater-state averaging every ``averagingFrequency``
  iterations, ``:250-256,338``).
- ``ParameterAveragingTrainingMaster.java:308``: the multi-node sync variant
  of the same averaging math.

TPU-native design — no thread replication, no message passing:

- **shared_gradients** (default): the global batch is sharded over the mesh
  'data' axis and params are replicated. The model's ordinary jitted train
  step then *is* synchronous data-parallel SGD — XLA GSPMD emits one fused
  all-reduce of the gradients over ICI. This collapses the whole
  accumulator/FancyBlockingQueue machinery into compiler output.
- **averaging**: a ``shard_map`` over the 'data' axis runs
  ``averaging_frequency`` *independent* local steps per device
  (``lax.scan``), then ``pmean``s params and updater state — bit-for-bit the
  reference's semantics (each worker drifts, then syncs), but as one compiled
  program instead of N threads + a host barrier.
"""

from __future__ import annotations

from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from deeplearning4j_tpu.observe import trace as _trace
from deeplearning4j_tpu.parallel.mesh import DATA_AXIS, make_mesh, shard_map
from deeplearning4j_tpu.parallel.sharding import batch_sharding, shard_model


from deeplearning4j_tpu.datasets.dataset import batch_nbytes as _batch_nbytes


def make_pure_step(net, train: bool = True):
    """Extract the model's train step as a pure function
    ``(params, states, upd, it, ep, x, y, mask, lmask, rng) ->
    (params, states, upd, loss)`` suitable for scan/shard_map composition."""

    def step(params, states, upd, it, ep, x, y, mask, lmask, rng):
        def lf(p):
            return net._loss_fn(p, states, x, y, rng, mask, lmask, train=train)

        from deeplearning4j_tpu.nn.tick import schedule_tick
        with schedule_tick(it, ep):  # dropout pSchedule sees the tick here too
            (loss, (new_states, _)), grads = jax.value_and_grad(lf, has_aux=True)(params)
        new_params, new_upd = net._apply_updates(params, grads, upd, it, ep)
        return new_params, new_states, new_upd, loss

    return step


class ParallelWrapper:
    """Data-parallel trainer over a device mesh (ParallelWrapper parity).

    Usage::

        net = MultiLayerNetwork(conf); net.init()
        pw = ParallelWrapper(net, mode="shared_gradients")
        pw.fit(iterator, epochs=2)
    """

    def __init__(self, model, mesh: Optional[Mesh] = None, *,
                 mode: str = "shared_gradients",
                 averaging_frequency: int = 5,
                 tp_axis: Optional[str] = None,
                 data_axis: str = DATA_AXIS,
                 metrics=None, metrics_name: str = "default"):
        if mode not in ("shared_gradients", "averaging"):
            raise ValueError(f"unknown mode {mode!r}")
        if mode == "averaging" and tp_axis is not None:
            raise ValueError("averaging mode runs workers on replicated params; "
                             "tensor parallelism requires mode='shared_gradients'")
        self.model = model
        self.mesh = mesh if mesh is not None else make_mesh()
        self.mode = mode
        self.averaging_frequency = max(1, int(averaging_frequency))
        self.data_axis = data_axis
        self.tp_axis = tp_axis
        self._avg_step = None
        if model.params is None:
            model.init()
        shard_model(model, self.mesh, tp_axis=tp_axis)
        self.n_workers = self.mesh.shape[data_axis]
        # optional duck-typed registry (observe.metrics): training-side
        # host→device transfer accounting next to the listener's series
        self._metrics_name = metrics_name
        self._m_transfer = None
        if metrics is not None:
            self._m_transfer = metrics.counter(
                "training_transfer_bytes_total",
                "Host to device bytes shipped with training batches",
                ("model",))

    # ------------------------------------------------------------- evaluate
    def evaluate(self, iterator, top_n: int = 1):
        """Data-parallel evaluation over the mesh
        (``SparkDl4jMultiLayer.evaluate`` role): each batch's features are
        sharded over the 'data' axis (params replicated), so the forward
        pass all-gathers nothing and each device scores its shard; metrics
        accumulate in one host-side Evaluation (the eval classes' ``merge``
        covers multi-process topologies). Ragged tail batches run
        unsharded, same policy as training."""
        import numpy as _np

        from deeplearning4j_tpu.eval.evaluation import Evaluation

        e = Evaluation(top_n=top_n)
        if hasattr(iterator, "reset"):
            iterator.reset()
        put = lambda a: jax.device_put(
            jnp.asarray(a),
            batch_sharding(self.mesh, _np.asarray(a).ndim, self.data_axis))
        for ds in iterator:
            x = _np.asarray(ds.features)
            shardable = x.shape[0] % self.n_workers == 0
            feats = put(x) if shardable else x
            fm = ds.features_mask
            if fm is not None:
                fm = put(fm) if shardable else _np.asarray(fm)
            if hasattr(self.model, "_to_mds"):  # ComputationGraph face
                out = self.model.output(
                    feats, masks=None if fm is None else [fm])
            else:
                out = self.model.output(feats, mask=fm)
            if isinstance(out, list):
                out = out[0]
            e.eval(_np.asarray(ds.labels), _np.asarray(out),
                   mask=None if ds.labels_mask is None
                   else _np.asarray(ds.labels_mask),
                   record_meta_data=getattr(ds, "example_meta_data", None))
        return e

    # ------------------------------------------------------------------ fit
    def fit(self, data, labels=None, *, epochs: int = 1,
            prefetch_depth: Optional[int] = None) -> "ParallelWrapper":
        """``prefetch_depth`` (default 2, 0 disables) wraps iterator sources
        in AsyncDataSetIterator so a producer thread hides the host-side
        batch preparation — the ParallelWrapperMain ``--prefetchSize``
        semantics. No device-put stage here: batches are sharded over the
        mesh per step, so placement happens with the sharding applied."""
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.datasets.iterators import wrap_for_prefetch

        if labels is not None:
            iterator = [DataSet(data, labels)]
        elif isinstance(data, DataSet):
            iterator = [data]
        else:
            iterator = data
        iterator = wrap_for_prefetch(iterator, prefetch_depth,
                                     device_put=None)

        with _trace.span("parallel_fit", category="train",
                         attrs={"mode": self.mode, "workers": self.n_workers,
                                "epochs": epochs}):
            for _ in range(epochs):
                for listener in self.model.listeners:
                    if hasattr(listener, "on_epoch_start"):
                        listener.on_epoch_start(self.model)
                if hasattr(iterator, "reset"):
                    iterator.reset()
                if self.mode == "shared_gradients":
                    for ds in iterator:
                        self._fit_step_traced(ds)
                else:
                    self._fit_averaging(iterator)
                self.model.epoch += 1
                for listener in self.model.listeners:
                    if hasattr(listener, "on_epoch_end"):
                        listener.on_epoch_end(self.model)
        return self

    def _fit_step_traced(self, ds) -> None:
        """One step, wrapped in a ``train_step`` span when tracing is on.
        The traced path syncs on the loss so the span covers the DEVICE
        time of the step (and any compile nests under it — step 0's
        compile shows up loudly); untraced runs keep async dispatch."""
        tracer = _trace.get_active_tracer()
        if tracer is None:
            self._fit_batch_sync(ds)
            return
        net = self.model
        with tracer.span("train_step", category="train",
                         attrs={"mode": self.mode}) as sp:
            self._fit_batch_sync(ds)
            try:
                sp.set_attribute("loss", float(net.score_))  # device sync
            except Exception:  # noqa: BLE001 - score may be deferred
                pass
            sp.set_attribute("iteration", int(net.iteration))
            sp.set_attribute("batch", int(getattr(net, "last_batch_size", 0)
                                          or 0))

    # ------------------------------------------- shared-gradients (per step)
    def _fit_batch_sync(self, ds) -> None:
        """One globally-synchronous step: batch sharded over 'data', params
        replicated → XLA all-reduces gradients over ICI inside the step.

        A final ragged batch (size not divisible by the data-axis size) runs
        unsharded — same math, no DP speedup for that one step (the reference
        ParallelWrapper likewise handles arbitrary tail batches)."""
        net = self.model
        if self._m_transfer is not None:
            self._m_transfer.inc(_batch_nbytes(ds), model=self._metrics_name)
        n = int(np.asarray(ds.features).shape[0])
        if n % self.n_workers:
            net._fit_batch(ds)
            return
        put = lambda a: jax.device_put(
            jnp.asarray(a),
            batch_sharding(self.mesh, np.asarray(a).ndim, self.data_axis))
        from deeplearning4j_tpu.datasets.dataset import DataSet
        sharded = DataSet(
            put(ds.features), put(ds.labels),
            None if ds.features_mask is None else put(ds.features_mask),
            None if ds.labels_mask is None else put(ds.labels_mask))
        net._fit_batch(sharded)

    # ----------------------------------------------------- averaging mode
    def _build_avg_step(self, k: int, x_sds, y_sds, has_fm, has_lm, fm_nd, lm_nd):
        net = self.model
        step = make_pure_step(net)
        daxis = self.data_axis

        def worker(params, states, upd, it0, ep, xs, ys, fms, lms, rng):
            # params/states/upd arrive replicated; xs/ys are this worker's
            # [k, local_batch, ...] shard. Each worker gets a distinct rng.
            rng = jax.random.fold_in(rng, jax.lax.axis_index(daxis))

            def body(carry, inp):
                p, s, u, it = carry
                xi, yi, fmi, lmi, ri = inp
                p, s, u, loss = step(p, s, u, it, ep, xi, yi, fmi, lmi, ri)
                return (p, s, u, it + 1.0), loss

            rngs = jax.random.split(rng, k)
            (params, states, upd, _), losses = jax.lax.scan(
                body, (params, states, upd, it0), (xs, ys, fms, lms, rngs))
            # ParameterAveragingTrainingMaster parity: average params AND
            # updater state (averageUpdatersState, ParallelWrapper.java:338);
            # BN running stats averaged likewise.
            pm = lambda t: jax.tree_util.tree_map(
                lambda a: jax.lax.pmean(a, daxis), t)
            return pm(params), pm(states), pm(upd), jax.lax.pmean(
                jnp.mean(losses), daxis)

        rep = P()
        spec = lambda nd: P(None, daxis, *([None] * (nd - 2)))
        mapped = shard_map(
            worker, mesh=self.mesh,
            in_specs=(rep, rep, rep, rep, rep, spec(x_sds), spec(y_sds),
                      spec(fm_nd) if has_fm else rep,
                      spec(lm_nd) if has_lm else rep, rep),
            out_specs=(rep, rep, rep, rep))
        return jax.jit(mapped, donate_argnums=(0, 1, 2))

    def _fit_averaging(self, iterator) -> None:
        """Accumulate averaging_frequency batches, then run K local steps per
        worker + param averaging as one compiled program. Batches whose size
        doesn't divide the worker count run unsharded via the model's own
        step (same tail-batch policy as shared_gradients)."""
        net = self.model
        k = self.averaging_frequency
        dtype = net.conf.global_conf.jnp_dtype()
        pending: List[Any] = []

        def stack_masks(masks, arrays):
            """None-mixed masks → all-ones of [batch, T] (DataSet.merge policy)."""
            if all(m is None for m in masks):
                return None
            out = []
            for m, a in zip(masks, arrays):
                if m is None:
                    a = np.asarray(a)
                    m = np.ones(a.shape[:2] if a.ndim >= 3 else a.shape[:1],
                                np.float32)
                out.append(jnp.asarray(np.asarray(m)))
            return jnp.stack(out)

        def flush():
            if not pending:
                return
            tracer = _trace.get_active_tracer()
            if tracer is None:
                _flush_inner()
                return
            with tracer.span("train_step", category="train",
                             attrs={"mode": "averaging",
                                    "local_steps": len(pending)}) as sp:
                _flush_inner()
                try:
                    sp.set_attribute("loss", float(net.score_))  # sync
                except Exception:  # noqa: BLE001
                    pass
                sp.set_attribute("iteration", int(net.iteration))

        def _flush_inner():
            kk = len(pending)
            if self._m_transfer is not None:
                self._m_transfer.inc(sum(_batch_nbytes(d) for d in pending),
                                     model=self._metrics_name)
            xs = jnp.stack([jnp.asarray(d.features, dtype) for d in pending])
            ys = jnp.stack([jnp.asarray(d.labels, dtype) for d in pending])
            fms = stack_masks([d.features_mask for d in pending],
                              [d.features for d in pending])
            lms = stack_masks([d.labels_mask for d in pending],
                              [d.labels for d in pending])
            from deeplearning4j_tpu.nn import helpers as _helpers
            key = ("avg", kk, xs.shape, ys.shape,
                   None if fms is None else fms.shape,
                   None if lms is None else lms.shape,
                   _helpers.version())  # updater-helper changes must retrace
            if self._avg_step is None or self._avg_step[0] != key:
                self._avg_step = (key, self._build_avg_step(
                    kk, xs.ndim, ys.ndim, fms is not None, lms is not None,
                    0 if fms is None else fms.ndim,
                    0 if lms is None else lms.ndim))
            fn = self._avg_step[1]
            it = jnp.asarray(net.iteration, jnp.float32)
            ep = jnp.asarray(net.epoch, jnp.float32)
            rng = net._next_rng()
            net.params, net.states, net.updater_states, loss = fn(
                net.params, net.states, net.updater_states, it, ep,
                xs, ys, fms, lms, rng)
            net.score_ = loss
            net.iteration += kk
            for listener in net.listeners:
                if hasattr(listener, "iteration_done"):
                    listener.iteration_done(net, net.iteration, net.epoch)
            pending.clear()

        for ds in iterator:
            if int(np.asarray(ds.features).shape[0]) % self.n_workers:
                flush()
                # ragged tail still crosses the host-device boundary: count
                # it (same accounting as the shared_gradients path)
                if self._m_transfer is not None:
                    self._m_transfer.inc(_batch_nbytes(ds),
                                         model=self._metrics_name)
                net._fit_batch(ds)  # ragged tail batch: unsharded
                continue
            if pending and np.asarray(ds.features).shape != np.asarray(
                    pending[-1].features).shape:
                flush()  # shape change (e.g. smaller tail): can't stack
            pending.append(ds)
            if len(pending) == k:
                flush()
        flush()
