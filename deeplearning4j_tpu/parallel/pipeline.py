"""Pipeline parallelism: stage-sharded training over a 'pipe' mesh axis.

The reference has NO pipeline parallelism (SURVEY.md §2.b: "optional: stage
sharding via shard_map + collective permute") — this is a TPU-first addition
the brief treats as first-class. Design: GPipe-style microbatching expressed
as one compiled program.

- Every stage runs the SAME computation shape (uniform inter-stage width), so
  the whole pipeline is a single ``shard_map`` over the 'pipe' axis with
  stage-stacked parameters ``[S, ...]`` sharded on axis 0 — stage identity is
  ``lax.axis_index``.
- The schedule is a ``lax.scan`` over ``n_micro + S - 1`` ticks; activations
  hop stages with ``lax.ppermute`` each tick (fill-and-drain bubble included).
- Backward needs no hand-written schedule: ``jax.grad`` through the scan and
  the ppermute transposes into the reverse pipeline automatically — the
  compiler emits the backward collectives.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import PIPELINE_AXIS, shard_map

PIPE_AXIS = PIPELINE_AXIS  # canonical axis name lives in parallel/mesh.py


def pipeline_forward(stage_fn: Callable, stacked_params, micro_x,
                     *, axis_name: str = PIPE_AXIS):
    """Run microbatches through the stage pipeline (call INSIDE shard_map).

    stage_fn(params_stage, x) -> y with x/y of identical shape.
    stacked_params: this stage's slice (leading dim 1 stripped by the caller).
    micro_x: [n_micro, B_micro, ...] — every stage receives the full
    microbatch stack; only stage 0 actually consumes it.
    Returns [n_micro, B_micro, ...] outputs as produced by the LAST stage
    (zeros elsewhere), so the caller psums/selects at the loss.
    """
    s = jax.lax.axis_index(axis_name)
    n_stages = jax.lax.psum(1, axis_name)
    n_micro = micro_x.shape[0]
    ticks = n_micro + n_stages - 1
    buf_shape = micro_x.shape[1:]

    def tick(carry, t):
        state, outputs = carry
        # stage 0 ingests microbatch t (when in range); others take the
        # activation handed over from the previous stage
        idx = jnp.clip(t, 0, n_micro - 1)
        # SELECT, not arithmetic blend: a transient inf/NaN in the ring
        # wraparound must never reach stage 0 (0 * inf = NaN)
        x_in = jnp.where(s == 0, micro_x[idx], state)
        y = stage_fn(stacked_params, x_in)
        # last stage writes its finished microbatch (tick t finishes
        # microbatch t - (S-1) at the last stage)
        out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
        is_last = s == n_stages - 1
        valid = jnp.logical_and(is_last, t >= n_stages - 1)
        outputs = jax.lax.cond(
            valid,
            lambda o: o.at[out_idx].set(y),
            lambda o: o,
            outputs)
        # hand activations to the next stage (ring permute; the wraparound
        # into stage 0 is ignored because stage 0 always feeds from micro_x)
        nxt = jax.lax.ppermute(
            y, axis_name,
            [(i, (i + 1) % n_stages) for i in range(n_stages)])
        return (nxt, outputs), None

    state0 = jnp.zeros(buf_shape, micro_x.dtype)
    outputs0 = jnp.zeros_like(micro_x)
    (_, outputs), _ = jax.lax.scan(tick, (state0, outputs0),
                                   jnp.arange(ticks))
    # replicate the last stage's outputs to every stage (zero elsewhere)
    return jax.lax.psum(outputs, axis_name)


class PipelineParallel:
    """Stage-sharded trainer for a uniform stack of stage functions.

    ``stage_init(rng) -> params`` and ``stage_fn(params, x) -> y`` define one
    stage (x, y same shape); ``loss_fn(y, labels) -> scalar`` scores the final
    stage's output. ``fit_step`` runs forward + backward + SGD across all
    stages in ONE jitted shard_map program.
    """

    def __init__(self, mesh: Mesh, stage_init: Callable, stage_fn: Callable,
                 loss_fn: Callable, n_stages: Optional[int] = None,
                 learning_rate: float = 0.1, axis_name: str = PIPE_AXIS,
                 seed: int = 0):
        self.mesh = mesh
        self.axis_name = axis_name
        self.n_stages = n_stages or int(mesh.shape[axis_name])
        if self.n_stages != int(mesh.shape[axis_name]):
            # each device holds exactly one stage (worker reads a[0]); a
            # mismatch would silently compute with a subset of the stages
            raise ValueError(
                f"n_stages ({self.n_stages}) must equal the {axis_name!r} "
                f"mesh axis size ({int(mesh.shape[axis_name])})")
        self.stage_fn = stage_fn
        self.loss_fn = loss_fn
        self.learning_rate = learning_rate
        keys = jax.random.split(jax.random.PRNGKey(seed), self.n_stages)
        per_stage = [stage_init(k) for k in keys]
        # stack stage params on a leading axis sharded over 'pipe'
        self.params = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *per_stage)
        self._step = None
        self._fwd = None

    def _build(self):
        axis = self.axis_name
        stage_fn = self.stage_fn
        loss_fn = self.loss_fn
        lr = self.learning_rate

        def worker(stacked, micro_x, micro_y):
            local = jax.tree_util.tree_map(lambda a: a[0], stacked)
            n_stages = jax.lax.psum(1, axis)

            def loss_of(p):
                outs = pipeline_forward(stage_fn, p, micro_x, axis_name=axis)
                per_micro = jax.vmap(loss_fn)(outs, micro_y)
                # every stage evaluates the SAME replicated loss, and the
                # psum transpose sums the S identical cotangent streams —
                # divide here so the differentiated quantity is the true loss
                return jnp.mean(per_micro) / n_stages

            loss_scaled, grads = jax.value_and_grad(loss_of)(local)
            loss = loss_scaled * n_stages
            # each stage's grads live on that stage; no all-reduce needed
            new_local = jax.tree_util.tree_map(
                lambda p, g: p - lr * g, local, grads)
            new_stacked = jax.tree_util.tree_map(
                lambda a: a[None], new_local)
            return new_stacked, jax.lax.pmax(loss, axis)

        rep = P()
        mapped = shard_map(
            worker, mesh=self.mesh,
            in_specs=(P(self.axis_name), rep, rep),
            out_specs=(P(self.axis_name), rep))
        return jax.jit(mapped, donate_argnums=(0,))

    def fit_step(self, micro_x, micro_y) -> float:
        """One pipelined train step over [n_micro, B_micro, ...] batches."""
        if self._step is None:
            self._step = self._build()
        self.params, loss = self._step(self.params,
                                       jnp.asarray(micro_x),
                                       jnp.asarray(micro_y))
        return loss

    def forward(self, micro_x):
        """Pipelined inference: [n_micro, B, ...] -> outputs of the stack."""
        if self._fwd is None:
            axis = self.axis_name
            stage_fn = self.stage_fn

            def worker(stacked, micro_x):
                local = jax.tree_util.tree_map(lambda a: a[0], stacked)
                return pipeline_forward(stage_fn, local, micro_x,
                                        axis_name=axis)

            self._fwd = jax.jit(shard_map(
                worker, mesh=self.mesh,
                in_specs=(P(self.axis_name), P()), out_specs=P()))
        return self._fwd(self.params, jnp.asarray(micro_x))
