"""Elastic worker entry point for CLI jobs.

``python -m deeplearning4j_tpu.parallel.elastic_worker`` is what the
``train --elastic N`` supervisor launches: it loads a serialized model
and an ``.npz`` dataset, joins the generation's ``jax.distributed``
world from the supervisor's environment (``parallel/elastic.py``), and
runs the generic elastic runloop — restore, heartbeats, fenced rotation
checkpoints, resume. Rank 0 of the generation that finishes training
writes the final model zip to ``--out``.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser("elastic-worker")
    ap.add_argument("--modelPath", required=True,
                    help="model zip written by ModelSerializer")
    ap.add_argument("--dataPath", required=True,
                    help=".npz with 'features' and 'labels' arrays")
    ap.add_argument("--out", required=True,
                    help="final model zip (written by rank 0)")
    ap.add_argument("--batchSize", type=int, default=32)
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--threshold", type=float, default=1e-3)
    ap.add_argument("--checkpoint-every", type=int, default=1,
                    dest="checkpoint_every")
    ap.add_argument("--save-mode", choices=("sync", "async"),
                    default="sync", dest="save_mode",
                    help="checkpoint commit path: sync blocks training "
                         "for the whole save; async overlaps the save "
                         "with the next steps (bounded in-flight, "
                         "stamped only after every rank's shard lands)")
    args = ap.parse_args(argv)

    import numpy as np

    from deeplearning4j_tpu.datasets.dataset import (DataSet,
                                                     ListDataSetIterator)
    from deeplearning4j_tpu.parallel.elastic import run_elastic_worker
    from deeplearning4j_tpu.util import model_serializer

    z = np.load(args.dataPath)
    ds = DataSet(z["features"], z["labels"])

    def build_model():
        return model_serializer.restore_model(args.modelPath)

    def build_iterator():
        return ListDataSetIterator(ds, args.batchSize)

    def on_done(net, ctx):
        if ctx.process_id == 0:
            directory = os.path.dirname(os.path.abspath(args.out))
            os.makedirs(directory, exist_ok=True)
            model_serializer.write_model(net, args.out)
            print(f"[slot {ctx.slot}] wrote {args.out}", flush=True)

    run_elastic_worker(
        build_model, build_iterator, epochs=args.epochs,
        master_kwargs={"batch_size_per_worker": args.batchSize,
                       "threshold": args.threshold},
        checkpoint_every=args.checkpoint_every,
        save_mode=args.save_mode,
        on_done=on_done)
    return 0


if __name__ == "__main__":
    sys.exit(main())
