"""Clock sources for multi-host phase timing.

Reference: ``dl4j-spark/.../time/NTPTimeSource.java`` (and the ``TimeSource``
SPI next to it) — Spark phase timings are stamped with an NTP-corrected
clock so events from different hosts line up on one timeline, with a
system-clock fallback when NTP is unreachable.

TPU-native framing is unchanged: multi-host jobs still need comparable
timestamps for the exported timeline (``ui/modules.py`` timeline export,
``parallel/master.py`` TrainingStats). The implementation speaks plain
SNTP (RFC 4330 client mode) over UDP so it needs no dependencies, caches
the measured offset for ``update_frequency`` seconds, and degrades to the
system clock on any failure — the reference's fallback behavior.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Optional

# seconds between the NTP epoch (1900) and the Unix epoch (1970)
_NTP_DELTA = 2208988800


class TimeSource:
    """SPI: a clock returning milliseconds since the Unix epoch."""

    def current_time_millis(self) -> int:  # pragma: no cover - interface
        raise NotImplementedError

    def ensure_synced(self) -> None:
        """Block until the clock is usable for cross-host comparison (no-op
        for clocks with nothing to measure). Callers that stamp timelines
        (e.g. the TrainingMaster front end) invoke this once at startup so
        the offset never jumps mid-run."""


class SystemClockTimeSource(TimeSource):
    """The local clock (``SystemClockTimeSource`` in the reference)."""

    def current_time_millis(self) -> int:
        return int(time.time() * 1000)


class NTPTimeSource(TimeSource):
    """System clock corrected by an SNTP-measured offset.

    One UDP round trip per ``update_frequency`` window: offset =
    ((t1 - t0) + (t2 - t3)) / 2 from the classic four-timestamp exchange,
    where t0/t3 are local send/receive and t1/t2 the server receive/send.
    On any socket failure the last good offset is kept (0 before the first
    success — i.e. plain system time, the reference's fallback).

    ``current_time_millis`` never blocks: when a window expires it kicks a
    background daemon thread to refresh the offset and returns immediately
    with the last good one. Call ``sync()`` explicitly (e.g. at master
    startup) to block for the first measurement.
    """

    def __init__(self, server: str = "pool.ntp.org", port: int = 123,
                 timeout: float = 2.0, update_frequency: float = 1800.0,
                 eager: bool = True):
        self.server = server
        self.port = port
        self.timeout = timeout
        self.update_frequency = update_frequency
        self._offset_ms = 0.0
        self._last_sync: Optional[float] = None
        self.last_error: Optional[str] = None
        self._sync_lock = threading.Lock()
        self._state_lock = threading.Lock()
        self._last_success: Optional[float] = None
        self._sync_thread: Optional[threading.Thread] = None
        if eager:
            # start measuring at construction so the first stamps are already
            # corrected; eager=False keeps the socket quiet until first use
            self._sync_in_background()

    # ------------------------------------------------------------ protocol
    def _query_offset_ms(self) -> float:
        """One SNTP exchange; returns offset in ms (raises on failure)."""
        packet = bytearray(48)
        packet[0] = 0x1B  # LI=0, VN=3, Mode=3 (client)
        t0 = time.time()
        struct.pack_into(">I", packet, 40, int(t0 + _NTP_DELTA))
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.settimeout(self.timeout)
            s.sendto(bytes(packet), (self.server, self.port))
            data, _ = s.recvfrom(48)
        t3 = time.time()
        if len(data) < 48:
            raise ValueError(f"short NTP response ({len(data)} bytes)")

        def ts(off):
            sec, frac = struct.unpack(">II", data[off:off + 8])
            return sec - _NTP_DELTA + frac / 2 ** 32

        t1 = ts(32)  # server receive
        t2 = ts(40)  # server transmit
        return (((t1 - t0) + (t2 - t3)) / 2.0) * 1000.0

    def sync(self) -> bool:
        """Force a sync now; True on success (offset updated).

        Safe to call concurrently with the background refresh: state writes
        are serialized, and a failing exchange never clobbers the result of
        a success that completed after it started.
        """
        started = time.time()
        try:
            offset = self._query_offset_ms()
        except (OSError, ValueError) as e:  # timeout/unreachable/short resp.
            with self._state_lock:
                if self._last_success is None or self._last_success < started:
                    self.last_error = (f"{type(e).__name__}: {e}"
                                       if isinstance(e, OSError) else str(e))
                    self._last_sync = time.time()  # back off until next window
            return False
        with self._state_lock:
            self._offset_ms = offset
            self._last_sync = self._last_success = time.time()
            self.last_error = None
        return True

    @property
    def offset_millis(self) -> float:
        return self._offset_ms

    def ensure_synced(self) -> None:
        """One blocking exchange if no sync attempt has completed yet
        (the eager background attempt may still be in flight)."""
        if self._last_sync is None:
            self.sync()

    def _sync_in_background(self) -> None:
        """Start one refresh thread if none is running (non-blocking)."""
        with self._sync_lock:
            if self._sync_thread is not None and self._sync_thread.is_alive():
                return
            t = threading.Thread(target=self.sync, daemon=True,
                                 name="ntp-time-source-sync")
            self._sync_thread = t
            t.start()

    def current_time_millis(self) -> int:
        now = time.time()
        if (self._last_sync is None
                or now - self._last_sync > self.update_frequency):
            self._sync_in_background()
        return int(now * 1000 + self._offset_ms)


class ManualTimeSource(TimeSource):
    """A clock that only moves when told to — the injectable time source
    the alert engine's state machine and the watchdog tests run on, so
    every window/transition is exercised deterministically (no sleeps)."""

    def __init__(self, start_ms: int = 0):
        self._ms = float(start_ms)
        self._lock = threading.Lock()

    def current_time_millis(self) -> int:
        with self._lock:
            return int(self._ms)

    def advance(self, seconds: float = 0.0, millis: float = 0.0) -> int:
        """Move the clock forward; returns the new time in millis."""
        with self._lock:
            self._ms += seconds * 1000.0 + millis
            return int(self._ms)

    def set_millis(self, ms: float) -> None:
        with self._lock:
            self._ms = float(ms)


_DEFAULT: TimeSource = SystemClockTimeSource()


def get_time_source() -> TimeSource:
    """Process-wide clock used for phase stamps (``TimeSourceProvider``)."""
    return _DEFAULT


def set_time_source(ts: TimeSource) -> None:
    global _DEFAULT
    _DEFAULT = ts
