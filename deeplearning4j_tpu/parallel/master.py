"""TrainingMaster layer: cluster-style training drivers over the mesh.

Capability parity with the reference's Spark scale-out layer
(`dl4j-spark/.../api/TrainingMaster.java:28`,
`ParameterAveragingTrainingMaster.java` — split sizing ``:287-298``, training
``:308``, tree aggregation / ``aggregationDepth``;
`dl4j-spark-parameterserver/.../SharedTrainingMaster.java:493` — threshold-
compressed gradient sharing over Aeron; export-based iteration
`impl/paramavg/util/ExportSupport.java`; per-phase timing
`api/stats/CommonSparkTrainingStats.java`) — redesigned for the TPU stack:

- Spark executors → mesh axis shards. The "cluster" is a ``jax.sharding.Mesh``;
  multi-host runs enter through ``jax.distributed`` (`init_distributed`) with
  per-host input pipelines, exactly the single-controller JAX model.
- broadcast + treeAggregate → XLA collectives over ICI/DCN. ``aggregationDepth``
  is accepted but XLA's all-reduce already uses optimal reduction topology.
- Aeron threshold messages → in-step quantization: each worker applies the
  Strom-style threshold sign-quantization to its update, keeps the residual,
  and a ``psum`` shares the quantized updates (`EncodingHandler.java`
  semantics; the wire-format sparse codec lives in
  ``deeplearning4j_tpu.parallel.compression``).
"""

from __future__ import annotations

import os
import time
from typing import Any, Iterable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import (
    DATA_AXIS,
    is_multiprocess,
    make_global,
    make_mesh,
    shard_map,
)
from deeplearning4j_tpu.parallel.trainer import ParallelWrapper


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     coordinator_bind_address: Optional[str] = None) -> None:
    """Multi-host entry: join the JAX coordination service (replaces the
    reference's Aeron introduction/shard protocol,
    `SharedTrainingWrapper.java:214-244`). No-op when single-process.

    ``coordinator_bind_address`` lets process 0 listen on a different
    interface than the one peers dial (``coordinator_address`` is the
    ADVERTISED address) — NAT/container pods where 0.0.0.0 must be bound
    but a routable name advertised. ``None`` keeps jax's default (bind
    the advertised address)."""
    if num_processes is None or num_processes <= 1:
        return
    _enable_cpu_collectives()
    kwargs = {}
    if coordinator_bind_address is not None:
        kwargs["coordinator_bind_address"] = coordinator_bind_address
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id, **kwargs)


def _enable_cpu_collectives() -> None:
    """The CPU backend has no native cross-process collectives (XLA raises
    "Multiprocess computations aren't implemented on the CPU backend") —
    route them through Gloo TCP. Must run before the backend initializes;
    a value the operator set explicitly (flag or env) is left alone, and
    on TPU the CPU-client setting is inert."""
    try:
        from jax._src import xla_bridge  # registers the flag
        current = xla_bridge.CPU_COLLECTIVES_IMPLEMENTATION.value
        if current in (None, "none") \
                and not xla_bridge.backends_are_initialized():
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # noqa: BLE001 - older/newer jax: best effort only
        pass


class TrainingStats:
    """Per-phase wall-clock timings (`CommonSparkTrainingStats.java`), with
    each event also stamped by the process-wide TimeSource — plug in
    :class:`~deeplearning4j_tpu.parallel.time_source.NTPTimeSource` and
    events from different hosts line up on one timeline (the reference's
    NTP-corrected `BaseEventStats` timestamps)."""

    def __init__(self, time_source=None):
        self.phase_times: dict = {}
        self.events: list = []  # (phase, start_millis, duration_millis)
        self._ts = time_source  # None → resolve per add(), so a
        # set_time_source() AFTER the master was built still takes effect

    def add(self, phase: str, seconds: float) -> None:
        from deeplearning4j_tpu.parallel.time_source import get_time_source
        self.phase_times.setdefault(phase, []).append(seconds)
        ts = self._ts if self._ts is not None else get_time_source()
        end_ms = ts.current_time_millis()
        self.events.append((phase, int(end_ms - seconds * 1000),
                            int(seconds * 1000)))

    def total(self, phase: str) -> float:
        return sum(self.phase_times.get(phase, []))

    def as_dict(self) -> dict:
        return {k: {"count": len(v), "total_s": sum(v)}
                for k, v in self.phase_times.items()}


class TrainingMaster:
    """SPI: how distributed fitting is orchestrated
    (`api/TrainingMaster.java:28`)."""

    def execute_training(self, network, data_iterator: Iterable) -> None:
        raise NotImplementedError

    def get_training_stats(self) -> TrainingStats:
        return getattr(self, "stats", TrainingStats())


class ParameterAveragingTrainingMaster(TrainingMaster):
    """Synchronous periodic parameter averaging
    (`ParameterAveragingTrainingMaster.java`).

    Splits the incoming stream into chunks of
    ``num_workers * batch_size_per_worker * averaging_frequency`` examples
    (split sizing ``:287-298``); each split runs ``averaging_frequency``
    local steps per worker followed by parameter + updater-state averaging —
    executed as ONE compiled shard_map program per split
    (:class:`ParallelWrapper` averaging mode) instead of Spark map + tree
    aggregation.
    """

    def __init__(self, batch_size_per_worker: int = 16,
                 averaging_frequency: int = 5,
                 num_workers: Optional[int] = None,
                 aggregation_depth: int = 2,
                 repartition: str = "always",
                 export_directory: Optional[str] = None,
                 mesh: Optional[Mesh] = None,
                 data_axis: str = DATA_AXIS):
        self.batch_size_per_worker = batch_size_per_worker
        self.averaging_frequency = max(1, averaging_frequency)
        self.mesh = mesh if mesh is not None else make_mesh()
        self.data_axis = data_axis
        self.num_workers = num_workers or int(self.mesh.shape[data_axis])
        # accepted for parity; XLA's all-reduce already picks the reduction
        # topology, so depth is advisory only
        self.aggregation_depth = aggregation_depth
        self.repartition = repartition
        self.export_directory = export_directory
        self.stats = TrainingStats()
        self._pw: Optional[ParallelWrapper] = None

    class Builder:
        def __init__(self, batch_size_per_worker: int = 16):
            self._kw = {"batch_size_per_worker": batch_size_per_worker}

        def averaging_frequency(self, f):
            self._kw["averaging_frequency"] = f
            return self

        def aggregation_depth(self, d):
            self._kw["aggregation_depth"] = d
            return self

        def workers(self, n):
            self._kw["num_workers"] = n
            return self

        def export_directory(self, d):
            self._kw["export_directory"] = d
            return self

        def build(self):
            return ParameterAveragingTrainingMaster(**self._kw)

    # -- data staging ------------------------------------------------------
    def _repartition(self, data_iterator) -> List:
        """Regroup the stream into worker-divisible batches of
        batch_size_per_worker * num_workers examples (the reference
        repartitions the RDD so every executor sees equal counts)."""
        from deeplearning4j_tpu.datasets.dataset import DataSet

        t0 = time.perf_counter()
        per_round = self.batch_size_per_worker * self.num_workers
        feats, labs, n_buf = [], [], 0
        out: List[DataSet] = []
        for ds in data_iterator:
            if ds.features_mask is not None or ds.labels_mask is not None:
                # masked sequence data is not re-chunked; pass through
                out.append(ds)
                continue
            feats.append(np.asarray(ds.features))
            labs.append(np.asarray(ds.labels))
            n_buf += feats[-1].shape[0]
            while n_buf >= per_round:
                f = np.concatenate(feats) if len(feats) > 1 else feats[0]
                l = np.concatenate(labs) if len(labs) > 1 else labs[0]
                out.append(DataSet(f[:per_round], l[:per_round]))
                feats, labs = [f[per_round:]], [l[per_round:]]
                n_buf = feats[0].shape[0]
        if n_buf:
            out.append(DataSet(np.concatenate(feats) if len(feats) > 1 else feats[0],
                               np.concatenate(labs) if len(labs) > 1 else labs[0]))
        if self.export_directory:
            os.makedirs(self.export_directory, exist_ok=True)
            for i, ds in enumerate(out):
                arrays = {"features": np.asarray(ds.features),
                          "labels": np.asarray(ds.labels)}
                if ds.features_mask is not None:
                    arrays["features_mask"] = np.asarray(ds.features_mask)
                if ds.labels_mask is not None:
                    arrays["labels_mask"] = np.asarray(ds.labels_mask)
                # zero-padded index: lexicographic == numeric replay order
                np.savez(os.path.join(self.export_directory,
                                      f"split{i:06d}.npz"), **arrays)
        self.stats.add("split", time.perf_counter() - t0)
        return out

    @staticmethod
    def load_exported(directory: str) -> List:
        """Replay a staged export directory (`ExportSupport.java` parity) in
        the original split order."""
        from deeplearning4j_tpu.datasets.dataset import DataSet

        names = [f for f in os.listdir(directory) if f.endswith(".npz")]
        # numeric sort handles legacy unpadded names too
        names.sort(key=lambda f: (len(f), f))
        out = []
        for f in names:
            z = np.load(os.path.join(directory, f))
            out.append(DataSet(
                z["features"], z["labels"],
                z["features_mask"] if "features_mask" in z else None,
                z["labels_mask"] if "labels_mask" in z else None))
        return out

    # -- training ----------------------------------------------------------
    def execute_training(self, network, data_iterator: Iterable) -> None:
        batches = self._repartition(data_iterator)
        # cache the wrapper so the compiled shard_map step survives epochs
        pw = self._pw
        if pw is None or pw.model is not network:
            pw = self._pw = ParallelWrapper(
                network, self.mesh, mode="averaging",
                averaging_frequency=self.averaging_frequency,
                data_axis=self.data_axis)
        t0 = time.perf_counter()
        pw.fit(batches)
        network.epoch -= 1  # pw.fit counts an epoch; the master's caller owns epochs
        self.stats.add("fit", time.perf_counter() - t0)


class SharedTrainingMaster(TrainingMaster):
    """Per-step threshold-compressed gradient sharing
    (`SharedTrainingMaster.java` + `EncodedGradientsAccumulator.java:33`).

    Each worker: local gradients → local updater → update + residual →
    Strom threshold sign-quantization (magnitudes below ``threshold`` stay in
    the residual; survivors are quantized to ±threshold) → ``psum`` over the
    mesh → everyone applies the same summed quantized update. The adaptive
    threshold decay/boost of `EncodingHandler.java:69-94` is applied between
    steps from the on-device sparsity measurement.
    """

    def __init__(self, batch_size_per_worker: int = 16,
                 threshold: float = 1e-3, min_threshold: float = 1e-5,
                 threshold_step: float = 1e-5, step_trigger: float = 0.05,
                 step_delay: int = 50, shake_frequency: int = 0,
                 mesh: Optional[Mesh] = None, data_axis: str = DATA_AXIS):
        self.batch_size_per_worker = batch_size_per_worker
        self.threshold = float(threshold)
        self.min_threshold = float(min_threshold)
        self.threshold_step = float(threshold_step)
        self.step_trigger = float(step_trigger)  # target sparsity ratio
        self.step_delay = step_delay
        self.shake_frequency = shake_frequency
        self.mesh = mesh if mesh is not None else make_mesh()
        self.data_axis = data_axis
        self.num_workers = int(self.mesh.shape[data_axis])
        self.stats = TrainingStats()
        self._step_fn = None
        self._net_ref = None
        self._residual = None
        self._steps_done = 0
        self._shake_restore: Optional[float] = None

    class Builder:
        def __init__(self, batch_size_per_worker: int = 16):
            self._kw = {"batch_size_per_worker": batch_size_per_worker}

        def update_threshold(self, t):
            self._kw["threshold"] = t
            return self

        def min_update_threshold(self, t):
            self._kw["min_threshold"] = t
            return self

        def workers_per_node(self, n):
            return self  # mesh decides worker count; accepted for parity

        def build(self):
            return SharedTrainingMaster(**self._kw)

    def _build_step(self, net):
        daxis = self.data_axis

        def worker(params, states, upd, residual, it, ep, x, y, rng, thr):
            # Workers compute local grads/updates on their batch shard; the
            # quantized updates are summed across the mesh (the Aeron
            # broadcast path, now one ICI collective). ``residual`` leaves
            # arrive as this worker's [1, *param_shape] slice of the stacked
            # per-worker residual state.
            rng = jax.random.fold_in(rng, jax.lax.axis_index(daxis))

            def lf(p):
                return net._loss_fn(p, states, x, y, rng, None, None, train=True)

            from deeplearning4j_tpu.nn.tick import schedule_tick
            with schedule_tick(it, ep):  # dropout pSchedule sees the tick
                (loss, (new_states, _)), grads = jax.value_and_grad(
                    lf, has_aux=True)(params)
            # local updater: update magnitudes, not raw grads, are shared
            # (StochasticGradientDescent.java:66-73 stores the UPDATE)
            stepped, new_upd = net._apply_updates(params, grads, upd, it, ep)
            update = jax.tree_util.tree_map(lambda a, b: a - b, params, stepped)
            acc = jax.tree_util.tree_map(lambda r, u: r + u[None], residual, update)
            quant = jax.tree_util.tree_map(
                lambda a: jnp.where(jnp.abs(a) >= thr,
                                    jnp.sign(a) * thr, 0.0).astype(a.dtype), acc)
            new_residual = jax.tree_util.tree_map(lambda a, q: a - q, acc, quant)
            # every node applies the SUM of all workers' quantized updates
            # (EncodedGradientsAccumulator applies each received message)
            shared = jax.tree_util.tree_map(
                lambda q: jax.lax.psum(q, daxis), quant)
            new_params = jax.tree_util.tree_map(
                lambda p, s: p - s[0], params, shared)
            # sparsity: fraction of elements encoded (EncodingHandler feedback)
            counts = jax.tree_util.tree_map(
                lambda q: (jnp.sum(q != 0), q.size), quant,
                is_leaf=lambda a: hasattr(a, "shape"))
            leaves = jax.tree_util.tree_leaves(counts)
            nz = sum(leaves[0::2])
            total = sum(leaves[1::2])
            avg = lambda t: jax.tree_util.tree_map(
                lambda a: jax.lax.pmean(a, daxis), t)
            sparsity = jax.lax.pmean(nz / total, daxis)
            return (new_params, avg(new_states), avg(new_upd), new_residual,
                    jax.lax.pmean(loss, daxis), sparsity)

        rep = P()
        shard0 = P(daxis)

        mapped = shard_map(
            worker, mesh=self.mesh,
            in_specs=(rep, rep, rep, shard0, rep, rep, shard0, shard0,
                      rep, rep),
            out_specs=(rep, rep, rep, shard0, rep, rep))
        return jax.jit(mapped, donate_argnums=(0, 1, 2, 3))

    # -- compression-state checkpointing ---------------------------------
    # A preemption checkpoint that carries only model + updater state
    # resumes ALMOST exactly: the adaptive threshold re-warms and the
    # un-transmitted residuals are lost (they re-accumulate, shifting a
    # few low-order bits of every later update). Exact resume needs this
    # state too — the reference has no analog (its accumulator dies with
    # the worker; membership is fixed — SharedTrainingWrapper.java:131).

    def state_snapshot(self) -> dict:
        """This PROCESS's compression state (threshold machinery + its
        local residual shard) as host numpy arrays — the rank-local
        checkpoint shard, decoupled from the live training state so an
        async save thread can write it while the next step mutates the
        residual (:func:`write_state_snapshot`)."""
        snap = {
            "threshold": np.float64(self.threshold),
            "steps_done": np.int64(self._steps_done),
            "shake_restore": np.float64(
                -1.0 if self._shake_restore is None else self._shake_restore),
        }
        if self._residual is not None:
            leaves = jax.tree_util.tree_leaves(self._residual)
            for i, leaf in enumerate(leaves):
                if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
                    # ALL local shards, in worker order — a process usually
                    # owns several devices, each holding one worker slice
                    # of the worker-stacked residual (axis 0)
                    shards = sorted(leaf.addressable_shards,
                                    key=lambda s: s.index[0].start or 0)
                    snap[f"res{i}"] = np.concatenate(
                        [np.asarray(s.data) for s in shards], axis=0)
                else:
                    snap[f"res{i}"] = np.asarray(leaf).copy()
        return snap

    @staticmethod
    def write_state_snapshot(snapshot: dict, path: str) -> None:
        """Write a :meth:`state_snapshot` npz atomically. The elastic
        commit protocol (elastic.py) treats this file's EXISTENCE as
        "shard landed" — a torn write from a mid-save kill must never be
        stampable as committed."""
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as fh:  # handle, not path: savez would
            np.savez(fh, **snapshot)  # append .npz to the name
        os.replace(tmp, path)

    def save_state(self, path: str) -> None:
        """Write this PROCESS's compression state (threshold machinery +
        its local residual shard) as an npz. In a multi-process run every
        process must save its own file — residual shards differ."""
        self.write_state_snapshot(self.state_snapshot(), path)

    def load_state(self, path: str) -> None:
        """Restore state written by :meth:`save_state`.

        Single-process meshes tolerate a WORKER-COUNT change (the
        elastic-shrink restore path): the saved per-worker residual stack
        is summed and spread evenly over the new worker stack, so the
        un-transmitted gradient mass and the adapted threshold both
        survive an N→N-1 world change. A mismatch in the per-parameter
        shapes themselves (different architecture) still fails loudly.
        Multi-process runs stay strict — residual shards are rank-local
        and a shrunk world cannot see the dead rank's shard; skip
        load_state there and re-accumulate. The residual is re-placed
        lazily on the next ``execute_training`` call."""
        data = np.load(path)
        self.threshold = float(data["threshold"])
        self._steps_done = int(data["steps_done"])
        sr = float(data["shake_restore"])
        self._shake_restore = None if sr < 0 else sr
        res = [data[k] for k in sorted(
            (k for k in data.files if k.startswith("res")),
            key=lambda k: int(k[3:]))]
        self._residual_restore = res or None
        if self._residual is not None and self._residual_restore is not None:
            # master already bound to a network: place the residual NOW —
            # deferring to the next step-fn rebuild would silently keep the
            # current residual while the threshold scalars rolled back
            self._residual = self._place_restored_residual(
                self._residual, mp=is_multiprocess(self.mesh),
                shard_spec=P(self.data_axis))

    _residual_restore = None

    def _place_restored_residual(self, zeros_tree, mp: bool, shard_spec):
        leaves, treedef = jax.tree_util.tree_flatten(zeros_tree)
        saved = self._residual_restore
        self._residual_restore = None
        if len(saved) != len(leaves):
            raise ValueError(
                f"restored residual has {len(saved)} leaves, model needs "
                f"{len(leaves)} — was the checkpoint from this architecture?")
        placed = []
        for z, s in zip(leaves, saved):
            if mp:
                # validate BEFORE constructing the global array — the jax
                # constructor's own mismatch error would bury the remedy
                expect_local = (z.shape[0] // jax.process_count(),) + \
                    tuple(z.shape[1:])
                if tuple(s.shape) != expect_local:
                    raise ValueError(
                        f"restored residual shard {s.shape} does not tile "
                        f"to {z.shape} over {jax.process_count()} processes "
                        "— resuming on a different worker count drops "
                        "residuals: skip load_state and re-accumulate")
                sharding = jax.sharding.NamedSharding(
                    self.mesh, shard_spec)
                arr = jax.make_array_from_process_local_data(
                    sharding, np.asarray(s, z.dtype))
            else:
                if tuple(s.shape) != tuple(z.shape):
                    if tuple(s.shape[1:]) == tuple(z.shape[1:]):
                        # mesh reshape (worker count changed, e.g. an
                        # elastic shrink): conserve the un-transmitted
                        # mass — sum the saved per-worker stack and
                        # spread it evenly over the new one
                        total = np.asarray(s, np.float64).sum(axis=0)
                        s = np.broadcast_to(total / z.shape[0], z.shape)
                    else:
                        raise ValueError(
                            f"restored residual shape {s.shape} != "
                            f"{z.shape} — the checkpoint is from a "
                            "different architecture, not just a different "
                            "worker count: skip load_state and "
                            "re-accumulate")
                arr = jnp.asarray(np.asarray(s, z.dtype))
            placed.append(arr)
        return jax.tree_util.tree_unflatten(treedef, placed)

    def _adapt_threshold(self, sparsity: float) -> None:
        """EncodingHandler.java:69-94: decay threshold toward min when too few
        elements pass (residual starving), raise it when too many pass."""
        self._steps_done += 1
        if self._shake_restore is not None:
            # previous step was a shake: restore the working threshold
            self.threshold = self._shake_restore
            self._shake_restore = None
        if self._steps_done < self.step_delay:
            return
        if sparsity < 1e-4:  # almost nothing transmitted → lower threshold
            self.threshold = max(self.min_threshold,
                                 self.threshold - self.threshold_step)
        elif sparsity > self.step_trigger:  # too dense → raise threshold
            self.threshold = self.threshold + self.threshold_step
        if self.shake_frequency and self._steps_done % self.shake_frequency == 0:
            # periodic "shake": lower for ONE step to flush residuals, then
            # restore (EncodingHandler's temporary shake semantics)
            self._shake_restore = self.threshold
            self.threshold = max(self.min_threshold, self.threshold * 0.5)

    def execute_training(self, network, data_iterator: Iterable) -> None:
        if network.params is None:
            network.init()
        dtype = network.conf.global_conf.jnp_dtype()
        mp = is_multiprocess(self.mesh)
        rep, shard0 = P(), P(self.data_axis)
        if self._step_fn is None or self._net_ref is not network:
            # the compiled worker closes over the network: rebuild on switch
            self._net_ref = network
            self._step_fn = self._build_step(network)
            # stacked per-worker residuals, sharded over the data axis
            self._residual = jax.tree_util.tree_map(
                lambda p: np.zeros((self.num_workers,) + p.shape,
                                   np.asarray(p).dtype),
                network.params)
            if mp:
                # cross-process run (jax.distributed): every host holds the
                # same full values; lift them to GLOBAL arrays over the mesh
                if self._residual_restore is not None:
                    self._residual = self._place_restored_residual(
                        self._residual, mp=True, shard_spec=shard0)
                else:
                    self._residual = make_global(self._residual, self.mesh,
                                                 shard0)
                network.params = make_global(network.params, self.mesh, rep)
                network.states = make_global(network.states, self.mesh, rep)
                network.updater_states = make_global(
                    network.updater_states, self.mesh, rep)
            else:
                if self._residual_restore is not None:
                    self._residual = self._place_restored_residual(
                        self._residual, mp=False, shard_spec=shard0)
                else:
                    self._residual = jax.tree_util.tree_map(jnp.asarray,
                                                            self._residual)
                # a restored model's params arrive COMMITTED to one device
                # (orbax device_puts on load); the sharded step needs them
                # replicated over the whole mesh — uncommitted fresh-init
                # arrays pass through device_put for free
                rep_sh = jax.sharding.NamedSharding(self.mesh, rep)
                network.params = jax.device_put(network.params, rep_sh)
                network.states = jax.device_put(network.states, rep_sh)
                if network.updater_states is not None:
                    network.updater_states = jax.device_put(
                        network.updater_states, rep_sh)
        t0 = time.perf_counter()
        for ds in data_iterator:
            x = np.asarray(ds.features)
            y = np.asarray(ds.labels)
            if (x.shape[0] % self.num_workers
                    or ds.features_mask is not None
                    or ds.labels_mask is not None):
                if mp:
                    raise ValueError(
                        "multi-process SharedTrainingMaster requires batch "
                        f"sizes divisible by {self.num_workers} workers and "
                        "no masks (got batch "
                        f"{x.shape[0]}, masks={ds.features_mask is not None})")
                # ragged tail or masked sequence data: the sharded step
                # doesn't carry masks — run unsharded (same math, no DP)
                network._fit_batch(ds)
                continue
            it = jnp.asarray(network.iteration, jnp.float32)
            ep = jnp.asarray(network.epoch, jnp.float32)
            rng = network._next_rng()
            xb = np.asarray(x, dtype)
            yb = np.asarray(y, dtype)
            if mp:
                xb, yb = make_global((xb, yb), self.mesh, shard0)
                it, ep, rng = make_global((it, ep, rng), self.mesh, rep)
            else:
                xb, yb = jnp.asarray(xb), jnp.asarray(yb)
            (network.params, network.states, network.updater_states,
             self._residual, loss, sparsity) = self._step_fn(
                network.params, network.states, network.updater_states,
                self._residual, it, ep, xb, yb, rng,
                np.float32(self.threshold))
            network.score_ = loss
            network.iteration += 1
            self._adapt_threshold(float(sparsity))
            for listener in network.listeners:
                if hasattr(listener, "iteration_done"):
                    listener.iteration_done(network, network.iteration,
                                            network.epoch)
        self.stats.add("fit", time.perf_counter() - t0)


class DistributedMultiLayerNetwork:
    """Front end pairing a network with a TrainingMaster
    (`SparkDl4jMultiLayer.java:71` role: ``fit(RDD)`` → master)."""

    def __init__(self, network, training_master: TrainingMaster):
        self.network = network
        self.master = training_master

    def fit(self, data_iterator, epochs: int = 1):
        if self.network.params is None:
            self.network.init()
        # settle the NTP offset BEFORE the first phase stamp so the timeline
        # never jumps when a background sync lands mid-run (one blocking
        # exchange at startup; no-op for already-synced / plain clocks)
        from deeplearning4j_tpu.parallel.time_source import get_time_source
        get_time_source().ensure_synced()
        for _ in range(epochs):
            if hasattr(data_iterator, "reset"):
                data_iterator.reset()
            self.master.execute_training(self.network, data_iterator)
            self.network.epoch += 1
        return self.network

    def evaluate(self, iterator):
        return self.network.evaluate(iterator)

    def get_training_stats(self) -> TrainingStats:
        return self.master.get_training_stats()
