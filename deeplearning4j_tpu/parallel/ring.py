"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has no attention and no sequence parallelism — its longest
sequence mechanism is truncated BPTT (`MultiLayerNetwork.java:1309`,
SURVEY.md §5 "Long-context"). This module is the TPU-native long-context
design the survey calls for: sequences are sharded over a Mesh axis
(``mesh.SEQUENCE_AXIS``); the ring strategy never materialises the full
[T, T] score matrix on one chip (Ulysses does — it trades that memory for
fewer collective steps).

Two strategies, both jit/shard_map-compatible:

- :func:`ring_attention` — blockwise attention with a flash-style streaming
  softmax (running max + normaliser). K/V blocks rotate around the ring via
  ``jax.lax.ppermute`` so each hop rides a single ICI link; compute on block
  i overlaps the transfer of block i+1 (XLA schedules the ppermute + einsum
  concurrently since they have no data dependence).
- :func:`ulysses_attention` — all-to-all switch: resharding [N, T/s, H, Dh]
  (sequence-sharded) → [N, T, H/s, Dh] (head-sharded), plain attention per
  head group, then all-to-all back. Fewer collective steps but requires
  n_heads % shards == 0.

Both compute the same attention as
``nn.layers.attention.dot_product_attention`` up to float32 round-off (the
streaming softmax reassociates the sum), asserted vs the single-device
reference on an 8-device CPU mesh.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import SEQUENCE_AXIS, shard_map

_NEG_INF = -1e30  # large finite negative: avoids nan from (-inf) - (-inf)


def _ring_attention_sharded(q, k, v, mask_kv, *, axis_name, causal, scale):
    """Per-shard body (runs under shard_map).

    q, k, v: [N, H, Tq_local, Dh] / [N, H, Tk_local, Dh] local shards.
    mask_kv: [N, Tk_local] validity of local keys (1=valid) or None.
    """
    n_shards = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    tq = q.shape[2]
    tk = k.shape[2]
    dtype = q.dtype

    q32 = (q * scale).astype(jnp.float32)
    out = jnp.zeros(q.shape[:2] + (tq, v.shape[-1]), jnp.float32)
    row_max = jnp.full(q.shape[:3], _NEG_INF, jnp.float32)
    row_sum = jnp.zeros(q.shape[:3], jnp.float32)

    has_mask = mask_kv is not None  # static: skips mask ops and its ppermute

    perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]

    def accumulate(step, k_blk, v_blk, m_blk, out, row_max, row_sum):
        # block that arrived after `step` hops originated at my_idx - step
        src = (my_idx - step) % n_shards

        def do(acc):
            out, row_max, row_sum = acc
            scores = jnp.einsum("nhqd,nhkd->nhqk", q32,
                                k_blk.astype(jnp.float32))
            valid = None
            if has_mask:
                valid = m_blk[:, None, None, :] > 0            # [N,1,1,Tk]
            if causal:
                q_pos = my_idx * tq + jnp.arange(tq)
                k_pos = src * tk + jnp.arange(tk)
                cm = q_pos[None, None, :, None] >= k_pos[None, None, None, :]
                valid = cm if valid is None else jnp.logical_and(valid, cm)
            if valid is not None:
                scores = jnp.where(valid, scores, _NEG_INF)
            blk_max = jnp.max(scores, axis=-1)
            new_max = jnp.maximum(row_max, blk_max)
            correction = jnp.exp(row_max - new_max)
            p = jnp.exp(scores - new_max[..., None])
            if valid is not None:
                # zero invalid entries so fully-masked rows keep row_sum == 0
                p = jnp.where(valid, p, 0.0)
            new_sum = row_sum * correction + jnp.sum(p, axis=-1)
            new_out = out * correction[..., None] + jnp.einsum(
                "...qk,...kd->...qd", p, v_blk.astype(jnp.float32))
            return new_out, new_max, new_sum

        if causal and tq == tk:
            # blocks strictly in the future are fully masked — skip the matmul
            return jax.lax.cond(src > my_idx, lambda acc: acc, do,
                                (out, row_max, row_sum))
        return do((out, row_max, row_sum))

    def body(step, carry):
        out, row_max, row_sum, k_blk, v_blk, m_blk = carry
        out, row_max, row_sum = accumulate(step, k_blk, v_blk, m_blk,
                                           out, row_max, row_sum)
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        if has_mask:
            m_blk = jax.lax.ppermute(m_blk, axis_name, perm)
        return out, row_max, row_sum, k_blk, v_blk, m_blk

    # n_shards-1 rotate-and-accumulate hops, then the last block in place
    # (no trailing ppermute whose result would be discarded).
    carry = (out, row_max, row_sum, k, v,
             mask_kv if has_mask else jnp.zeros((), jnp.float32))
    out, row_max, row_sum, k_blk, v_blk, m_blk = jax.lax.fori_loop(
        0, n_shards - 1, body, carry)
    out, row_max, row_sum = accumulate(n_shards - 1, k_blk, v_blk, m_blk,
                                       out, row_max, row_sum)
    # rows with no valid key (fully masked) emit zeros, not nan
    denom = jnp.where(row_sum > 0, row_sum, 1.0)
    return (out / denom[..., None]).astype(dtype)


def ring_attention(q, k, v, *, axis_name: str = SEQUENCE_AXIS,
                   mask: Optional[jax.Array] = None, causal: bool = False):
    """Ring attention over sequence shards. Call under shard_map/pjit.

    q, k, v: [N, H, T_local, Dh] — the local sequence shard of each device
    on mesh axis ``axis_name``. ``mask``: [N, T_local] key validity (1=valid).
    Returns [N, H, T_local, Dh]; matches full attention to float32 round-off.
    Fully-masked query rows return zeros.
    """
    scale = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], q.dtype))
    return _ring_attention_sharded(q, k, v, mask, axis_name=axis_name,
                                   causal=causal, scale=scale)


def ring_self_attention(q, k, v, mesh: Mesh, *,
                        axis_name: str = SEQUENCE_AXIS,
                        mask: Optional[jax.Array] = None,
                        causal: bool = False):
    """Convenience wrapper: full [N, H, T, Dh] arrays in, shard_map inside.

    Shards T over ``axis_name`` (batch/head replicated) and runs
    :func:`ring_attention`. For production nets compose the per-shard
    function into your own pjit'd step instead.
    """
    n_shards = mesh.shape[axis_name]
    if q.shape[2] % n_shards:
        raise ValueError(
            f"ring attention needs seq len divisible by shards "
            f"({q.shape[2]} % {n_shards})")
    spec_qkv = P(None, None, axis_name, None)
    spec_mask = P(None, axis_name)
    in_specs = (spec_qkv, spec_qkv, spec_qkv,
                spec_mask if mask is not None else None)

    @functools.partial(shard_map, mesh=mesh, in_specs=in_specs,
                       out_specs=spec_qkv)
    def run(q, k, v, m):
        return ring_attention(q, k, v, axis_name=axis_name, mask=m,
                              causal=causal)

    return run(q, k, v, mask)


def _plain_attention(q, k, v, mask):
    """Raw einsum attention, deliberately NOT the seam-consulting
    ``dot_product_attention``: this runs inside the helper's own shard_map
    body, where consulting the seam again would re-enter the registered
    helper and nest a second shard_map on the same mesh."""
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    scores = jnp.einsum("nhqd,nhkd->nhqk", q, k) * scale
    if mask is not None:
        m = mask[:, None, None, :] if mask.ndim == 2 else mask
        scores = jnp.where(m > 0, scores, jnp.finfo(scores.dtype).min)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("nhqk,nhkd->nhqd", w, v)


def _ulysses_sharded(q, k, v, mask, *, axis_name, causal):
    """Per-shard Ulysses body: [N, H, T/s, Dh] in → all-to-all →
    [N, H/s, T, Dh] → plain attention → all-to-all back."""

    def seq_to_head(x):
        # split heads (axis 1) across shards, gather sequence (axis 2)
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    def head_to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    qh, kh, vh = seq_to_head(q), seq_to_head(k), seq_to_head(v)
    full_mask = None
    if mask is not None:
        full_mask = jax.lax.all_gather(mask, axis_name, axis=1, tiled=True)
    if causal:
        t = qh.shape[2]
        tri = jnp.tril(jnp.ones((t, t), jnp.float32))[None, None]
        full_mask = tri if full_mask is None else (
            full_mask[:, None, None, :] * tri)
    out = _plain_attention(qh, kh, vh, full_mask)
    return head_to_seq(out)


def ulysses_attention(q, k, v, mesh: Mesh, *,
                      axis_name: str = SEQUENCE_AXIS,
                      mask: Optional[jax.Array] = None,
                      causal: bool = False):
    """Ulysses (all-to-all) sequence parallelism on full [N, H, T, Dh] arrays.

    Requires H % mesh.shape[axis_name] == 0. Two all-to-alls per call; the
    attention itself is the stock fused XLA path.
    """
    n_shards = mesh.shape[axis_name]
    if q.shape[1] % n_shards:
        raise ValueError(
            f"ulysses needs n_heads divisible by shards ({q.shape[1]} % {n_shards})")
    if q.shape[2] % n_shards:
        raise ValueError(
            f"ulysses needs seq len divisible by shards ({q.shape[2]} % {n_shards})")
    spec_qkv = P(None, None, axis_name, None)
    spec_mask = P(None, axis_name)
    in_specs = (spec_qkv, spec_qkv, spec_qkv,
                spec_mask if mask is not None else None)

    @functools.partial(shard_map, mesh=mesh, in_specs=in_specs,
                       out_specs=spec_qkv)
    def run(q, k, v, m):
        return _ulysses_sharded(q, k, v, m, axis_name=axis_name, causal=causal)

    return run(q, k, v, mask)


class SequenceParallelAttentionHelper:
    """Attention-seam helper that runs every attention layer sequence-parallel
    (ring or Ulysses) over a mesh axis — register it and the whole model
    (zoo TransformerEncoder, imported BERT, any SelfAttentionLayer graph)
    becomes long-context without model changes:

        helpers.set_helper("attention",
                           SequenceParallelAttentionHelper(mesh))

    strategy: "ring" (never materializes a [T,T] tile per chip) or
    "ulysses" (all-to-all head switch; needs n_heads % shards == 0).
    Conservative gate: no attention mask (attention-level masks would need
    sharding too), no attention dropout, T divisible by the shard count.
    """

    def __init__(self, mesh: Mesh, strategy: str = "ring",
                 axis_name: str = SEQUENCE_AXIS, causal: bool = False):
        if strategy not in ("ring", "ulysses"):
            raise ValueError(f"unknown strategy {strategy!r} (ring|ulysses)")
        self.mesh = mesh
        self.strategy = strategy
        self.axis_name = axis_name
        self.causal = causal
        self.n_shards = mesh.shape[axis_name]

    def supports(self, layer, q_shape, mask, dropout_active,
                 causal=False) -> bool:
        if causal != self.causal:
            # causality of the sharded kernel must match the request, else
            # registering the helper would change model outputs
            return False
        if mask is not None or dropout_active:
            return False
        t = q_shape[-2]
        if t % self.n_shards:
            return False
        if self.strategy == "ulysses" and q_shape[1] % self.n_shards:
            return False
        return True

    def attend(self, q, k, v):
        fn = ring_self_attention if self.strategy == "ring" else ulysses_attention
        return fn(q, k, v, self.mesh, axis_name=self.axis_name,
                  causal=self.causal)
