"""Pipeline runner: the orchestration that makes the stages one product.

``ContinuousPipeline`` composes the subsystem — journaled state machine
(``state.py``), streaming trainer (``trainer.py``), eval gate
(``gate.py``), canary controller (``canary.py``) and the registry's
weighted-routing/shadow data plane (``serving/registry.py``) — into the
loop::

    stream -> TRAIN (mini-epoch fit, watchdog-guarded)
           -> EVAL  (gate vs the serving version, journaled)
           -> CANARY (ramp + shadow, SLO/alert-watched)
           -> PROMOTE (hot-swap) | ROLLBACK (discard)

Crash safety: every stage is entered/committed through the fenced
journal, so a restarted pipeline resumes at the crashed stage and
converges to the same terminal state.  Work that cannot survive a crash
is *redone* (an uncommitted TRAIN retrains from the serving version, an
uncommitted CANARY re-ramps from the first step); work that must happen
exactly once is *idempotent* (PROMOTE re-runs ``registry.activate``,
which no-ops when the version is already live) and the journal's
single-terminal rule makes a second promote/rollback un-committable.
The trained candidate itself is made durable at TRAIN commit: the runner
serializes it into the journal directory and records the path, so a
restarted process (whose in-memory registry is fresh) re-registers the
same weights rather than the same version *number*.

``PipelineConfig`` is the JSON schema shared by the ``pipeline`` CLI
subcommand, ``examples/pipeline_config.json`` and
``tools/validate_pipeline_config.py``.
"""

from __future__ import annotations

import json
import os
import threading
import time as _time
from typing import Any, Callable, Dict, List, Optional

from deeplearning4j_tpu.observe import log as _slog
from deeplearning4j_tpu.observe.health import WatchdogAlarm
from deeplearning4j_tpu.pipeline.canary import CanaryController, parse_schedule
from deeplearning4j_tpu.pipeline.gate import GATE_METRICS, EvalGate
from deeplearning4j_tpu.pipeline.state import PipelineStateMachine
from deeplearning4j_tpu.pipeline.trainer import (ContinuousTrainer,
                                                 StreamBuffer, StreamStuck)

_WATCHDOG_MODES = ("off", "log", "raise")


class CandidateLost(RuntimeError):
    """A resumed run's candidate is neither registered in this process
    nor recoverable from its persisted checkpoint — the run cannot
    proceed and is decided as a journaled ROLLBACK."""


class PipelineConfig:
    """Parsed + validated pipeline parameters.

    Schema (all sections optional, defaults shown)::

        {
          "name": "model",
          "cycles": 1,
          "train": {"batch_size": 32, "batches_per_mini_epoch": 4,
                    "mini_epochs": 3, "take_timeout_s": 5.0,
                    "watchdog": "raise"},
          "gate":  {"metric": "loss", "rel_margin": 0.0,
                    "abs_margin": 0.0, "batch_size": 64},
          "canary": {"schedule": [{"fraction": 0.1, "hold_s": 30},
                                  {"fraction": 0.5, "hold_s": 30}],
                     "shadow_sample": 0.25,
                     "divergence_threshold": 0.001,
                     "max_divergences": null,
                     "abort_on_alerts": null,
                     "poll_s": 0.5}
        }

    ``parse`` raises ``ValueError`` naming the offending field on any
    schema problem; :meth:`lint` returns dry-run warnings for configs
    that parse but cannot behave as written (the validator's second
    pass).
    """

    _SECTIONS = ("name", "cycles", "train", "gate", "canary")
    _TRAIN_KEYS = ("batch_size", "batches_per_mini_epoch", "mini_epochs",
                   "take_timeout_s", "watchdog")
    _GATE_KEYS = ("metric", "rel_margin", "abs_margin", "batch_size")
    _CANARY_KEYS = ("schedule", "shadow_sample", "divergence_threshold",
                    "max_divergences", "abort_on_alerts", "poll_s")

    def __init__(self):
        self.name = "model"
        self.cycles = 1
        self.train: Dict[str, Any] = {
            "batch_size": 32, "batches_per_mini_epoch": 4,
            "mini_epochs": 3, "take_timeout_s": 5.0, "watchdog": "raise"}
        self.gate: Dict[str, Any] = {
            "metric": "loss", "rel_margin": 0.0, "abs_margin": 0.0,
            "batch_size": 64}
        self.canary: Dict[str, Any] = {
            "schedule": [{"fraction": 0.1, "hold_s": 30},
                         {"fraction": 0.5, "hold_s": 30}],
            "shadow_sample": 0.25, "divergence_threshold": 1e-3,
            "max_divergences": None, "abort_on_alerts": None,
            "poll_s": 0.5}

    # ------------------------------------------------------------- parsing
    @classmethod
    def parse(cls, spec) -> "PipelineConfig":
        """From a parsed dict, a JSON string, or a file path."""
        if isinstance(spec, (str, bytes)) and not str(
                spec).lstrip().startswith("{"):
            with open(spec, "r", encoding="utf-8") as fh:
                spec = json.load(fh)
        elif isinstance(spec, (str, bytes)):
            spec = json.loads(spec)
        if not isinstance(spec, dict):
            raise ValueError("pipeline config must be a JSON object")
        unknown = set(spec) - set(cls._SECTIONS)
        if unknown:
            raise ValueError(f"unknown config section(s) {sorted(unknown)} "
                             f"(known: {cls._SECTIONS})")
        cfg = cls()
        if "name" in spec:
            if not isinstance(spec["name"], str) or not spec["name"]:
                raise ValueError("name: must be a non-empty string")
            cfg.name = spec["name"]
        if "cycles" in spec:
            if not isinstance(spec["cycles"], int) or spec["cycles"] < 1:
                raise ValueError(
                    f"cycles: must be an int >= 1, got {spec['cycles']!r}")
            cfg.cycles = spec["cycles"]
        for section, known, target in (
                ("train", cls._TRAIN_KEYS, cfg.train),
                ("gate", cls._GATE_KEYS, cfg.gate),
                ("canary", cls._CANARY_KEYS, cfg.canary)):
            sub = spec.get(section)
            if sub is None:
                continue
            if not isinstance(sub, dict):
                raise ValueError(f"{section}: must be an object")
            bad = set(sub) - set(known)
            if bad:
                raise ValueError(f"{section}: unknown key(s) {sorted(bad)} "
                                 f"(known: {known})")
            target.update(sub)
        cfg._validate()
        return cfg

    def _validate(self) -> None:
        t = self.train
        for key in ("batch_size", "batches_per_mini_epoch", "mini_epochs"):
            if not isinstance(t[key], int) or t[key] < 1:
                raise ValueError(
                    f"train.{key}: must be an int >= 1, got {t[key]!r}")
        if not isinstance(t["take_timeout_s"], (int, float)) \
                or t["take_timeout_s"] <= 0:
            raise ValueError(
                f"train.take_timeout_s: must be > 0, "
                f"got {t['take_timeout_s']!r}")
        wd = t["watchdog"]
        if not (wd in _WATCHDOG_MODES or isinstance(wd, dict)):
            raise ValueError(
                f"train.watchdog: must be one of {_WATCHDOG_MODES} or a "
                f"TrainingWatchdog kwargs object, got {wd!r}")
        g = self.gate
        if g["metric"] not in GATE_METRICS:
            raise ValueError(f"gate.metric: unknown metric "
                             f"{g['metric']!r} (one of {GATE_METRICS})")
        for key in ("rel_margin", "abs_margin"):
            if not isinstance(g[key], (int, float)) or g[key] < 0:
                raise ValueError(
                    f"gate.{key}: must be >= 0, got {g[key]!r}")
        if not isinstance(g["batch_size"], int) or g["batch_size"] < 1:
            raise ValueError(f"gate.batch_size: must be an int >= 1, "
                             f"got {g['batch_size']!r}")
        c = self.canary
        try:
            parse_schedule(c["schedule"])
        except (TypeError, KeyError) as e:
            raise ValueError(f"canary.schedule: malformed step ({e})") from e
        except ValueError as e:
            raise ValueError(f"canary.schedule: {e}") from e
        if not isinstance(c["shadow_sample"], (int, float)) \
                or not 0.0 <= c["shadow_sample"] <= 1.0:
            raise ValueError(f"canary.shadow_sample: must be in [0, 1], "
                             f"got {c['shadow_sample']!r}")
        if not isinstance(c["divergence_threshold"], (int, float)) \
                or c["divergence_threshold"] < 0:
            raise ValueError(
                f"canary.divergence_threshold: must be >= 0, "
                f"got {c['divergence_threshold']!r}")
        if c["max_divergences"] is not None and (
                not isinstance(c["max_divergences"], int)
                or c["max_divergences"] < 0):
            raise ValueError(
                f"canary.max_divergences: must be null or an int >= 0, "
                f"got {c['max_divergences']!r}")
        if c["abort_on_alerts"] is not None and (
                not isinstance(c["abort_on_alerts"], list)
                or not all(isinstance(a, str) for a in c["abort_on_alerts"])):
            raise ValueError(
                "canary.abort_on_alerts: must be null or a list of rule "
                "names")
        if not isinstance(c["poll_s"], (int, float)) or c["poll_s"] <= 0:
            raise ValueError(
                f"canary.poll_s: must be > 0, got {c['poll_s']!r}")

    # ---------------------------------------------------------------- lint
    def lint(self) -> List[str]:
        """Dry-run warnings for configs that parse but cannot behave as
        written (nothing is executed)."""
        problems: List[str] = []
        c = self.canary
        if c["max_divergences"] is not None and c["shadow_sample"] == 0:
            problems.append(
                "canary.max_divergences is set but shadow_sample is 0 — "
                "no shadow comparisons ever run, so the divergence budget "
                "can never trigger a rollback")
        if all(float(s["fraction"] if isinstance(s, dict) else s.fraction)
               * float(s["hold_s"] if isinstance(s, dict) else s.hold_s) == 0
               for s in c["schedule"]):
            problems.append(
                "canary.schedule holds every fraction for 0s — the canary "
                "decides instantly and observes no traffic")
        if self.train["watchdog"] == "off" \
                and self.gate["rel_margin"] == 0 \
                and self.gate["abs_margin"] == 0:
            problems.append(
                "train.watchdog is off and both gate margins are 0 — a "
                "noisily-trained candidate will be rejected by the strict "
                "gate with no earlier signal; consider watchdog 'log' or "
                "a small gate margin")
        return problems


class ContinuousPipeline:
    """One model's continuous-training loop over a live registry.

    The caller owns the stream (a ``streaming.Route`` delivering into
    ``buffer``), the ``registry`` (with the model's serving version
    registered and live) and the held-out ``eval_set``; the pipeline owns
    the journal under ``state_dir`` and the stage choreography.

    ``canary_wait(poll_s)`` runs between canary ticks — the seam where
    deterministic callers advance a ``ManualTimeSource`` and drive
    traffic; it defaults to a real sleep.  ``alerts`` is an
    ``observe.alerts.AlertManager`` whose firing rules can roll the
    canary back.  :meth:`request_stop` (the CLI's SIGTERM path) drains
    cleanly: the open run is decided as a journaled ROLLBACK instead of
    being abandoned mid-stage.
    """

    def __init__(self, registry, name: str, state_dir: str, *,
                 config: Optional[PipelineConfig] = None,
                 buffer: Optional[StreamBuffer] = None,
                 route=None, eval_set=None,
                 metrics=None, tracer=None, time_source=None, alerts=None,
                 sample_input=None,
                 candidate_source: Optional[Callable[[], Any]] = None,
                 canary_wait: Optional[Callable[[float], None]] = None):
        self.registry = registry
        self.name = name
        self.state_dir = str(state_dir)
        self.config = config if config is not None else PipelineConfig()
        self.buffer = buffer if buffer is not None else StreamBuffer()
        self.route = route
        self.eval_set = eval_set
        self.metrics = metrics
        self.tracer = tracer
        self.time_source = time_source
        self.alerts = alerts
        self.sample_input = sample_input
        self.candidate_source = candidate_source
        self.canary_wait = canary_wait
        self.sm = PipelineStateMachine(self.state_dir, name=name,
                                       metrics=metrics)
        self._stop = threading.Event()
        self._log = _slog.get_logger("pipeline")
        # how long the CANARY stage waits for an async candidate warmup
        # before deciding rollback (sync warmup finishes at registration,
        # so the default path never waits)
        self.warm_timeout_s = 120.0

    # ------------------------------------------------------------ plumbing
    def request_stop(self) -> None:
        """Ask for a clean drain: the current run decides ROLLBACK at the
        next stage boundary / canary tick instead of crashing mid-stage."""
        self._stop.set()

    @property
    def stopped(self) -> bool:
        return self._stop.is_set()

    def _serving_model(self):
        served = self.registry.get(self.name)
        return served.versions[served.current_version].model

    def _candidate_model(self):
        if self.candidate_source is not None:
            return self.candidate_source()
        base = self._serving_model()
        if hasattr(base, "clone"):
            return base.clone()
        raise TypeError(
            f"serving model {type(base).__name__} has no clone(); pass "
            "candidate_source= to supply candidate models")

    def _persist_candidate(self, model) -> Optional[str]:
        """Serialize the trained candidate next to the journal so a
        restarted process can re-register the same weights."""
        path = os.path.join(self.state_dir,
                            f"candidate_run{self.sm.run:04d}.zip")
        try:
            from deeplearning4j_tpu.util import model_serializer
            model_serializer.write_model(model, path)
            return path
        except Exception:  # noqa: BLE001 — non-serializable candidates
            # (duck-typed stubs) stay process-local; resume then retrains
            return None

    def _ensure_candidate(self, data: dict) -> int:
        """Resolve the journaled candidate to a version in THIS process's
        registry: the journaled version when it exists, else re-register
        from the persisted checkpoint path."""
        version = data.get("candidate_version")
        served = self.registry.get(self.name)
        if version is not None and version in served.versions:
            return int(version)
        path = data.get("candidate_path")
        if not path:
            # later-stage records only carry the version; the durable
            # checkpoint path lives in this run's TRAIN commit
            for r in self.sm.stage_history():
                if (r.get("stage"), r.get("event")) == ("TRAIN", "commit"):
                    path = r.get("data", {}).get("candidate_path")
        if path and os.path.exists(path):
            return self.registry.register(
                self.name, path=path, activate=False,
                sample_input=self.sample_input)
        raise CandidateLost(
            f"run {self.sm.run}: candidate v{version} is not registered "
            f"and its checkpoint is gone (path={path!r})")

    def restore_promoted(self) -> Optional[int]:
        """Re-apply the journal's LATEST committed PROMOTE to this
        process's registry — the cross-process crash-recovery step for
        callers (the CLI) that rebuild the registry from the original
        baseline artifact: without it a restart would silently serve
        pre-promotion weights and write them to --modelOutputPath even
        though the journal records the promotion. Returns the version
        the promoted weights got in THIS registry (None when no promote
        was journaled or its checkpoint is gone)."""
        records = self.sm.journal.records()
        last_promote = None
        for r in records:
            if (r.get("stage"), r.get("event")) == ("PROMOTE", "commit"):
                last_promote = r
        if last_promote is None:
            return None
        run = int(last_promote.get("run", -1))
        path = None
        for r in records:
            if int(r.get("run", -2)) == run and \
                    (r.get("stage"), r.get("event")) == ("TRAIN", "commit"):
                path = r.get("data", {}).get("candidate_path")
        if not path or not os.path.exists(path):
            self._log.warning(
                "journal records a PROMOTE but its candidate checkpoint "
                "is gone; serving the registered baseline",
                run=run, candidate_path=path)
            return None
        version = self.registry.register(
            self.name, path=path, activate=True,
            sample_input=self.sample_input)
        self._log.info("restored journaled promotion", run=run,
                       version=version, candidate_path=path)
        return version

    def _candidate_zip(self, run: int) -> str:
        return os.path.join(self.state_dir, f"candidate_run{run:04d}.zip")

    def _retire_candidate(self, run: int, version: Optional[int]) -> None:
        """A decided ROLLBACK has no further use for the candidate: drop
        its registry version (full weights + warmed forwards) and its
        persisted checkpoint, so an indefinitely-running pipeline does
        not leak one model per rejected cycle."""
        try:
            if version is not None \
                    and version != self.registry.get(
                        self.name).current_version:
                self.registry.unregister(self.name, version)
        except Exception:  # noqa: BLE001 — retirement is best-effort
            pass
        try:
            os.unlink(self._candidate_zip(run))
        except OSError:
            pass

    def _prune_candidate_zips(self, keep_run: int) -> None:
        """After a PROMOTE, only the promoted run's checkpoint is needed
        (it is what ``restore_promoted`` re-registers after a restart)."""
        try:
            names = os.listdir(self.state_dir)
        except OSError:
            return
        keep = os.path.basename(self._candidate_zip(keep_run))
        for n in names:
            if n.startswith("candidate_run") and n.endswith(".zip") \
                    and n != keep:
                try:
                    os.unlink(os.path.join(self.state_dir, n))
                except OSError:
                    pass

    def _await_candidate_warm(self, version: int) -> tuple:
        """Block until the candidate's AOT warmup finished (async
        registries warm in the background; the traffic split is
        warm-gated, so fronting a cold candidate is refused anyway).
        A FAILED warmup gets one ``rewarm()``; persistent failure or
        timeout returns (False, why) and the canary decides rollback
        instead of crash-looping on the warm gate."""
        if not hasattr(self.registry, "warmup_state"):
            return True, "registry has no warmup tracking"
        deadline = _time.monotonic() + self.warm_timeout_s
        rewarmed = False
        while True:
            state = self.registry.warmup_state(self.name, version)
            status = state.get("status")
            if status in ("warm", "skipped", "unknown"):
                return True, status
            if status == "error":
                if rewarmed:
                    return False, (state.get("reason")
                                   or "warmup failed twice")
                rewarmed = True
                try:
                    self.registry.rewarm(self.name, version)
                except Exception as e:  # noqa: BLE001
                    return False, f"rewarm failed: {e}"
                continue
            if _time.monotonic() > deadline:
                return False, (f"warmup still {status!r} after "
                               f"{self.warm_timeout_s}s")
            _time.sleep(0.05)

    # -------------------------------------------------------------- stages
    def _stage_train(self) -> dict:
        cfg = self.config.train
        candidate = self._candidate_model()
        wd = cfg["watchdog"]
        watchdog = (None if wd == "off"
                    else dict(wd) if isinstance(wd, dict)
                    else {"action": wd})
        trainer = ContinuousTrainer(
            candidate, self.buffer,
            batch_size=cfg["batch_size"],
            batches_per_mini_epoch=cfg["batches_per_mini_epoch"],
            take_timeout_s=cfg["take_timeout_s"],
            metrics=self.metrics, tracer=self.tracer,
            model_name=f"{self.name}-candidate", watchdog=watchdog)
        stats = None
        for _ in range(cfg["mini_epochs"]):
            if self._stop.is_set():
                break
            try:
                stats = trainer.train_mini_epoch()
            except StreamStuck:
                err = (getattr(self.route, "error", None)
                       if self.route is not None else None)
                if err is not None:
                    # a FAILED route is not a drained one: a candidate
                    # trained on a truncated stream must not promote
                    raise StreamStuck(f"stream failed: {err!r}") from err
                if trainer.mini_epochs > 0 and self._route_finished():
                    break  # stream drained cleanly: train on what arrived
                raise
        if stats is None:
            raise StreamStuck(
                "stream delivered nothing to train on "
                f"(route error: {getattr(self.route, 'error', None)!r})")
        version = self.registry.register(
            self.name, model=candidate, activate=False,
            sample_input=self.sample_input)
        path = self._persist_candidate(candidate)
        return {"candidate_version": version, "candidate_path": path,
                "examples": trainer.examples_seen,
                "mini_epochs": trainer.mini_epochs,
                "score": stats["score"]}

    def _route_finished(self) -> bool:
        """A CLEAN drain only — a failed route is handled (and raised)
        separately in the train loop."""
        if self.route is None:
            return True  # no route attached: caller feeds the buffer
        return getattr(self.route, "result", None) is not None

    def _stage_eval(self, candidate_version: int) -> dict:
        if self.eval_set is None:
            raise ValueError("eval gate needs eval_set= (a held-out "
                             "DataSet) — refusing to promote unevaluated "
                             "candidates")
        cfg = self.config.gate
        gate = EvalGate(self.eval_set, metric=cfg["metric"],
                        rel_margin=cfg["rel_margin"],
                        abs_margin=cfg["abs_margin"],
                        batch_size=cfg["batch_size"])
        served = self.registry.get(self.name)
        candidate = served.versions[candidate_version].model
        result = gate.evaluate(candidate, self._serving_model())
        out = result.to_dict()
        out["candidate_version"] = candidate_version
        return out

    def _stage_canary(self, candidate_version: int) -> dict:
        cfg = self.config.canary
        warm, why = self._await_candidate_warm(candidate_version)
        if not warm:
            return {"decision": "rollback",
                    "reason": f"candidate never became warm: {why}",
                    "candidate_version": candidate_version,
                    "shadow": {"requests": 0, "divergences": 0}}
        controller = CanaryController(
            self.registry, self.name, candidate_version,
            schedule=cfg["schedule"], time_source=self.time_source,
            alerts=self.alerts, abort_on_alerts=cfg["abort_on_alerts"],
            shadow_sample=cfg["shadow_sample"],
            divergence_threshold=cfg["divergence_threshold"],
            max_divergences=cfg["max_divergences"],
            on_event=lambda kind, detail: self.sm.note(
                f"canary {kind}", **detail))
        controller.start()
        while True:
            if self._stop.is_set():
                controller.report_alarm("operator stop (drain)")
            decision = controller.tick()
            if decision is not None:
                break
            if self.canary_wait is not None:
                self.canary_wait(cfg["poll_s"])
            else:
                _time.sleep(cfg["poll_s"])
        shadow = controller.shadow_final or {"requests": 0,
                                             "divergences": 0}
        return {"decision": decision, "reason": controller.reason,
                "candidate_version": candidate_version,
                "shadow": {k: shadow.get(k, 0)
                           for k in ("requests", "divergences")}}

    # ------------------------------------------------------------ the loop
    def _rollback_run(self, reason: str) -> dict:
        """Decide the open run as a journaled ROLLBACK from wherever it
        currently is — the recovery for a resumed run whose candidate is
        unrecoverable (a crash loop otherwise: the run could neither
        finish nor be superseded)."""
        st = self.sm.state()
        if st.stage in ("TRAIN", "EVAL", "CANARY") and not st.committed:
            self.sm.commit(st.stage, aborted=reason)
        st = self.sm.state()
        if not (st.stage == "ROLLBACK" and not st.committed):
            self.sm.enter("ROLLBACK", reason=reason)
        self.registry.clear_traffic_split(self.name)
        self.registry.clear_shadow(self.name)
        self.sm.commit("ROLLBACK", reason=reason)
        self._retire_candidate(self.sm.run, None)
        return self._summary()

    def run_cycle(self) -> dict:
        """Advance the journal to this run's terminal commit — starting a
        fresh run from IDLE, or finishing a crashed predecessor's run
        from its resume point — and return the run summary.

        While a tracer is active the whole cycle runs inside a
        ``pipeline_run`` span, so every journal append made during it
        (``PipelineJournal.append`` stamps the active trace id) and every
        log line is correlatable back to the cycle that decided."""
        from deeplearning4j_tpu.observe import trace as _trace
        tracer = self.tracer if self.tracer is not None \
            else _trace.get_active_tracer()
        if tracer is None:
            return self._run_cycle_inner()
        with tracer.span("pipeline_run", category="pipeline",
                         attrs={"pipeline": self.name}) as sp:
            summary = self._run_cycle_inner()
            sp.set_attribute("run", summary.get("run"))
            sp.set_attribute("outcome", summary.get("outcome"))
            return summary

    def _run_cycle_inner(self) -> dict:
        st = self.sm.state()
        if st.stage == "IDLE":
            # a predecessor that crashed right after begin_run left an
            # opened-but-empty run: continue IT rather than abandoning it
            # undecided under a fresh run number
            if not self.sm.open_empty_run():
                self.sm.begin_run()
            st = self.sm.state()
        self._log.info("pipeline cycle", run=self.sm.run, stage=st.stage,
                       committed=st.committed)
        try:
            return self._run_stages(st)
        except CandidateLost as e:
            # the run cannot proceed and must not crash-loop: decide it
            self._log.warning("candidate unrecoverable; rolling back",
                              run=self.sm.run, reason=str(e))
            return self._rollback_run(f"candidate lost: {e}")

    def _run_stages(self, st) -> dict:
        # TRAIN ---------------------------------------------------------
        if st.stage in ("IDLE",) or (st.stage == "TRAIN"
                                     and not st.committed):
            if st.stage != "TRAIN":
                self.sm.enter("TRAIN")
            try:
                data = self._stage_train()
            except (WatchdogAlarm, StreamStuck) as e:
                self.sm.commit("TRAIN", aborted=f"{type(e).__name__}: {e}")
                self.sm.enter("ROLLBACK", reason=f"TRAIN aborted: {e}")
                data = None
            if data is not None:
                self.sm.commit("TRAIN", **data)
            st = self.sm.state()

        # EVAL ----------------------------------------------------------
        if st.stage == "TRAIN" and st.committed:
            if "candidate_version" not in st.data:  # aborted TRAIN commit
                self.sm.enter("ROLLBACK", reason="TRAIN aborted")
            else:
                version = self._ensure_candidate(st.data)
                self.sm.enter("EVAL", candidate_version=version)
            st = self.sm.state()
        if st.stage == "EVAL" and not st.committed:
            version = self._ensure_candidate(st.data)
            self.sm.commit("EVAL", **self._stage_eval(version))
            st = self.sm.state()

        # gate verdict → CANARY or ROLLBACK -----------------------------
        if st.stage == "EVAL" and st.committed:
            version = self._ensure_candidate(st.data)
            if not st.data.get("passed"):
                self.sm.enter("ROLLBACK", candidate_version=version,
                              reason=st.data.get("detail",
                                                 "eval gate failed"))
            else:
                self.sm.enter("CANARY", candidate_version=version)
            st = self.sm.state()
        if st.stage == "CANARY" and not st.committed:
            version = self._ensure_candidate(st.data)
            self.sm.commit("CANARY", **self._stage_canary(version))
            st = self.sm.state()

        # decision → PROMOTE or ROLLBACK --------------------------------
        if st.stage == "CANARY" and st.committed:
            version = self._ensure_candidate(st.data)
            if st.data.get("decision") == "promote":
                self.sm.enter("PROMOTE", candidate_version=version)
            else:
                self.sm.enter("ROLLBACK", candidate_version=version,
                              reason=st.data.get("reason", "canary"))
            st = self.sm.state()
        if st.stage == "PROMOTE" and not st.committed:
            version = self._ensure_candidate(st.data)
            # idempotent: a resume after the swap landed no-ops here, so
            # the journal's single PROMOTE commit matches ≤1 swap event
            self.registry.activate(self.name, version)
            self.sm.commit("PROMOTE", version=version)
            # older runs' checkpoints are superseded; keep only this one
            # (restore_promoted's cross-process recovery artifact)
            self._prune_candidate_zips(self.sm.run)
        elif st.stage == "ROLLBACK" and not st.committed:
            # nothing was promoted; make sure no canary plumbing survives
            self.registry.clear_traffic_split(self.name)
            self.registry.clear_shadow(self.name)
            self.sm.commit("ROLLBACK",
                           reason=st.data.get("reason", "rolled back"))
            # the rejected candidate has no further use: free its
            # registry slot + persisted checkpoint
            self._retire_candidate(self.sm.run,
                                   st.data.get("candidate_version"))
        return self._summary()

    def _summary(self) -> dict:
        outcome = self.sm.decided()
        terminal = [r for r in self.sm.stage_history()
                    if r.get("event") == "commit"
                    and r.get("stage") == outcome]
        data = terminal[-1].get("data", {}) if terminal else {}
        summary = {"run": self.sm.run, "outcome": outcome,
                   "detail": data,
                   "live_version":
                       self.registry.get(self.name).current_version}
        self._log.info("pipeline run decided", **{
            "run": summary["run"], "outcome": outcome,
            "live_version": summary["live_version"]})
        return summary

    def run(self, cycles: Optional[int] = None) -> List[dict]:
        """Run ``cycles`` full runs (default: config), stopping early on
        :meth:`request_stop`; returns the per-run summaries."""
        cycles = self.config.cycles if cycles is None else int(cycles)
        out = []
        for _ in range(cycles):
            out.append(self.run_cycle())
            if self._stop.is_set():
                break
        return out
