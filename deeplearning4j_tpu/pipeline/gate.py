"""Eval gate: the candidate must beat (or stay within margins of) the
serving version on a held-out eval set before any traffic touches it.

The EVAL stage of the pipeline.  Two metrics:

- ``"loss"`` (default): mean loss on the eval set, lower is better —
  candidate passes when
  ``cand <= base * (1 + rel_margin) + abs_margin``;
- ``"accuracy"``: top-1 classification accuracy via the evaluation
  surface, higher is better — candidate passes when
  ``cand >= base * (1 - rel_margin) - abs_margin``.

The full :class:`GateResult` (both measurements, margins, verdict) is
what the pipeline runner records in the journal's EVAL commit, so every
promote/rollback decision is auditable after the fact.
"""

from __future__ import annotations

from typing import Optional

from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator

GATE_METRICS = ("loss", "accuracy")


class GateResult:
    """One gate evaluation: the candidate and baseline measurements and
    the pass/fail verdict with its reasoning."""

    __slots__ = ("passed", "metric", "candidate", "baseline", "threshold",
                 "detail")

    def __init__(self, passed: bool, metric: str, candidate: float,
                 baseline: float, threshold: float, detail: str):
        self.passed = bool(passed)
        self.metric = metric
        self.candidate = float(candidate)
        self.baseline = float(baseline)
        self.threshold = float(threshold)
        self.detail = detail

    def to_dict(self) -> dict:
        return {"passed": self.passed, "metric": self.metric,
                "candidate": self.candidate, "baseline": self.baseline,
                "threshold": self.threshold, "detail": self.detail}

    def __repr__(self):  # pragma: no cover - debugging aid
        return (f"GateResult({'PASS' if self.passed else 'FAIL'} "
                f"{self.metric}: cand={self.candidate:.6g} vs "
                f"base={self.baseline:.6g}, thr={self.threshold:.6g})")


class EvalGate:
    """Held-out comparison gate between a candidate and the live model.

    ``eval_set`` is a ``DataSet``; margins are relative and absolute
    slack on the baseline's measurement (both default 0 — the candidate
    must strictly meet the serving model).  ``batch_size`` only matters
    for the accuracy metric's iterator.
    """

    def __init__(self, eval_set: DataSet, *, metric: str = "loss",
                 rel_margin: float = 0.0, abs_margin: float = 0.0,
                 batch_size: int = 64):
        if metric not in GATE_METRICS:
            raise ValueError(f"unknown gate metric {metric!r} "
                             f"(one of {GATE_METRICS})")
        if rel_margin < 0 or abs_margin < 0:
            raise ValueError("gate margins must be >= 0")
        self.eval_set = eval_set
        self.metric = metric
        self.rel_margin = float(rel_margin)
        self.abs_margin = float(abs_margin)
        self.batch_size = int(batch_size)

    # ------------------------------------------------------------ measure
    def measure(self, model) -> float:
        if self.metric == "loss":
            return float(model.score(self.eval_set))
        it = ListDataSetIterator(self.eval_set, self.batch_size)
        return float(model.evaluate(it).accuracy())

    def evaluate(self, candidate, baseline,
                 baseline_value: Optional[float] = None) -> GateResult:
        """Gate ``candidate`` against ``baseline`` (or a pre-measured
        ``baseline_value`` — e.g. the journaled measurement of the
        serving version, so a resumed EVAL compares against the same
        number)."""
        base = (self.measure(baseline) if baseline_value is None
                else float(baseline_value))
        cand = self.measure(candidate)
        if self.metric == "loss":
            threshold = base * (1.0 + self.rel_margin) + self.abs_margin
            passed = cand <= threshold
            cmp = "<="
        else:
            threshold = base * (1.0 - self.rel_margin) - self.abs_margin
            passed = cand >= threshold
            cmp = ">="
        return GateResult(
            passed, self.metric, cand, base, threshold,
            f"candidate {self.metric} {cand:.6g} {cmp} {threshold:.6g} "
            f"(baseline {base:.6g}, rel_margin={self.rel_margin}, "
            f"abs_margin={self.abs_margin}): "
            f"{'PASS' if passed else 'FAIL'}")
