"""Continuous trainer: a streaming route feeding mini-epoch ``fit()``.

The TRAIN stage of the pipeline.  A :class:`StreamBuffer` is the sink a
``streaming.Route`` delivers into (``route.to_callable(buffer.put)``);
:class:`ContinuousTrainer` drains it in *mini-epochs* — bounded batches of
fresh examples — and runs ordinary incremental ``fit()`` on the candidate
model with the full observability loop attached through
``observe.attach_observability``: the ``TraceListener`` exports
``training_*`` series into the pipeline's metrics registry and a
``TrainingWatchdog`` guards every mini-epoch (NaN loss, gradient
explosion, divergence, stalls) with the configured action policy.

Items on the buffer are either ``DataSet`` batches or ``(features,
labels)`` tuples; single examples and whole batches both work — the
trainer rebatches to its configured ``batch_size``.
"""

from __future__ import annotations

import threading
import time
from typing import Any, List, Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.observe.health import attach_observability


class StreamStuck(RuntimeError):
    """The stream delivered no new examples within the wait budget —
    distinguishable from a cleanly drained route (which reports its
    processed count via ``route.result``)."""


class StreamBuffer:
    """Bounded thread-safe example buffer between a route and the trainer.

    ``put`` blocks when full (backpressure into the route thread rather
    than unbounded memory growth); ``take`` blocks up to ``timeout_s``
    for at least one item.  ``close()`` unblocks everything — a closed,
    empty buffer yields no more items.
    """

    def __init__(self, capacity: int = 1024):
        self.capacity = int(capacity)
        self._items: List[Any] = []
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._not_full = threading.Condition(self._lock)
        self._closed = False
        self.total_in = 0

    def put(self, item: Any) -> None:
        with self._lock:
            while len(self._items) >= self.capacity and not self._closed:
                self._not_full.wait(0.1)
            if self._closed:
                raise RuntimeError("buffer is closed")
            self._items.append(item)
            self.total_in += 1
            self._not_empty.notify_all()

    def take(self, max_items: int, timeout_s: Optional[float] = None
             ) -> List[Any]:
        """Up to ``max_items`` buffered items; blocks up to ``timeout_s``
        for the FIRST item (never for a full batch), so a slow stream
        still makes progress in small mini-epochs."""
        with self._lock:
            # deadline loop: Condition.wait may wake spuriously, and a
            # premature empty return would misreport a healthy stream as
            # stuck (aborting the TRAIN stage)
            deadline = (None if timeout_s is None
                        else time.monotonic() + timeout_s)
            while not self._items and not self._closed:
                if deadline is None:
                    self._not_empty.wait()
                    continue
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._not_empty.wait(remaining)
            out = self._items[:max_items]
            del self._items[:len(out)]
            if out:
                self._not_full.notify_all()
            return out

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._not_empty.notify_all()
            self._not_full.notify_all()

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)


def _example_count(item: Any) -> int:
    x = (item.features if isinstance(item, DataSet) else item[0])
    x = np.asarray(x)
    return 1 if x.ndim == 1 else int(x.shape[0])


def _to_datasets(items: List[Any], batch_size: int) -> List[DataSet]:
    """Rebatch a mix of DataSets / (x, y) pairs into ``batch_size`` rows."""
    xs, ys = [], []
    for item in items:
        if isinstance(item, DataSet):
            x, y = np.asarray(item.features), np.asarray(item.labels)
        else:
            x, y = (np.asarray(item[0]), np.asarray(item[1]))
        if x.ndim == 1:  # a single example
            x, y = x[None], np.asarray(y)[None]
        xs.append(x)
        ys.append(y)
    if not xs:
        return []
    x = np.concatenate(xs, axis=0)
    y = np.concatenate(ys, axis=0)
    return [DataSet(x[i:i + batch_size], y[i:i + batch_size])
            for i in range(0, len(x), batch_size)]


class ContinuousTrainer:
    """Mini-epoch incremental trainer over a :class:`StreamBuffer`.

    ``watchdog`` follows the ``attach_observability`` contract (``True``
    for defaults, a kwargs dict, or a ready ``TrainingWatchdog``); with a
    ``"raise"`` policy a diverging candidate aborts the TRAIN stage with
    ``WatchdogAlarm``, which the pipeline runner turns into a rejected
    run.  ``metrics``/``tracer`` ride into the attached ``TraceListener``
    so ``training_*`` series land in the same registry the canary's alert
    rules read.
    """

    def __init__(self, model, buffer: StreamBuffer, *,
                 batch_size: int = 32, batches_per_mini_epoch: int = 4,
                 take_timeout_s: float = 5.0,
                 metrics=None, tracer=None,
                 model_name: str = "candidate", watchdog=None,
                 prefetch_depth: Optional[int] = None):
        self.model = model
        self.buffer = buffer
        self.batch_size = int(batch_size)
        self.batches_per_mini_epoch = int(batches_per_mini_epoch)
        self.take_timeout_s = float(take_timeout_s)
        # forwarded to fit(): mini-epoch batch lists are small, so the
        # default (None → fit decides) usually skips the async wrap
        self.prefetch_depth = prefetch_depth
        self.examples_seen = 0
        self.mini_epochs = 0
        self.listeners = attach_observability(
            model, tracer=tracer, metrics=metrics, model_name=model_name,
            trace=True, watchdog=watchdog)

    def train_mini_epoch(self) -> dict:
        """Drain one mini-epoch of fresh examples and ``fit()`` on them.

        Raises :class:`StreamStuck` when the buffer stays empty past the
        take timeout — the caller (pipeline runner) checks the route's
        ``result``/``error`` to tell "drained" from "stuck".
        """
        budget = self.batch_size * self.batches_per_mini_epoch
        items: list = []
        taken = 0
        while taken < budget:
            # the budget counts EXAMPLES (an item may be a whole batch);
            # only the first take waits — once data flows, drain greedily
            got = self.buffer.take(
                1, timeout_s=self.take_timeout_s if not items else 0.0)
            if not got:
                break
            items.extend(got)
            taken += _example_count(got[0])
        if not items:
            raise StreamStuck(
                f"no stream items within {self.take_timeout_s}s")
        batches = _to_datasets(items, self.batch_size)
        n = sum(int(np.asarray(b.features).shape[0]) for b in batches)
        self.model.fit(batches, epochs=1,  # fit() takes any DataSet iterable
                       prefetch_depth=self.prefetch_depth)
        self.examples_seen += n
        self.mini_epochs += 1
        return {"examples": n, "batches": len(batches),
                "score": float(self.model.score_),
                "mini_epoch": self.mini_epochs}
