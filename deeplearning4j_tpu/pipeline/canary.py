"""Canary controller: ramp candidate traffic, watch the signals, decide.

The CANARY stage of the pipeline.  The controller drives the registry's
two canary capabilities (``serving/registry.py``):

- **weighted routing** — each schedule step gives the candidate version a
  traffic fraction (deterministic smooth weighted round-robin inside
  ``predict``), held for ``hold_s`` on the injected ``TimeSource``;
- **shadow mode** — before any fraction is applied, a sample of live
  requests is duplicated to the candidate off the response path and the
  output divergence is counted (``shadow_divergence_total{model}``) and
  logged (bounded).

Signals that roll the canary back, checked every :meth:`tick`:

- an attached ``AlertManager`` rule firing (``abort_on_alerts`` names a
  subset; ``None`` watches every firing rule — the SLO burn-rate rules
  the serving tier already evaluates);
- shadow divergences exceeding ``max_divergences`` (``None`` disables);
- an explicit :meth:`report_alarm` (the trainer watchdog's alarms, an
  operator abort).

When every schedule step has held cleanly the decision is ``"promote"``.
The controller is clockless-loop friendly: drive ``tick()`` manually
under a ``ManualTimeSource`` for deterministic tests, or call
:meth:`run` to poll on the real clock.  It never touches model versions
itself — clearing the split/shadow is its only registry write on
decision; the PROMOTE/ROLLBACK registry action belongs to the pipeline
runner so it lands inside the journaled terminal stage.
"""

from __future__ import annotations

import dataclasses
import time as _time
from typing import Callable, List, Optional, Sequence

from deeplearning4j_tpu.parallel.time_source import (TimeSource,
                                                     get_time_source)


@dataclasses.dataclass
class CanaryStep:
    """One ramp step: give the candidate ``fraction`` of live traffic and
    hold it for ``hold_s`` seconds before the next step."""

    fraction: float
    hold_s: float

    def __post_init__(self):
        if not 0.0 < float(self.fraction) <= 1.0:
            raise ValueError(
                f"canary fraction must be in (0, 1], got {self.fraction}")
        if float(self.hold_s) < 0:
            raise ValueError(f"hold_s must be >= 0, got {self.hold_s}")


def parse_schedule(spec: Sequence) -> List[CanaryStep]:
    """``[{"fraction": f, "hold_s": s}, ...]`` (or CanaryStep instances)
    → validated, strictly-increasing ramp."""
    steps = [s if isinstance(s, CanaryStep)
             else CanaryStep(float(s["fraction"]), float(s["hold_s"]))
             for s in spec]
    if not steps:
        raise ValueError("canary schedule must have at least one step")
    for a, b in zip(steps, steps[1:]):
        if b.fraction <= a.fraction:
            raise ValueError(
                f"canary fractions must strictly increase "
                f"({a.fraction} -> {b.fraction})")
    return steps


class CanaryController:
    """Ramp ``candidate_version`` of ``name`` through ``schedule``.

    Lifecycle: :meth:`start` applies shadow mode + the first fraction;
    :meth:`tick` advances (returns ``None`` while undecided, else
    ``"promote"``/``"rollback"``); :attr:`decision`/:attr:`reason` carry
    the outcome.  ``on_event(kind, detail)`` observes ramp/decision
    events (the runner journals them as notes).
    """

    def __init__(self, registry, name: str, candidate_version: int, *,
                 schedule: Sequence, time_source: Optional[TimeSource] = None,
                 alerts=None, abort_on_alerts: Optional[Sequence[str]] = None,
                 shadow_sample: float = 0.0,
                 divergence_threshold: float = 1e-3,
                 max_divergences: Optional[int] = None,
                 on_event: Optional[Callable[[str, dict], None]] = None):
        self.registry = registry
        self.name = name
        self.candidate_version = int(candidate_version)
        self.schedule = parse_schedule(schedule)
        self.time_source = (time_source if time_source is not None
                            else get_time_source())
        self.alerts = alerts
        self.abort_on_alerts = (None if abort_on_alerts is None
                                else set(abort_on_alerts))
        self.shadow_sample = float(shadow_sample)
        self.divergence_threshold = float(divergence_threshold)
        self.max_divergences = max_divergences
        self.on_event = on_event
        self.step_index: Optional[int] = None
        self.step_started_ms: Optional[int] = None
        self.decision: Optional[str] = None
        self.reason: Optional[str] = None
        self.shadow_final: Optional[dict] = None  # snapshot at decision
        self._alarm: Optional[str] = None

    # ------------------------------------------------------------- helpers
    def _now_ms(self) -> int:
        return self.time_source.current_time_millis()

    def _event(self, kind: str, **detail) -> None:
        if self.on_event is not None:
            self.on_event(kind, detail)

    def _apply_step(self, index: int) -> None:
        step = self.schedule[index]
        self.registry.set_traffic_split(
            self.name, {self.candidate_version: step.fraction})
        self.step_index = index
        self.step_started_ms = self._now_ms()
        self._event("ramp", step=index, fraction=step.fraction,
                    hold_s=step.hold_s)

    # ----------------------------------------------------------- lifecycle
    def start(self) -> "CanaryController":
        """Arm shadow mode (if sampled) then apply the first fraction.
        The registry refuses a cold candidate (warm-gated split), so a
        canary can never put an uncompiled version in front of traffic."""
        if self.shadow_sample > 0:
            self.registry.set_shadow(
                self.name, self.candidate_version,
                sample=self.shadow_sample,
                divergence_threshold=self.divergence_threshold)
            self._event("shadow", sample=self.shadow_sample)
        self._apply_step(0)
        return self

    def report_alarm(self, reason: str) -> None:
        """Push an external abort signal (trainer watchdog alarm, operator
        stop); the next :meth:`tick` rolls back."""
        self._alarm = str(reason)

    def _bad_signal(self) -> Optional[str]:
        if self._alarm is not None:
            return f"alarm: {self._alarm}"
        if self.alerts is not None:
            firing = set(self.alerts.firing())
            watched = (firing if self.abort_on_alerts is None
                       else firing & self.abort_on_alerts)
            if watched:
                return f"alert(s) firing: {sorted(watched)}"
        if self.max_divergences is not None:
            state = self.registry.shadow_state(self.name)
            if state and state.get("divergences", 0) > self.max_divergences:
                return (f"shadow divergences {state['divergences']} exceed "
                        f"budget {self.max_divergences}")
        return None

    def _decide(self, decision: str, reason: str) -> str:
        # pull the candidate out of the traffic path before reporting;
        # the journaled PROMOTE/ROLLBACK happens in the runner afterwards
        if self.shadow_sample > 0:
            self.registry.drain_shadow(timeout_s=5.0)
            self.shadow_final = self.registry.shadow_state(self.name)
        self.registry.clear_traffic_split(self.name)
        if self.shadow_sample > 0:
            self.registry.clear_shadow(self.name)
        self.decision, self.reason = decision, reason
        self._event("decision", decision=decision, reason=reason)
        return decision

    def tick(self) -> Optional[str]:
        """Advance the state: check abort signals, ramp when the hold
        elapsed, decide at the end.  ``None`` while still canarying."""
        if self.decision is not None:
            return self.decision
        if self.step_index is None:
            raise RuntimeError("canary not started (call start() first)")
        bad = self._bad_signal()
        if bad is not None:
            return self._decide("rollback", bad)
        step = self.schedule[self.step_index]
        held_s = (self._now_ms() - self.step_started_ms) / 1e3
        if held_s < step.hold_s:
            return None
        if self.step_index + 1 < len(self.schedule):
            self._apply_step(self.step_index + 1)
            return None
        return self._decide(
            "promote",
            f"all {len(self.schedule)} ramp step(s) held cleanly "
            f"(final fraction {step.fraction})")

    def run(self, *, poll_s: float = 1.0,
            wait: Optional[Callable[[float], None]] = None) -> str:
        """Poll :meth:`tick` until decided. ``wait`` is the between-tick
        hook (default: real ``time.sleep``) — deterministic callers
        advance a ``ManualTimeSource`` and drive traffic there."""
        wait = _time.sleep if wait is None else wait
        if self.step_index is None:
            self.start()
        while True:
            decision = self.tick()
            if decision is not None:
                return decision
            wait(poll_s)
