"""Continuous-training pipeline (L7 of the stack, above serving/observe).

The self-retraining product loop the framework's pieces were built for:
a streaming route feeds mini-epoch incremental ``fit()`` (watchdog- and
trace-guarded), the candidate must pass a held-out eval gate against the
serving version, then canaries at ramped traffic fractions with shadow
diffing before an automatic promote — or an alert/watchdog-driven
rollback.  A fenced, journaled state machine (the elastic supervisor's
ledger pattern) makes the whole loop crash-safe: a killed pipeline
resumes at the stage it died in and can never double-promote.

- ``state``   — :class:`PipelineStateMachine` / :class:`PipelineJournal`
  (fencing, single-terminal-decision journal, fault-injection hook);
- ``trainer`` — :class:`ContinuousTrainer` + :class:`StreamBuffer`
  (stream → mini-epoch fit with ``attach_observability`` wired in);
- ``gate``    — :class:`EvalGate` (candidate vs serving within margins);
- ``canary``  — :class:`CanaryController` (ramp schedule on a
  ``TimeSource``, alert/shadow-divergence rollback signals);
- ``runner``  — :class:`ContinuousPipeline` + :class:`PipelineConfig`
  (the orchestration + the JSON config schema shared with the CLI and
  ``tools/validate_pipeline_config.py``).
"""

from deeplearning4j_tpu.pipeline.canary import (  # noqa: F401
    CanaryController,
    CanaryStep,
    parse_schedule,
)
from deeplearning4j_tpu.pipeline.gate import (  # noqa: F401
    GATE_METRICS,
    EvalGate,
    GateResult,
)
from deeplearning4j_tpu.pipeline.runner import (  # noqa: F401
    ContinuousPipeline,
    PipelineConfig,
)
from deeplearning4j_tpu.pipeline.state import (  # noqa: F401
    AlreadyDecided,
    IllegalTransition,
    PipelineJournal,
    PipelineState,
    PipelineStateMachine,
    STAGES,
    StalePipelineError,
    TERMINAL_STAGES,
)
from deeplearning4j_tpu.pipeline.trainer import (  # noqa: F401
    ContinuousTrainer,
    StreamBuffer,
    StreamStuck,
)
